"""``python -m dlrover_tpu.run`` — the elastic launcher CLI.

Parity: reference dlrover/trainer/torch/elastic_run.py (``dlrover-run``):
a torchrun-superset that (a) bootstraps a local master in standalone mode,
(b) merges master-pushed config, (c) gates on pre-check, then hands off to
the elastic agent. Here the launched workers are JAX processes.

Usage:
    python -m dlrover_tpu.run --standalone --nproc_per_node 1 train.py ...
    python -m dlrover_tpu.run --master host:port --nnodes 2:4 train.py ...
"""

import argparse
import atexit
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Optional, Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.monitor import ResourceMonitor
from dlrover_tpu.agent.training_monitor import (
    METRICS_FILE_ENV,
    TrainingMonitor,
)
from dlrover_tpu.agent.training import ElasticAgent, RunResult, WorkerSpec
from dlrover_tpu.common.constants import (
    NodeEnv,
    PreCheckStatus,
)
from dlrover_tpu.common.env_utils import get_env_bool, get_env_int
from dlrover_tpu.common.log import logger


def parse_nnodes(value: str) -> Tuple[int, int]:
    if ":" in value:
        lo, hi = value.split(":", 1)
        return int(lo), int(hi)
    n = int(value)
    return n, n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlrover-tpu-run", description="elastic JAX process launcher"
    )
    p.add_argument("--standalone", action="store_true", default=False)
    p.add_argument("--master", type=str, default="", help="master addr host:port")
    p.add_argument("--nnodes", type=str, default="1", help="N or MIN:MAX")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=-1)
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--node_unit", type=int, default=1)
    p.add_argument("--rdzv_join_timeout", type=float, default=600.0)
    p.add_argument("--monitor_interval", type=float, default=1.0)
    p.add_argument(
        "--network-check",
        action="store_true",
        default=False,
        help="run node/ICI health probes before training",
    )
    p.add_argument(
        "--comm-perf-test",
        action="store_true",
        default=False,
        help="include bandwidth benchmarks in the network check",
    )
    p.add_argument("--log_dir", type=str, default="")
    p.add_argument("--pre_check_timeout", type=float, default=600.0)
    p.add_argument(
        "--ckpt_replica_group",
        type=int,
        default=1,
        help="nodes per in-memory checkpoint replica group (1 = off)",
    )
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def _launch_local_master(node_num: int) -> Tuple[subprocess.Popen, str]:
    """Standalone bootstrap (reference elastic_run.py:326
    _launch_dlrover_local_master)."""
    port_file = os.path.join(
        tempfile.mkdtemp(prefix="dlrover_tpu_"), "master_port"
    )
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--platform",
            "local",
            "--node_num",
            str(node_num),
            "--port_file",
            port_file,
        ],
        start_new_session=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            port = open(port_file).read().strip()
            if port:
                return proc, f"127.0.0.1:{port}"
        if proc.poll() is not None:
            raise RuntimeError("local master exited during startup")
        time.sleep(0.2)
    proc.kill()
    raise RuntimeError("local master did not publish its port in 60s")


def wait_pre_check(client: MasterClient, timeout: float):
    """Gate on master pre-check (reference elastic_run.py:295)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            status = client.get_pre_check_result()
        except Exception:
            time.sleep(1)
            continue
        if status in (PreCheckStatus.PASS, PreCheckStatus.DISABLED):
            return
        if status == PreCheckStatus.FAIL:
            raise SystemExit("master pre-check failed; aborting launch")
        time.sleep(2)
    raise SystemExit("timed out waiting for master pre-check")


def _merge_master_config(client: MasterClient, args):
    """Master-pushed config overrides CLI defaults (reference
    elastic_run.py:438 _merge_elastic_config_from_master)."""
    try:
        config = client.get_elastic_run_config()
    except Exception:
        return
    if "network_check" in config:
        args.network_check = config["network_check"].lower() == "true"
    if "max_restarts" in config:
        args.max_restarts = int(config["max_restarts"])
    if "monitor_interval" in config:
        args.monitor_interval = float(config["monitor_interval"])


def run(args) -> int:
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    master_proc: Optional[subprocess.Popen] = None

    node_rank = args.node_rank
    if node_rank < 0:
        node_rank = get_env_int(NodeEnv.NODE_RANK, 0)

    if args.standalone and not args.master:
        master_proc, master_addr = _launch_local_master(max_nodes)

        def _cleanup():
            if master_proc.poll() is None:
                master_proc.terminate()

        atexit.register(_cleanup)
    else:
        master_addr = args.master or os.getenv(NodeEnv.MASTER_ADDR, "")
        if not master_addr:
            raise SystemExit(
                "--master (or DLROVER_TPU_MASTER_ADDR) required unless "
                "--standalone"
            )

    os.environ[NodeEnv.MASTER_ADDR] = master_addr
    os.environ[NodeEnv.NODE_RANK] = str(node_rank)
    client = MasterClient(master_addr, node_id=node_rank)
    if not client.wait_master_ready(60):
        raise SystemExit(f"master at {master_addr} not reachable")

    _merge_master_config(client, args)
    wait_pre_check(client, args.pre_check_timeout)

    if args.network_check:
        from dlrover_tpu.agent.node_check import run_network_check

        ok = run_network_check(
            client,
            node_rank=node_rank,
            nproc_per_node=args.nproc_per_node,
            comm_perf=args.comm_perf_test,
            node_unit=args.node_unit,
        )
        if not ok:
            logger.error("node failed network check; exiting for relaunch")
            return 3

    monitor = ResourceMonitor(client)
    monitor.start()

    # Metrics-file step reporting (reference TorchTrainingMonitor): a
    # training loop that never talks RPC still feeds goodput accounting
    # by appending JSON lines to DLROVER_TPU_METRICS_FILE.
    training_monitor = None
    metrics_path = os.getenv(METRICS_FILE_ENV, "")
    if metrics_path:
        training_monitor = TrainingMonitor(client, metrics_path)
        training_monitor.start()

    from dlrover_tpu.agent.paral_config_tuner import ParalConfigTuner

    paral_tuner = ParalConfigTuner(client)
    paral_tuner.start()

    # User-pluggable failover extension (reference
    # trainer/torch/elastic_run.py:550 _setup_dynamic_failover_extension):
    # DLROVER_TPU_FAILOVER_EXT="pkg.module:factory" -> factory(client,
    # node_rank) returning a DiagnosisAgent-compatible object.
    diagnosis_agent = None
    ext_spec = os.getenv("DLROVER_TPU_FAILOVER_EXT", "")
    if ext_spec:
        try:
            module_name, factory_name = ext_spec.split(":", 1)
            import importlib

            module = importlib.import_module(module_name)
            diagnosis_agent = getattr(module, factory_name)(
                client, node_rank
            )
            logger.info("loaded failover extension %s", ext_spec)
        except Exception:
            logger.exception(
                "failover extension %r failed to load; using default",
                ext_spec,
            )

    timer_collectors = []
    if get_env_bool("DLROVER_TPU_TIMER"):
        from dlrover_tpu.diagnosis.collectors import TpuTimerMetricCollector
        from dlrover_tpu.tpu_timer.bridge import port_file_path

        for local_rank in range(args.nproc_per_node):
            c = TpuTimerMetricCollector(
                master_client=client,
                node_id=node_rank,
                port=18889 + local_rank,
                port_file=port_file_path(local_rank),
            )
            c.start()
            timer_collectors.append(c)

    spec = WorkerSpec(
        entrypoint=args.training_script,
        args=list(args.training_script_args),
        nproc_per_node=args.nproc_per_node,
        max_restarts=args.max_restarts,
        node_rank=node_rank,
        node_unit=args.node_unit,
        join_timeout=args.rdzv_join_timeout,
        monitor_interval=args.monitor_interval,
        redirect_output=args.log_dir or None,
    )
    from dlrover_tpu.flash_ckpt.saver import AsyncCheckpointSaver

    replica_manager = None
    if args.ckpt_replica_group > 1:
        from dlrover_tpu.flash_ckpt.replica import (
            CkptReplicaManager,
            ReplicaTokenUnavailable,
        )

        from dlrover_tpu.common.env_utils import get_hostname_ip

        try:
            replica_manager = CkptReplicaManager(
                node_rank=node_rank,
                master_client=client,
                group_size=args.ckpt_replica_group,
            )
        except ReplicaTokenUnavailable:
            logger.error(
                "no replica auth token available; running WITHOUT "
                "cross-host checkpoint replicas"
            )
    if replica_manager is not None:
        # Publish a routable address, not loopback: peers resolve it from
        # the master KV store.
        replica_manager.start(advertise_host=get_hostname_ip()[1])
        try:
            # A fresh host after relaunch pulls its shm images back from a
            # peer so workers can do a memory-first restore. Ask every
            # possible rank: the push-time grouping used the rendezvous
            # world, which this fresh node cannot reconstruct.
            restored = replica_manager.restore_missing_segments(
                args.nproc_per_node,
                candidate_ranks=list(range(max_nodes)),
            )
            if restored:
                logger.info(
                    "restored %d shm checkpoint segments from peers",
                    restored,
                )
        except Exception:
            logger.warning(
                "replica pull failed; storage restore will be used",
                exc_info=True,
            )
    saver = AsyncCheckpointSaver.start_async_saving_ckpt(
        client=client, replica_manager=replica_manager
    )
    agent = ElasticAgent(
        spec, client, ckpt_saver=saver, diagnosis_agent=diagnosis_agent
    )

    def _signal_handler(signum, frame):
        logger.info("launcher received signal %d; stopping workers", signum)
        agent.stop()
        if training_monitor is not None:
            # Preemption is exactly when the final steps matter for
            # goodput accounting: flush them before dying.
            try:
                training_monitor.poll_once()
            except Exception:
                pass
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, _signal_handler)

    result = agent.run()
    monitor.stop()
    if training_monitor is not None:
        training_monitor.poll_once()  # flush the final steps
        training_monitor.stop()
    paral_tuner.stop()
    for c in timer_collectors:
        c.stop()
    if result == RunResult.SUCCEEDED:
        code = 0
    elif result == RunResult.RELAUNCH:
        code = 3  # cluster layer replaces this node
    else:
        code = 1
    if master_proc is not None:
        try:
            master_proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            master_proc.terminate()
    return code


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
