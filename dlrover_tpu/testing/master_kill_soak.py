"""master_kill chaos episode: SIGKILL the control plane, not a worker.

Episode kind 7 of the chaos soak (docs/DESIGN.md §37). The master runs
as its own subprocess (:mod:`dlrover_tpu.testing.soak_master`) with a
durable journal; a seeded fault rule crashes it at the
``master.journal.write`` point with ``kind=dispatch`` — AFTER a shard
lease became durable, BEFORE the reply reached the worker, the
nastiest window for exactly-once accounting. The harness restarts the
master (generation 1, same journal, same port, no faults) and the
training worker — which was given a ``DLROVER_TPU_MASTER_OUTAGE_S``
ride-through window and is NEVER restarted — must finish the dataset.

Asserted invariants:

1. **Exactly-once across the master crash** — the worker's
   order-independent integer state equals the full-dataset expectation
   (the journaled-but-undelivered lease is timeout-requeued exactly
   once, delivered done-reports are never re-dispatched).
2. **Zero worker restarts** — one generation, zero deaths: the outage
   mode + epoch fencing rode the crash out entirely client-side.
3. **Epoch fencing** — generation 1 answers with master_epoch ==
   generation 0's + 1 (the restart is visible, monotone, and fenced).
4. **Bounded recovery** — first post-kill worker step lands within
   ``recovery_bound_s``; the master's clean SIGTERM shutdown leaves a
   ``clean_shutdown`` journal (graceful drain flushed it).
5. **Deterministic trace** — the master's fault trace contains exactly
   the planned crash at the planned hit count (same seed, same trace).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from dlrover_tpu.fault import FaultRule, FaultSchedule
from dlrover_tpu.fault.registry import SCHEDULE_ENV, TRACE_ENV

MASTER_READY_TIMEOUT_S = 30.0
RECOVERY_BOUND_S = 60.0
WORKER_OUTAGE_S = 45.0


def _repo_root() -> str:
    import dlrover_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        dlrover_tpu.__file__
    )))


def build_master_schedule(ep_seed: int, nth: int) -> FaultSchedule:
    return FaultSchedule([
        FaultRule(
            "master.journal.write", action="crash", nth=nth,
            match={"kind": "dispatch"}, rule_id="master-sigkill",
        ),
    ], seed=ep_seed, label="master-gen0")


def _spawn_master(ep_dir: str, journal: str, ready_file: str, port: int,
                  generation: int, schedule_path: str,
                  task_timeout_s: float) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        TRACE_ENV: os.path.join(ep_dir, f"trace_master_gen{generation}.jsonl"),
        "PYTHONPATH": _repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
    })
    if schedule_path:
        env[SCHEDULE_ENV] = schedule_path
    else:
        env.pop(SCHEDULE_ENV, None)
    args = [
        sys.executable, "-m", "dlrover_tpu.testing.soak_master",
        "--port", str(port),
        "--journal", journal,
        "--ready-file", ready_file,
        "--task-timeout", str(task_timeout_s),
    ]
    with open(
        os.path.join(ep_dir, f"master_gen{generation}.log"), "w"
    ) as log:
        return subprocess.Popen(
            args, env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=_repo_root(),
        )


def _wait_ready(ready_file: str, proc: subprocess.Popen,
                timeout: float = MASTER_READY_TIMEOUT_S) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(ready_file):
            try:
                with open(ready_file) as f:
                    return json.load(f)
            except (OSError, ValueError):
                pass  # mid-replace; retry
        if proc.poll() is not None:
            raise RuntimeError(
                f"soak master exited rc={proc.returncode} before ready"
            )
        time.sleep(0.05)
    raise RuntimeError("soak master never became ready")


def _spawn_worker(cfg, ep_dir: str, master_port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DLROVER_TPU_JOB_NAME": os.path.basename(ep_dir),
        "DLROVER_TPU_FLIGHT_DIR": os.path.join(ep_dir, "flight"),
        TRACE_ENV: os.path.join(ep_dir, "trace_worker.jsonl"),
        "PYTHONPATH": _repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
        # The whole point: the worker rides the master crash out in
        # outage mode instead of dying on exhausted retries.
        "DLROVER_TPU_MASTER_OUTAGE_S": str(WORKER_OUTAGE_S),
    })
    env.pop(SCHEDULE_ENV, None)  # no worker-side faults this episode
    args = [
        sys.executable, "-m", "dlrover_tpu.testing.soak_worker",
        "--master-addr", f"localhost:{master_port}",
        "--node-id", "0",
        "--dataset-size", str(cfg.dataset_size),
        "--shard-size", str(cfg.shard_size),
        "--ckpt-dir", os.path.join(ep_dir, "ckpt"),
        "--ckpt-every", str(cfg.ckpt_every),
        "--events", os.path.join(ep_dir, "events.jsonl"),
        "--progress", os.path.join(ep_dir, "progress"),
        "--generation", "0",
        "--step-ms", str(cfg.step_ms),
    ]
    with open(os.path.join(ep_dir, "worker_gen0.log"), "w") as log:
        return subprocess.Popen(
            args, env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=_repo_root(),
        )


def _dump_artifacts(ep_dir: str, artifact_dir: str, seed: int,
                    episode: int, reason: str) -> str:
    os.makedirs(artifact_dir, exist_ok=True)
    dest = os.path.join(artifact_dir, f"soak_seed{seed}_ep{episode}")
    shutil.rmtree(dest, ignore_errors=True)
    shutil.copytree(ep_dir, dest, dirs_exist_ok=True)
    with open(os.path.join(dest, "failure.json"), "w") as f:
        json.dump({
            "seed": seed, "episode": episode, "kind": "master_kill",
            "reason": reason,
        }, f, indent=2)
    return dest


def run_master_kill_episode(seed: int, episode: int, plan, cfg,
                            work_dir: str, artifact_dir: str) -> Dict:
    """Run the master_kill episode; returns a soak-shaped report dict.
    Raises SoakInvariantError (after dumping artifacts) on failure."""
    from dlrover_tpu.master.journal import load_journal
    from dlrover_tpu.testing.soak import (
        SoakInvariantError,
        _check_ledger_invariants,
        _read_events,
        _read_trace,
    )

    ep_seed = seed * 10007 + episode
    ep_dir = os.path.join(work_dir, f"soak-s{seed}-e{episode}")
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(os.path.join(ep_dir, "flight"), exist_ok=True)
    os.makedirs(os.path.join(ep_dir, "ckpt"), exist_ok=True)
    journal = os.path.join(ep_dir, "master.journal")
    nth = plan.master_kill_nth

    master_schedule = build_master_schedule(ep_seed, nth)
    schedule_path = os.path.join(ep_dir, "schedule_master_gen0.json")
    with open(schedule_path, "w") as f:
        f.write(master_schedule.to_json())

    report: Dict = {
        "episode": episode, "seed": seed, "kind": "master_kill",
        "generations": 1,
    }
    t_start = time.time()
    deadline = t_start + cfg.watchdog_s
    failure: Optional[str] = None
    worker: Optional[subprocess.Popen] = None
    master: Optional[subprocess.Popen] = None
    epochs: List[int] = []
    t_kill = 0.0
    master_restart_s = 0.0
    try:
        ready0 = os.path.join(ep_dir, "master_ready_gen0.json")
        master = _spawn_master(
            ep_dir, journal, ready0, 0, 0, schedule_path,
            cfg.task_timeout_s,
        )
        info0 = _wait_ready(ready0, master)
        epochs.append(info0["epoch"])
        port = info0["port"]

        worker = _spawn_worker(cfg, ep_dir, port)

        # Phase 1: the seeded crash SIGKILLs the master mid-episode.
        while master.poll() is None:
            if time.time() > deadline:
                failure = "watchdog: master crash never fired"
                break
            if worker.poll() is not None:
                failure = (
                    f"worker exited rc={worker.returncode} before the "
                    f"master crash fired (nth={nth} too high?)"
                )
                break
            time.sleep(0.02)
        if not failure:
            t_kill = time.time()
            if master.returncode != -signal.SIGKILL:
                failure = (
                    f"master gen0 exited rc={master.returncode}, "
                    f"expected SIGKILL from the fault schedule"
                )
        # Phase 2: restart from the journal — same port, no faults.
        if not failure:
            ready1 = os.path.join(ep_dir, "master_ready_gen1.json")
            master = _spawn_master(
                ep_dir, journal, ready1, port, 1, "",
                cfg.task_timeout_s,
            )
            info1 = _wait_ready(ready1, master)
            epochs.append(info1["epoch"])
            master_restart_s = time.time() - t_kill
        # Phase 3: the never-restarted worker must finish the dataset.
        if not failure:
            while worker.poll() is None:
                if time.time() > deadline:
                    failure = "watchdog: worker never finished after restart"
                    break
                if master.poll() is not None:
                    failure = (
                        f"master gen1 died rc={master.returncode}"
                    )
                    break
                time.sleep(0.05)
        if not failure and worker.returncode != 0:
            failure = f"worker exited rc={worker.returncode} (expected 0)"
        # Phase 4: graceful SIGTERM shutdown must drain + close the
        # journal (clean_shutdown asserted below).
        if not failure and master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=15)
            except subprocess.TimeoutExpired:
                master.kill()
                failure = "master gen1 did not exit on SIGTERM"
    finally:
        for proc in (worker, master):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)
        try:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(
                name=f"dlrover_tpu_ckpt_{os.path.basename(ep_dir)}_n0_0"
            )
            seg.close()
            seg.unlink()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass

    wall = time.time() - t_start
    events = _read_events(os.path.join(ep_dir, "events.jsonl"))
    master_trace = _read_events(
        os.path.join(ep_dir, "trace_master_gen0.jsonl")
    )
    try:
        if failure:
            raise SoakInvariantError(failure)
        # (1) exactly-once + checkpoint integrity, from the worker
        # ledger — identical invariant to the worker-kill kinds.
        _check_ledger_invariants(events, plan, cfg)
        # (2) zero worker restarts: one generation, one worker_start.
        starts = [e for e in events if e.get("kind") == "worker_start"]
        if len(starts) != 1:
            raise SoakInvariantError(
                f"worker restarted: {len(starts)} worker_start events "
                f"(outage ride-through failed)"
            )
        # (3) epoch fencing: restart bumped the incarnation by one.
        if epochs != [1, 2]:
            raise SoakInvariantError(
                f"master epochs {epochs}, expected [1, 2] "
                f"(journal epoch not monotone across restart)"
            )
        # (4) bounded recovery.
        post = [
            e for e in events
            if e.get("kind") == "step" and e.get("t", 0.0) > t_kill
        ]
        if not post:
            raise SoakInvariantError(
                "no worker step after the master kill"
            )
        recovery = post[0]["t"] - t_kill
        if recovery > RECOVERY_BOUND_S:
            raise SoakInvariantError(
                f"recovery {recovery:.1f}s exceeds bound "
                f"{RECOVERY_BOUND_S}s"
            )
        final = load_journal(journal)
        if not final.clean_shutdown:
            raise SoakInvariantError(
                "graceful SIGTERM shutdown did not close the journal"
            )
        # (5) deterministic fault trace: exactly the planned crash,
        # at exactly the planned hit count.
        crashes = [
            t for t in master_trace
            if t.get("rule_id") == "master-sigkill"
            and t.get("action") == "crash"
        ]
        if len(crashes) != 1 or crashes[0].get("hit") != nth:
            raise SoakInvariantError(
                f"master fault trace diverged from plan: {crashes} "
                f"(expected one crash at hit {nth})"
            )
    except SoakInvariantError as e:
        dest = _dump_artifacts(ep_dir, artifact_dir, seed, episode, str(e))
        print(
            f"SOAK EPISODE FAILED: {e}\n"
            f"  artifacts: {dest}\n"
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise

    step_events = [e for e in events if e.get("kind") == "step"]
    last_dur: Dict[int, float] = {}
    for e in step_events:
        last_dur[e["step"]] = e.get("dur", 0.0)
    productive_s = sum(last_dur.values())
    post = [e for e in step_events if e.get("t", 0.0) > t_kill]
    recovery = post[0]["t"] - t_kill if post else 0.0
    trace = _read_trace(
        os.path.join(ep_dir, "trace_master_gen0.jsonl"), "master"
    ) + _read_trace(os.path.join(ep_dir, "trace_worker.jsonl"), "worker")
    trace.sort(key=lambda t: (t["origin"], str(t["rule_id"])))
    report.update({
        "wall_s": round(wall, 3),
        "productive_step_s": round(productive_s, 3),
        "goodput_frac": round(min(productive_s / max(wall, 1e-9), 1.0), 4),
        "faults": trace,
        "deaths": 0,              # zero WORKER deaths — the invariant
        "master_kills": 1,
        "master_restart_s": round(master_restart_s, 3),
        "recovery_s": [round(recovery, 3)],
        "master_epochs": epochs,
        "steps_unique": len(last_dur),
        "steps_executed": len(step_events),
    })
    if not cfg.keep_artifacts_on_success:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return report
