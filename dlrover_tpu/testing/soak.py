"""Seeded chaos-soak harness: whole-stack fault episodes with invariants.

One episode = an in-process master (task manager + servicer + HTTP
transport), a crash-restartable training worker subprocess
(:mod:`dlrover_tpu.testing.soak_worker`), and a continuous-batching
serving engine — all driven through a seeded, deterministic
:class:`~dlrover_tpu.fault.FaultSchedule`. After every episode the four
system invariants are asserted (docs/DESIGN.md §26):

1. **Exactly-once shard accounting** — the worker's order-independent
   integer state equals the exactly-once expectation over the whole
   dataset, and the master's shard ledger is complete.
2. **Checkpoint integrity** — every restore's content CRC matches the
   corresponding save's; torn/truncated raw shards are rejected and the
   previous committed step restored; saves advance monotonically.
3. **Serving completeness** — every admitted request reaches DONE (or
   an explicit failure); an engine step that raises re-queues its
   in-flight requests instead of losing them.
4. **No deadlock** — a watchdog bounds the episode; on breach the
   worker is SIGTERMed (flight ring dumps) and the episode fails.

Fault randomness is in schedule GENERATION (parameters drawn from
``random.Random(seed, episode)``); triggers are deterministic hit
counters, so one seed reproduces one fault trace exactly.

On failure the episode's evidence — fault schedules, merged trace,
worker ledger, flight-recorder dumps — is copied to an artifact dir and
a one-line repro command is printed.
"""

import glob
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import FaultRule, FaultSchedule, arm, disarm
from dlrover_tpu.fault.registry import SCHEDULE_ENV, TRACE_ENV
from dlrover_tpu.testing import soak_worker as sw

EPISODE_KINDS = (
    "crash_drop",
    "torn_ckpt",
    "serving_report",
    # Episode 3 of every seed: a live N→M rescale is SIGKILLed between
    # the plan ack and the first post-rescale step (delegated to
    # dlrover_tpu/testing/rescale_soak.py). Appended last so episodes
    # 0-2 keep their historical (seed, episode) -> plan identity.
    "kill_during_rescale",
    # Episode 4: a serving-fleet replica is SIGKILLed mid-decode; the
    # router must re-route its in-flight ledger (at-most-once), mark it
    # broken, restart it, and re-admit it through half-open probes
    # (delegated to dlrover_tpu/testing/fleet_soak.py). Appended so
    # episodes 0-3 keep their (seed, episode) -> plan identity.
    "replica_kill_reroute",
    # Episode 5: the §30 closed-loop autoscaler under a persistent
    # per-rank delay at the step fault point plus worker deaths and a
    # serving-traffic spike — the autoscaled run must flag/evict/
    # replace the straggler within bounded decision windows and
    # strictly beat the static run's goodput fraction (delegated to
    # dlrover_tpu/testing/autoscale_soak.py). Appended so episodes 0-4
    # keep their (seed, episode) -> plan identity.
    "straggler_evict",
    # Episode 6: a prefill+decode split fleet (§36) has its DESTINATION
    # replica SIGKILLed between the source's KV export and the import
    # ack — the payload exists on the wire but nowhere durable. The
    # never-released source must complete the request exactly once,
    # block conservation must hold on both ends across the kill, and a
    # migration must succeed again after the breaker-gated restart
    # (delegated to dlrover_tpu/testing/fleet_soak.py). Appended so
    # episodes 0-5 keep their (seed, episode) -> plan identity.
    "kill_during_migration",
    # Episode 7: the MASTER is SIGKILLed between a journaled shard
    # dispatch and its reply (the master.journal.write fault point,
    # kind=dispatch), restarted from its durable journal, and the
    # never-restarted worker must ride the outage out and finish with
    # exactly-once accounting (delegated to
    # dlrover_tpu/testing/master_kill_soak.py). Appended so episodes
    # 0-6 keep their (seed, episode) -> plan identity.
    "master_kill",
)


class SoakInvariantError(AssertionError):
    pass


@dataclass
class SoakConfig:
    dataset_size: int = 512
    shard_size: int = 16
    ckpt_every: int = 2
    step_ms: float = 0.0           # simulated compute per worker step
    task_timeout_s: float = 2.0
    watchdog_s: float = 180.0
    max_generations: int = 5
    serve: bool = True
    serving_requests: int = 4
    serving_new_tokens: int = 4
    keep_artifacts_on_success: bool = False


@dataclass
class EpisodePlan:
    kind: str
    crash_step: int = 0            # 0 = no crash planned
    torn_persist_nth: int = 0      # 0 = no torn write planned
    fallback_step: int = 0         # expected restore step after torn
    worker_schedules: List[FaultSchedule] = field(default_factory=list)
    runner_schedule: Optional[FaultSchedule] = None
    # kill_during_rescale only: per-RANK schedules for the multi-worker
    # rescale episode (worker_schedules stays per-generation for the
    # single-worker kinds).
    rank_schedules: Dict[int, FaultSchedule] = field(default_factory=dict)
    # master_kill only: SIGKILL the master on the Nth journaled
    # dispatch record (the master.journal.write fault point).
    master_kill_nth: int = 0


def build_episode_plan(
    seed: int, episode: int, cfg: Optional[SoakConfig] = None
) -> EpisodePlan:
    """Deterministic plan for (seed, episode): which faults, where.

    The three base kinds rotate so ``--episodes 3`` covers every
    required fault class (worker SIGKILL, dropped get_task reply, torn
    shard write, serving step error); the rng fills in parameters.
    Torn-write positions are derived from ``cfg.ckpt_every`` (the
    worker persists at step 0 and then every ``ckpt_every`` steps)."""
    cfg = cfg or SoakConfig()
    every = max(cfg.ckpt_every, 1)
    total_steps = cfg.dataset_size // max(cfg.shard_size, 1)
    if total_steps <= 2 * every + 1:
        raise ValueError(
            f"dataset too small for a chaos episode: {total_steps} steps "
            f"cannot fit a crash after two checkpoint intervals of "
            f"{every} steps"
        )

    def pick_crash_step() -> int:
        # After at least two persisted intervals (so a torn newest step
        # still has a real fallback), but strictly inside the episode —
        # a crash planned past the last step would never fire.
        return min(
            2 * every + 1 + every * rng.randint(0, 2), total_steps - 1
        )

    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed)
    kind = EPISODE_KINDS[episode % len(EPISODE_KINDS)]
    plan = EpisodePlan(kind=kind)
    runner_rules: List[FaultRule] = []

    if kind == "crash_drop":
        plan.crash_step = pick_crash_step()
        plan.worker_schedules = [
            FaultSchedule([
                FaultRule("agent.worker.crash", action="crash",
                          nth=plan.crash_step, rule_id="worker-sigkill"),
            ], seed=ep_seed, label="gen0"),
            FaultSchedule([], seed=ep_seed, label="gen1"),
        ]
        runner_rules.append(FaultRule(
            "rpc.get.drop_reply", action="raise",
            nth=rng.randint(2, 4),
            match={"request": "MultiTaskRequest"},
            rule_id="drop-get-task-reply",
        ))
    elif kind == "torn_ckpt":
        # Crash mid-interval; the persist immediately before the crash
        # is torn, so the *newest committed* step is unrestorable from
        # disk and the shm image is declared lost on restart — the
        # restore must reject the torn step and fall back one interval.
        # Persists land at steps 0, every, 2*every, ... (the j-th, 1-
        # based, at step (j-1)*every); crash_step > 2*every keeps the
        # fallback step a real (non-initial, non-negative) checkpoint.
        plan.crash_step = pick_crash_step()
        last_persist_step = ((plan.crash_step - 1) // every) * every
        plan.torn_persist_nth = last_persist_step // every + 1
        plan.fallback_step = last_persist_step - every
        plan.worker_schedules = [
            FaultSchedule([
                # At least one full page: the raw writer pads the file
                # tail to page alignment, so a sub-page tear may only
                # eat padding and legitimately still restore.
                FaultRule("ckpt.persist.torn_write", action="truncate",
                          nth=plan.torn_persist_nth,
                          truncate_bytes=4096 + rng.randint(0, 2048),
                          rule_id="torn-shard-write"),
                FaultRule("agent.worker.crash", action="crash",
                          nth=plan.crash_step, rule_id="worker-sigkill"),
            ], seed=ep_seed, label="gen0"),
            FaultSchedule([
                FaultRule("ckpt.restore.memory", action="raise",
                          nth=1, rule_id="shm-image-lost"),
            ], seed=ep_seed, label="gen1"),
        ]
    elif kind == "straggler_evict":
        # The sim-job fault schedule (persistent per-node delay at the
        # step fault point + seeded worker deaths) is derived in
        # autoscale_soak.build_autoscale_plan from the same ep_seed;
        # the runner itself injects nothing extra.
        pass
    elif kind == "replica_kill_reroute":
        # The per-replica SIGKILL schedule is derived in
        # fleet_soak.build_fleet_schedules (same ep_seed); the runner
        # additionally drops one router dispatch on the wire so the
        # bounded-retry path fires in the same episode.
        runner_rules.append(FaultRule(
            "fleet.router.dispatch", action="raise",
            nth=rng.randint(2, 6),
            rule_id="drop-router-dispatch",
        ))
    elif kind == "kill_during_migration":
        # The destination-replica SIGKILL schedule (crash at the
        # fleet.replica.import fault point, between export and
        # import-ack) is derived in
        # fleet_soak.build_migration_schedules from the same ep_seed;
        # the runner itself injects nothing extra — the episode's
        # whole point is that ONE kill in that window already
        # exercises timeout-prune, source fallback and the
        # migration-probed breaker walk.
        pass
    elif kind == "master_kill":
        # The master dies on its Nth journaled dispatch — deep enough
        # in that the worker holds live leases and at least one
        # checkpoint interval has persisted, low enough that the 32ish
        # dispatches of the default dataset still reach it even before
        # any timeout-requeue redispatches.
        plan.master_kill_nth = rng.randint(
            2 * every + 2, max(2 * every + 2, (2 * total_steps) // 3)
        )
    elif kind == "kill_during_rescale":
        # Rank 1 dies mid-step (cuts the scale-down plan); rank 0 is
        # SIGKILLed in the restore-to-first-step window of THAT plan
        # (resume hit 1 is the bootstrap plan, hit 2 the scale-down),
        # and one plan broadcast is dropped on the wire for good
        # measure — the pull protocol must redeliver it.
        plan.crash_step = pick_crash_step()
        plan.rank_schedules = {
            1: FaultSchedule([
                FaultRule("agent.worker.crash", action="crash",
                          nth=plan.crash_step, rule_id="worker-sigkill"),
            ], seed=ep_seed, label="rank1"),
            0: FaultSchedule([
                FaultRule("rescale.resume.first_step", action="crash",
                          nth=2, rule_id="kill-mid-rescale"),
            ], seed=ep_seed, label="rank0"),
        }
        runner_rules.append(FaultRule(
            "rescale.plan.broadcast", action="raise",
            nth=rng.randint(1, 3),
            rule_id="drop-plan-broadcast",
        ))
    else:  # serving_report
        plan.worker_schedules = [
            FaultSchedule([
                FaultRule("data.prefetch.fetch", action="raise",
                          nth=rng.randint(1, 2),
                          rule_id="prefetch-fetch-fails"),
            ], seed=ep_seed, label="gen0"),
        ]
        runner_rules.append(FaultRule(
            "rpc.report.drop_reply", action="raise",
            nth=rng.randint(1, 3),
            match={"request": "TaskDoneBatchReport"},
            rule_id="drop-done-report-reply",
        ))
        runner_rules.append(FaultRule(
            "serving.step.error", action="raise",
            nth=rng.randint(2, 5),
            rule_id="serving-step-raises",
        ))

    plan.runner_schedule = FaultSchedule(
        runner_rules, seed=ep_seed, label=f"runner-ep{episode}"
    )
    return plan


# ---------------------------------------------------------------------------
# Episode execution
# ---------------------------------------------------------------------------


def _repo_root() -> str:
    import dlrover_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        dlrover_tpu.__file__
    )))


def _spawn_worker(plan, cfg, ep_dir, master_port, generation,
                  schedule_path) -> subprocess.Popen:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DLROVER_TPU_JOB_NAME": os.path.basename(ep_dir),
        "DLROVER_TPU_FLIGHT_DIR": os.path.join(ep_dir, "flight"),
        TRACE_ENV: os.path.join(ep_dir, "trace_worker.jsonl"),
        "PYTHONPATH": _repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
    })
    if schedule_path:
        env[SCHEDULE_ENV] = schedule_path
    else:
        env.pop(SCHEDULE_ENV, None)
    args = [
        sys.executable, "-m", "dlrover_tpu.testing.soak_worker",
        "--master-addr", f"localhost:{master_port}",
        "--node-id", "0",
        "--dataset-size", str(cfg.dataset_size),
        "--shard-size", str(cfg.shard_size),
        "--ckpt-dir", os.path.join(ep_dir, "ckpt"),
        "--ckpt-every", str(cfg.ckpt_every),
        "--events", os.path.join(ep_dir, "events.jsonl"),
        "--progress", os.path.join(ep_dir, "progress"),
        "--generation", str(generation),
        "--step-ms", str(cfg.step_ms),
    ]
    with open(
        os.path.join(ep_dir, f"worker_gen{generation}.log"), "w"
    ) as log:
        # The child holds its own duplicate of the fd; closing the
        # parent's handle here keeps long soaks from accumulating fds.
        return subprocess.Popen(
            args, env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=_repo_root(),
        )


class _ServingScenario:
    """Tiny continuous-batching engine driven alongside the worker."""

    def __init__(self, cfg: SoakConfig, rng: random.Random):
        import jax

        from dlrover_tpu.models import llama
        from dlrover_tpu.serving.engine import ServingEngine

        model_cfg = llama.tiny_config()
        params, _ = llama.init_params(model_cfg, jax.random.key(0))
        self.engine = ServingEngine(
            model_cfg, params, slots=2, max_len=64, prefill_chunk=8
        )
        self.engine.warmup()
        self.requests = []
        self._to_submit = [
            (
                [rng.randint(1, model_cfg.vocab_size - 1)
                 for _ in range(rng.randint(4, 10))],
                cfg.serving_new_tokens,
            )
            for _ in range(cfg.serving_requests)
        ]

    def tick(self):
        if self._to_submit:
            prompt, new = self._to_submit.pop(0)
            self.requests.append(self.engine.submit(prompt, new))
        if self.engine.pending():
            self.engine.step()

    def pending(self) -> int:
        return len(self._to_submit) + self.engine.pending()

    def drain(self, deadline: float):
        while self.pending() and time.time() < deadline:
            self.tick()

    def check_invariant(self):
        from dlrover_tpu.serving import scheduler as sched_lib

        # The engine's only explicit-failure surface is cancel(), which
        # also lands requests in DONE — so "completes or is explicitly
        # failed" reduces to: every submitted request reached DONE.
        stuck = [
            r.rid for r in self.requests if r.state != sched_lib.DONE
        ]
        if stuck:
            raise SoakInvariantError(
                f"serving requests neither completed nor explicitly "
                f"failed: rids {stuck}"
            )
        for r in self.requests:
            if r.state == sched_lib.DONE and not r.truncated:
                if len(r.tokens) != r.max_new_tokens:
                    raise SoakInvariantError(
                        f"request {r.rid} finished with "
                        f"{len(r.tokens)}/{r.max_new_tokens} tokens"
                    )


def _read_events(path: str) -> List[Dict]:
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    pass  # torn tail line from a SIGKILL mid-write
    except OSError:
        pass
    return events


def _read_trace(path: str, origin: str) -> List[Dict]:
    out = []
    for entry in _read_events(path):
        out.append({
            "origin": origin,
            "point": entry.get("point"),
            "action": entry.get("action"),
            "rule_id": entry.get("rule_id"),
            "hit": entry.get("hit"),
        })
    return out


def _check_ledger_invariants(events: List[Dict], plan: EpisodePlan,
                             cfg: SoakConfig):
    """Invariants 1 and 2, from the worker's crash-surviving ledger."""
    dones = [e for e in events if e.get("kind") == "done"]
    if not dones:
        raise SoakInvariantError("worker never reported completion")
    final = dones[-1]
    want_sum = sw.expected_sum(cfg.dataset_size)
    if final["sum"] != want_sum:
        raise SoakInvariantError(
            f"exactly-once violated: final sum {final['sum']} != "
            f"expected {want_sum} (records lost or replayed)"
        )
    if final["hist"] != sw.expected_hist(cfg.dataset_size).tolist():
        raise SoakInvariantError(
            "exactly-once violated: per-bucket record counts diverge"
        )
    # Checkpoint integrity: every restore's CRC matches the newest
    # prior save of that step; saves advance within a generation.
    saves_by_step: Dict[int, int] = {}
    last_save_step = {"gen": -1, "step": -1}
    max_save_step = -1
    for e in events:
        if e.get("kind") == "save":
            saves_by_step[e["step"]] = e["crc"]
            max_save_step = max(max_save_step, e["step"])
            if last_save_step["step"] >= e["step"] and (
                last_save_step["gen"] == e.get("generation", -2)
            ):
                raise SoakInvariantError(
                    f"saves not monotonic within a generation: "
                    f"{last_save_step['step']} then {e['step']}"
                )
            last_save_step = {
                "gen": e.get("generation", -2), "step": e["step"]
            }
        elif e.get("kind") == "restore":
            step = e["step"]
            if step > max_save_step:
                raise SoakInvariantError(
                    f"restored step {step} was never saved"
                )
            if step in saves_by_step and e["crc"] != saves_by_step[step]:
                raise SoakInvariantError(
                    f"restore of step {step} is not bit-identical to "
                    f"its save (crc {e['crc']} != {saves_by_step[step]})"
                )
        elif e.get("kind") == "restore_crc_mismatch" and (
            e.get("source") == "storage"
        ):
            raise SoakInvariantError(
                f"storage restore failed integrity at step {e.get('step')}"
            )
    if plan.kind == "torn_ckpt":
        restores = [
            e for e in events
            if e.get("kind") == "restore" and e.get("generation", 0) >= 1
        ]
        if not restores:
            raise SoakInvariantError(
                "torn episode: post-crash generation never restored"
            )
        got = restores[0]["step"]
        if got != plan.fallback_step:
            raise SoakInvariantError(
                f"torn shard not rejected: post-crash restore got step "
                f"{got}, expected fallback step {plan.fallback_step}"
            )


def _dump_artifacts(ep_dir: str, artifact_dir: str, plan: EpisodePlan,
                    seed: int, episode: int, reason: str) -> str:
    os.makedirs(artifact_dir, exist_ok=True)
    dest = os.path.join(artifact_dir, f"soak_seed{seed}_ep{episode}")
    shutil.rmtree(dest, ignore_errors=True)
    os.makedirs(dest, exist_ok=True)
    for name in ("events.jsonl", "trace_worker.jsonl", "progress"):
        src = os.path.join(ep_dir, name)
        if os.path.exists(src):
            shutil.copy(src, dest)
    for src in glob.glob(os.path.join(ep_dir, "worker_gen*.log")):
        shutil.copy(src, dest)
    flight_src = os.path.join(ep_dir, "flight")
    if os.path.isdir(flight_src):
        shutil.copytree(
            flight_src, os.path.join(dest, "flight"), dirs_exist_ok=True
        )
    for g, sched in enumerate(plan.worker_schedules):
        with open(os.path.join(dest, f"schedule_gen{g}.json"), "w") as f:
            f.write(sched.to_json())
    if plan.runner_schedule is not None:
        with open(os.path.join(dest, "schedule_runner.json"), "w") as f:
            f.write(plan.runner_schedule.to_json())
    with open(os.path.join(dest, "failure.json"), "w") as f:
        json.dump({
            "seed": seed, "episode": episode, "kind": plan.kind,
            "reason": reason,
        }, f, indent=2)
    return dest


def run_episode(seed: int, episode: int, cfg: SoakConfig,
                work_dir: str, artifact_dir: str) -> Dict:
    """Run one episode; returns its report dict. Raises
    SoakInvariantError (after dumping artifacts) on failure."""
    from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.shard.task_manager import TaskManager
    from dlrover_tpu.rpc.transport import HttpMasterServer

    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0x5EED)
    plan = build_episode_plan(seed, episode, cfg)
    if plan.kind == "kill_during_rescale":
        return _run_rescale_kind(
            seed, episode, plan, cfg, work_dir, artifact_dir
        )
    if plan.kind == "replica_kill_reroute":
        return _run_fleet_kind(
            seed, episode, plan, cfg, work_dir, artifact_dir
        )
    if plan.kind == "kill_during_migration":
        return _run_migration_kind(
            seed, episode, plan, cfg, work_dir, artifact_dir
        )
    if plan.kind == "straggler_evict":
        return _run_autoscale_kind(seed, episode, cfg)
    if plan.kind == "master_kill":
        return _run_master_kill_kind(
            seed, episode, plan, cfg, work_dir, artifact_dir
        )
    ep_dir = os.path.join(work_dir, f"soak-s{seed}-e{episode}")
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(os.path.join(ep_dir, "flight"), exist_ok=True)
    os.makedirs(os.path.join(ep_dir, "ckpt"), exist_ok=True)

    schedule_paths = []
    for g, sched in enumerate(plan.worker_schedules):
        path = os.path.join(ep_dir, f"schedule_gen{g}.json")
        with open(path, "w") as f:
            f.write(sched.to_json())
        schedule_paths.append(path)

    task_manager = TaskManager(task_timeout=cfg.task_timeout_s)
    perf_monitor = PerfMonitor()
    servicer = MasterServicer(
        rdzv_managers={},
        task_manager=task_manager,
        perf_monitor=perf_monitor,
    )
    server = HttpMasterServer(0, servicer)
    server.start()
    arm(plan.runner_schedule)

    serving = _ServingScenario(cfg, rng) if cfg.serve else None
    deaths: List[Dict] = []
    report: Dict = {
        "episode": episode, "seed": seed, "kind": plan.kind,
        "generations": 0,
    }
    t_start = time.time()
    deadline = t_start + cfg.watchdog_s
    failure: Optional[str] = None
    proc: Optional[subprocess.Popen] = None
    try:
        generation = 0
        while True:
            sched_path = (
                schedule_paths[generation]
                if generation < len(schedule_paths) else ""
            )
            proc = _spawn_worker(
                plan, cfg, ep_dir, server.port, generation, sched_path
            )
            report["generations"] = generation + 1
            last_recover = 0.0
            while proc.poll() is None:
                now = time.time()
                if now > deadline:
                    failure = "watchdog: episode deadline exceeded"
                    break
                if now - last_recover > 0.5:
                    last_recover = now
                    for mgr in list(
                        task_manager._datasets.values()  # noqa: SLF001
                    ):
                        mgr.recover_timeout_tasks(cfg.task_timeout_s)
                if serving is not None and serving.pending():
                    serving.tick()
                else:
                    time.sleep(0.02)
            if failure:
                break
            rc = proc.returncode
            if rc == sw.EXIT_OK:
                break
            death_t = time.time()
            deaths.append({
                "t": death_t, "rc": rc, "generation": generation,
                "signal": -rc if rc < 0 else None,
            })
            # The master's node-failure path: re-queue the dead
            # worker's in-flight leases.
            task_manager.recover_node_tasks(0)
            generation += 1
            if generation >= cfg.max_generations:
                failure = (
                    f"worker did not complete within "
                    f"{cfg.max_generations} generations (last rc={rc})"
                )
                break
        if not failure and serving is not None:
            serving.drain(deadline)
            if serving.pending():
                failure = "watchdog: serving did not drain"
    finally:
        if proc is not None and proc.poll() is None:
            # SIGTERM first: the worker's flight recorder dumps its ring
            # on SIGTERM, which is exactly the evidence we want.
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        disarm()
        server.stop()
        task_manager.stop()
        # The dead worker's shm checkpoint segment outlives it (that is
        # the flash-ckpt feature); reclaim it once the episode is over.
        try:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(
                name=f"dlrover_tpu_ckpt_{os.path.basename(ep_dir)}_n0_0"
            )
            seg.close()
            seg.unlink()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass

    wall = time.time() - t_start
    events = _read_events(os.path.join(ep_dir, "events.jsonl"))
    try:
        if failure:
            raise SoakInvariantError(failure)
        _check_ledger_invariants(events, plan, cfg)
        if serving is not None:
            serving.check_invariant()
    except SoakInvariantError as e:
        dest = _dump_artifacts(
            ep_dir, artifact_dir, plan, seed, episode, str(e)
        )
        print(
            f"SOAK EPISODE FAILED: {e}\n"
            f"  artifacts: {dest}\n"
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise

    # ---- goodput / MTTR accounting ------------------------------------
    step_events = [e for e in events if e.get("kind") == "step"]
    last_dur: Dict[int, float] = {}
    for e in step_events:
        last_dur[e["step"]] = e.get("dur", 0.0)
    productive_s = sum(last_dur.values())
    recoveries = []
    for death in deaths:
        after = [e for e in step_events if e["t"] > death["t"]]
        if after:
            recoveries.append(after[0]["t"] - death["t"])
    trace = (
        _read_trace(os.path.join(ep_dir, "trace_worker.jsonl"), "worker")
        + [
            {
                "origin": "runner",
                "point": t["point"],
                "action": t["action"],
                "rule_id": t["rule_id"],
                "hit": t["hit"],
            }
            for t in plan.runner_schedule.trace
        ]
    )
    trace.sort(key=lambda t: (t["origin"], str(t["rule_id"])))
    report.update({
        "wall_s": round(wall, 3),
        "productive_step_s": round(productive_s, 3),
        "goodput_frac": round(min(productive_s / max(wall, 1e-9), 1.0), 4),
        "faults": trace,
        "deaths": len(deaths),
        "recovery_s": [round(r, 3) for r in recoveries],
        "steps_unique": len(last_dur),
        "steps_executed": len(step_events),
    })
    if not cfg.keep_artifacts_on_success:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return report


def _run_rescale_kind(seed, episode, plan, cfg, work_dir, artifact_dir):
    """Episode kind 4: delegate to the multi-worker live-rescale
    harness and reshape its report to the soak report schema."""
    from dlrover_tpu.testing.rescale_soak import (
        RescaleSoakConfig,
        run_rescale_episode,
    )

    rcfg = RescaleSoakConfig(
        world=2,
        dataset_size=cfg.dataset_size,
        shard_size=cfg.shard_size,
        ckpt_every=cfg.ckpt_every,
        step_ms=cfg.step_ms,
        watchdog_s=cfg.watchdog_s,
        keep_artifacts_on_success=cfg.keep_artifacts_on_success,
    )
    try:
        report = run_rescale_episode(
            seed,
            cfg=rcfg,
            scenario="kill_during_rescale",
            work_dir=work_dir,
            artifact_dir=artifact_dir,
            runner_schedule=plan.runner_schedule,
            rank_schedules=plan.rank_schedules,
        )
    except SoakInvariantError:
        print(
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise
    gens = report.pop("generations", {})
    report.update({
        "episode": episode,
        "kind": plan.kind,
        "generations": sum(g + 1 for g in gens.values()),
    })
    return report


def _run_fleet_kind(seed, episode, plan, cfg, work_dir, artifact_dir):
    """Episode kind 5: delegate to the serving-fleet harness — a
    subprocess replica is SIGKILLed mid-decode, the router re-routes
    its in-flight ledger and walks the victim's breaker back to
    HEALTHY through half-open probes. The report is already
    soak-shaped."""
    from dlrover_tpu.testing.fleet_soak import (
        FleetSoakConfig,
        run_fleet_episode,
    )

    fcfg = FleetSoakConfig(
        watchdog_s=cfg.watchdog_s,
        keep_artifacts_on_success=cfg.keep_artifacts_on_success,
    )
    try:
        return run_fleet_episode(
            seed,
            episode=episode,
            cfg=fcfg,
            work_dir=work_dir,
            artifact_dir=artifact_dir,
            runner_schedule=plan.runner_schedule,
        )
    except SoakInvariantError:
        print(
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise


def _run_migration_kind(seed, episode, plan, cfg, work_dir,
                        artifact_dir):
    """Episode kind 6 (kill_during_migration): delegate to the fleet
    harness's §36 scenario — a prefill+decode split fleet whose
    destination replica is SIGKILLed between KV export and import ack.
    The report is already soak-shaped."""
    from dlrover_tpu.testing.fleet_soak import (
        FleetSoakConfig,
        run_migration_episode,
    )

    fcfg = FleetSoakConfig(
        watchdog_s=cfg.watchdog_s,
        keep_artifacts_on_success=cfg.keep_artifacts_on_success,
    )
    try:
        return run_migration_episode(
            seed,
            episode=episode,
            cfg=fcfg,
            work_dir=work_dir,
            artifact_dir=artifact_dir,
            runner_schedule=plan.runner_schedule,
        )
    except SoakInvariantError:
        print(
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise


def _run_master_kill_kind(seed, episode, plan, cfg, work_dir,
                          artifact_dir):
    """Episode kind 7 (master_kill): delegate to the control-plane
    crash-recovery harness — the master subprocess is SIGKILLed between
    a journaled dispatch and its reply, restarted from the journal, and
    the never-restarted worker must finish with exactly-once accounting
    (docs/DESIGN.md §37). The report is already soak-shaped."""
    from dlrover_tpu.testing.master_kill_soak import (
        run_master_kill_episode,
    )

    return run_master_kill_episode(
        seed, episode, plan, cfg, work_dir, artifact_dir
    )


def _run_autoscale_kind(seed, episode, cfg):
    """Episode kind 5 (straggler_evict): delegate to the closed-loop
    autoscaler harness
    — the same seeded fault+traffic schedule run static, dry-run and
    autoscaled; the autoscaled run must evict the delayed straggler
    within bounded decision windows and strictly beat the static
    goodput fraction. The report is already soak-shaped."""
    from dlrover_tpu.testing.autoscale_soak import (
        AutoscaleSoakConfig,
        run_autoscale_episode,
    )

    acfg = AutoscaleSoakConfig(
        watchdog_s=min(cfg.watchdog_s, 120.0),
    )
    try:
        return run_autoscale_episode(seed, episode=episode, cfg=acfg)
    except SoakInvariantError:
        print(
            f"  repro: python tools/chaos_soak.py --seed {seed} "
            f"--episode {episode}",
            file=sys.stderr, flush=True,
        )
        raise


def run_soak(seed: int = 0, episodes: int = 3,
             cfg: Optional[SoakConfig] = None,
             episode: Optional[int] = None,
             work_dir: Optional[str] = None,
             artifact_dir: Optional[str] = None) -> Dict:
    """Run ``episodes`` chaos episodes (or just ``episode``); returns a
    summary with per-episode reports and aggregate goodput/MTTR."""
    cfg = cfg or SoakConfig()
    work_dir = work_dir or tempfile.mkdtemp(prefix="dlrover_soak_")
    artifact_dir = artifact_dir or os.path.join(work_dir, "artifacts")
    targets = [episode] if episode is not None else list(range(episodes))
    reports = []
    for k in targets:
        logger.info("chaos soak: seed=%d episode=%d starting", seed, k)
        reports.append(
            run_episode(seed, k, cfg, work_dir, artifact_dir)
        )
    all_recoveries = [r for rep in reports for r in rep["recovery_s"]]
    walls = sum(r["wall_s"] for r in reports)
    productive = sum(r["productive_step_s"] for r in reports)
    return {
        "seed": seed,
        "episodes": len(reports),
        "reports": reports,
        "goodput_frac": round(productive / max(walls, 1e-9), 4),
        "mttr_mean_s": round(
            sum(all_recoveries) / len(all_recoveries), 3
        ) if all_recoveries else 0.0,
        "mttr_max_s": round(max(all_recoveries), 3)
        if all_recoveries else 0.0,
        "faults_injected": sum(len(r["faults"]) for r in reports),
        "invariants": "pass",
    }
