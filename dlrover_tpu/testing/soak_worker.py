"""Chaos-soak training worker: one crash-restartable generation.

Spawned (and re-spawned after every injected crash) by
``tools/chaos_soak.py``. Each generation runs the REAL worker-side
stack against the soak's in-process master:

- :class:`MasterClient` over the HTTP transport (keep-alive stub,
  at-most-once retry semantics);
- :class:`ShardingClient` with the prefetch pipeline + coalesced
  done-reports (exactly-once shard accounting under test);
- :class:`CheckpointEngine` standalone (shm image + raw-format disk
  persist + commit protocol, torn-shard rejection + fallback restore
  under test);
- :class:`ElasticTrainer` step bookkeeping (the ``agent.worker.crash``
  fault site) and the flight recorder.

The "model" is a deterministic numpy state updated per record —
integer leaves are order-independent exact sums, so after any fault
sequence the final state equals the exactly-once expectation iff every
record contributed exactly once relative to the restored checkpoints.

Crash-surviving evidence: every step/save/restore appends one fsynced
JSON line to ``--events`` BEFORE training continues, so even a SIGKILL
mid-step leaves a complete ledger for the runner's invariant checks.
"""

import argparse
import binascii
import json
import os
import sys
import time
from typing import Dict, Optional

import numpy as np

HIST_BUCKETS = 64
VEC_LEN = 256

# Worker exit codes the runner interprets.
EXIT_OK = 0
EXIT_INTEGRITY = 3      # restored checkpoint failed its content check
EXIT_ACCOUNTING = 4     # shard/report protocol failed


def fresh_state() -> Dict[str, np.ndarray]:
    return {
        "sum": np.zeros((), np.int64),
        "hist": np.zeros(HIST_BUCKETS, np.int64),
        "vec": np.zeros(VEC_LEN, np.float64),
    }


def apply_shard(state: Dict[str, np.ndarray], start: int, end: int):
    """Deterministic, order-independent (on the integer leaves) state
    update for records [start, end)."""
    idxs = np.arange(start, end, dtype=np.int64)
    state["sum"] += idxs.sum()
    np.add.at(state["hist"], idxs % HIST_BUCKETS, 1)
    np.add.at(state["vec"], idxs % VEC_LEN, np.sqrt(idxs + 1.0))


def expected_sum(dataset_size: int) -> int:
    return dataset_size * (dataset_size - 1) // 2


def expected_hist(dataset_size: int) -> np.ndarray:
    idxs = np.arange(dataset_size, dtype=np.int64)
    hist = np.zeros(HIST_BUCKETS, np.int64)
    np.add.at(hist, idxs % HIST_BUCKETS, 1)
    return hist


def state_crc(state: Dict[str, np.ndarray]) -> int:
    crc = 0
    for key in sorted(state):
        arr = np.ascontiguousarray(state[key])
        crc = binascii.crc32(arr.tobytes(), crc)
        crc = binascii.crc32(str(arr.dtype).encode(), crc)
    return crc


class EventLog:
    """Append-only fsynced JSONL ledger that survives SIGKILL."""

    def __init__(self, path: str):
        self._f = open(path, "a")

    def append(self, **entry):
        entry.setdefault("t", time.time())
        self._f.write(json.dumps(entry) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())


def _write_progress(path: str, step: int):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{step} {time.time():.6f}")
    os.replace(tmp, path)


def _restore(engine, events: EventLog):
    """Restore the newest restorable checkpoint, integrity-checked.

    Memory-first through the engine; a torn/implausible shm image (the
    worker may have been SIGKILLed mid shm write) falls back to the
    committed storage checkpoint, which itself falls back past
    torn/corrupt step dirs (engine fallback walk)."""
    result = None
    try:
        result = engine.load()
    except Exception as e:  # noqa: BLE001 — a torn shm image may raise
        events.append(kind="restore_memory_error", error=str(e)[:200])
    if result is not None:
        step, state, meta = result
        crc = state_crc(state)
        if crc == meta.get("state_crc"):
            return step, state, meta, "memory_or_storage"
        events.append(
            kind="restore_crc_mismatch", step=step,
            got=crc, want=meta.get("state_crc"),
        )
        # The shm image lied; retry restricted to committed storage.
        result = None
    try:
        result = engine._load_from_storage(None, None)  # noqa: SLF001
    except Exception as e:  # noqa: BLE001
        events.append(kind="restore_storage_error", error=str(e)[:200])
        result = None
    if result is None:
        return None
    step, state, meta = result
    crc = state_crc(state)
    if crc != meta.get("state_crc"):
        events.append(
            kind="restore_crc_mismatch", step=step,
            got=crc, want=meta.get("state_crc"), source="storage",
        )
        print("restored storage checkpoint failed integrity check",
              file=sys.stderr)
        sys.exit(EXIT_INTEGRITY)
    return step, state, meta, "storage"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="chaos soak worker")
    parser.add_argument("--master-addr", required=True)
    parser.add_argument("--node-id", type=int, default=0)
    parser.add_argument("--dataset", default="soak")
    parser.add_argument("--dataset-size", type=int, required=True)
    parser.add_argument("--shard-size", type=int, default=16)
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--ckpt-every", type=int, default=2,
                        help="checkpoint every N steps (shards)")
    parser.add_argument("--events", required=True,
                        help="append-only JSONL ledger path")
    parser.add_argument("--progress", required=True,
                        help="progress file (atomic replace per step)")
    parser.add_argument("--generation", type=int, default=0)
    parser.add_argument(
        "--step-ms", type=float, default=0.0,
        help="simulated compute per step, so goodput accounting has a "
        "visible productive-time signal",
    )
    args = parser.parse_args(argv)

    from dlrover_tpu.fault import arm_from_env

    arm_from_env()

    from dlrover_tpu.observability import flight_recorder

    flight_recorder.install_recorder(
        node_rank=args.node_id, local_rank=0,
        meta={"soak_generation": args.generation},
    )

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.flash_ckpt.engine import CheckpointEngine
    from dlrover_tpu.trainer.elastic.sharding_client import ShardingClient
    from dlrover_tpu.trainer.elastic.trainer import (
        ElasticBatchConfig,
        ElasticTrainer,
    )

    events = EventLog(args.events)
    events.append(kind="worker_start", generation=args.generation,
                  pid=os.getpid())

    client = MasterClient(
        args.master_addr, node_id=args.node_id, kind="http", timeout=10.0
    )
    engine = CheckpointEngine(args.ckpt_dir, standalone=True)

    restored = _restore(engine, events)
    if restored is not None:
        step0, state, meta, source = restored
        shard_ckpt = meta.get("shard_ckpt", "")
        events.append(
            kind="restore", step=int(step0), crc=state_crc(state),
            source=source, generation=args.generation,
        )
    else:
        step0, state, shard_ckpt = 0, fresh_state(), ""
        events.append(kind="fresh_start", generation=args.generation)

    sharding_client = ShardingClient(
        client,
        dataset_name=args.dataset,
        dataset_size=args.dataset_size,
        shard_size=args.shard_size,
        prefetch_depth=4,
        fetch_batch=2,
        report_batch=2,
        report_interval_s=0.2,
        wait_backoff_s=0.05,
        wait_backoff_max_s=0.5,
    )
    # The dataset position must rewind to EXACTLY the snapshot taken
    # with the restored state — shards completed after that snapshot
    # were rolled back out of the state and must be re-dispatched.
    sharding_client.restore_shard_checkpoint(shard_ckpt)

    trainer = ElasticTrainer(
        ElasticBatchConfig(
            global_batch_size=args.shard_size,
            micro_batch_per_device=args.shard_size,
        ),
        dp_size=1,
        master_client=client,
        report_interval_s=0.5,
    )
    trainer.global_step = int(step0)
    trainer.start_training()

    if restored is None:
        # Initial checkpoint BEFORE consuming anything: a later restart
        # then always has a (state, shard-snapshot) pair to rewind to.
        # Without it, a crash before the first cadence save would leave
        # the next generation starting with fresh state against a
        # master that already counted this generation's done-reports —
        # records silently lost (exactly-once broken).
        crc = state_crc(state)
        engine.save_to_storage(
            0, state,
            user_meta={
                "state_crc": crc,
                "shard_ckpt": sharding_client.get_shard_checkpoint(),
            },
        )
        events.append(kind="save", step=0, crc=crc,
                      generation=args.generation)

    while True:
        t_step = time.time()
        task = sharding_client.fetch_task()
        if task is None:
            break
        apply_shard(state, task.start, task.end)
        if args.step_ms > 0:
            time.sleep(args.step_ms / 1e3)
        sharding_client.report_task_done(task)
        # agent.worker.crash fires inside step_completed — the ledger
        # entry below is intentionally AFTER it, so a crashed step never
        # claims completion.
        trainer.step_completed(steps=1)
        step = trainer.global_step
        events.append(
            kind="step", step=step, dur=time.time() - t_step,
            shard=[task.start, task.end], generation=args.generation,
        )
        _write_progress(args.progress, step)
        if step % max(args.ckpt_every, 1) == 0:
            try:
                ckpt_str = sharding_client.get_shard_checkpoint()
            except RuntimeError as e:
                # Unflushable done-reports: refusing the checkpoint is
                # the correct degraded behavior; train on and retry at
                # the next cadence tick.
                events.append(kind="ckpt_refused", step=step,
                              error=str(e)[:200])
                continue
            crc = state_crc(state)
            engine.save_to_storage(
                step, state,
                user_meta={"state_crc": crc, "shard_ckpt": ckpt_str},
            )
            events.append(kind="save", step=step, crc=crc,
                          generation=args.generation)

    sharding_client.stop()
    final = {
        "sum": int(state["sum"]),
        "hist": state["hist"].tolist(),
        "steps": int(trainer.global_step),
        "generation": args.generation,
        "crc": state_crc(state),
    }
    events.append(kind="done", **final)
    engine.close()
    return EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
