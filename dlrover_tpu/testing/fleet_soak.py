"""Fleet chaos episode: replica SIGKILL mid-decode, re-route, recover.

The ``replica_kill_reroute`` episode kind (chaos soak episode 4): a
:class:`~dlrover_tpu.serving.fleet.router.FleetRouter` over N
subprocess replicas serves a seeded Poisson-ish request stream while a
deterministic fault schedule SIGKILLs one replica between engine
iterations with requests live in its slots (``fleet.replica.step``
crash rule, armed through the standard env rigging so the fault trace
survives the kill). After the stream drains, the **fleet invariant** is
asserted:

    every accepted request completes or is explicitly failed exactly
    once — zero duplicate completions, zero silently lost — and the
    router's health FSM marked the killed replica BROKEN then re-
    admitted it after half-open probes succeeded.

Randomness lives in plan generation (`random.Random(seed, episode)`),
kill timing in the deterministic hit counter — one seed reproduces one
episode, the PR-5 contract.
"""

import os
import random
import shutil
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import FaultRule, FaultSchedule, arm, disarm
from dlrover_tpu.observability import tracing
from dlrover_tpu.serving.fleet import (
    BROKEN,
    HALF_OPEN,
    HEALTHY,
    FleetRouter,
    HealthPolicy,
    RouterConfig,
    SubprocessReplica,
)
from dlrover_tpu.testing.soak import SoakInvariantError, _read_trace


@dataclass
class FleetSoakConfig:
    replicas: int = 2
    requests: int = 12
    new_tokens_short: int = 4
    new_tokens_long: int = 10
    slots: int = 2
    max_len: int = 64
    prefill_chunk: int = 8
    watchdog_s: float = 180.0
    keep_artifacts_on_success: bool = False
    # Paged-KV replicas (§31): heartbeats then carry allocator stats
    # and the episode asserts the BLOCK-RECLAIM invariant — after the
    # mid-run kill and reroute, free+used+cached blocks still sum to
    # the managed pool on every replica and no refcount went negative
    # (a block leak under crash is a regression from day one).
    paged: bool = True
    block_size: int = 8


def build_fleet_schedules(
    seed: int, episode: int, cfg: Optional[FleetSoakConfig] = None
) -> Dict[str, FaultSchedule]:
    """Deterministic per-replica schedules for (seed, episode): the
    victim replica is SIGKILLed on its Nth serve-loop iteration WITH
    work pending (the fault point sits inside the ``engine.pending()``
    branch, so hit N always lands mid-decode)."""
    cfg = cfg or FleetSoakConfig()
    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0xF1EE7)
    victim = str(rng.randrange(cfg.replicas))
    # Late enough that requests are decoding, early enough that the
    # kill always fires before the stream drains.
    kill_nth = rng.randint(4, 10)
    schedules = {
        victim: FaultSchedule([
            FaultRule("fleet.replica.step", action="crash",
                      nth=kill_nth, rule_id="replica-sigkill"),
        ], seed=ep_seed, label=f"replica{victim}"),
    }
    return schedules


def build_migration_schedules(
    seed: int, episode: int, cfg: Optional[FleetSoakConfig] = None
) -> Dict[str, FaultSchedule]:
    """Deterministic schedule for ``kill_during_migration`` (seed,
    episode): the DESTINATION decode replica (always replica 1 in the
    two-replica prefill+decode topology) is SIGKILLed at the
    ``fleet.replica.import`` fault point — after the source exported
    the KV payload, before the import ack is emitted. The nth import
    it dies on is the seeded part."""
    cfg = cfg or FleetSoakConfig()
    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0x3160)
    victim = "1"  # the decode tier of the 2-replica split topology
    kill_nth = rng.randint(1, 3)
    return {
        victim: FaultSchedule([
            FaultRule("fleet.replica.import", action="crash",
                      nth=kill_nth, rule_id="dst-sigkill-mid-import"),
        ], seed=ep_seed, label=f"replica{victim}"),
    }


def run_fleet_episode(
    seed: int,
    episode: int = 4,
    cfg: Optional[FleetSoakConfig] = None,
    work_dir: Optional[str] = None,
    artifact_dir: Optional[str] = None,
    runner_schedule: Optional[FaultSchedule] = None,
) -> Dict:
    """One replica_kill_reroute episode; returns a soak-shaped report.
    Raises SoakInvariantError (artifacts kept) on violation."""
    import tempfile

    cfg = cfg or FleetSoakConfig()
    work_dir = work_dir or tempfile.mkdtemp(prefix="dlrover_fleet_")
    artifact_dir = artifact_dir or os.path.join(work_dir, "artifacts")
    ep_dir = os.path.join(work_dir, f"fleet-s{seed}-e{episode}")
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(ep_dir, exist_ok=True)
    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0x5EED)
    schedules = build_fleet_schedules(seed, episode, cfg)
    victim = next(iter(schedules))

    schedule_paths: Dict[str, str] = {}
    for rid, sched in schedules.items():
        path = os.path.join(ep_dir, f"schedule_replica{rid}.json")
        with open(path, "w") as f:
            f.write(sched.to_json())
        schedule_paths[rid] = path

    from dlrover_tpu.observability.registry import MetricsRegistry

    registry = MetricsRegistry()
    # Tracing is part of the episode's proof surface (§29): the router
    # traces into its own sink, each replica subprocess into its own
    # (rigged through the env by SubprocessReplica.start), and the
    # trace invariant below reads the merged files.
    prev_tracer = tracing.active_tracer()
    router_sink = os.path.join(ep_dir, "spans_router.jsonl")
    tracing.arm(tracing.Tracer(service="router", sink_path=router_sink))

    def _restore_tracer():
        tracing.disarm()
        if prev_tracer is not None:
            tracing.arm(prev_tracer)

    try:
        replicas = [
            SubprocessReplica(
                str(i), ep_dir,
                slots=cfg.slots, max_len=cfg.max_len,
                prefill_chunk=cfg.prefill_chunk,
                paged=cfg.paged, block_size=cfg.block_size,
                # Per-generation: the victim's SIGKILL schedule arms
                # only generation 0 — its post-restart generations run
                # clean, so the half-open probes can actually succeed.
                schedule_path=(
                    [schedule_paths[str(i)]]
                    if str(i) in schedule_paths else ""
                ),
            )
            for i in range(cfg.replicas)
        ]
        router = FleetRouter(
            replicas,
            RouterConfig(
                max_retries=3,
                seed=ep_seed,
                health=HealthPolicy(
                    heartbeat_timeout_s=2.0,
                    probe_cooldown_s=0.5,
                    probe_successes=2,
                ),
            ),
            registry=registry,
        )
    except BaseException:
        # Construction failed before the run's own finally could take
        # over: the episode tracer must not stay armed process-wide.
        _restore_tracer()
        raise
    if runner_schedule is not None:
        arm(runner_schedule)

    health_seen = {rid: set() for rid in router._replicas}  # noqa: SLF001

    def note_health():
        for rid in health_seen:
            health_seen[rid].add(router.health_state(rid))

    t_start = time.time()
    deadline = t_start + cfg.watchdog_s
    accepted: List = []
    failure: Optional[str] = None
    vocab_hi = 100  # tiny llama vocab is larger; any id >= 1 works
    try:
        router.start(timeout_s=min(120.0, cfg.watchdog_s))
        to_submit = [
            (
                [rng.randint(1, vocab_hi) for _ in
                 range(rng.randint(4, 10))],
                cfg.new_tokens_long if rng.random() < 0.5
                else cfg.new_tokens_short,
            )
            for _ in range(cfg.requests)
        ]
        while to_submit or router.pending():
            if time.time() > deadline:
                failure = "watchdog: fleet episode deadline exceeded"
                break
            if to_submit:
                prompt, new = to_submit.pop(0)
                accepted.append(router.submit(prompt, new))
            router.step()
            note_health()
            time.sleep(0.005)
        # Recovery half: keep trickling traffic until the victim's
        # breaker walks BROKEN -> HALF_OPEN -> HEALTHY again.
        while not failure and router.health_state(victim) != HEALTHY:
            if time.time() > deadline:
                failure = (
                    f"watchdog: victim replica {victim} never "
                    f"re-admitted (stuck {router.health_state(victim)})"
                )
                break
            if router.pending() == 0:
                accepted.append(router.submit(
                    [rng.randint(1, vocab_hi) for _ in range(5)],
                    cfg.new_tokens_short,
                ))
            router.step()
            note_health()
            time.sleep(0.005)
        if not failure:
            try:
                router.run_until_idle(
                    timeout_s=max(1.0, deadline - time.time())
                )
            except TimeoutError as e:
                failure = f"watchdog: {e}"
    finally:
        if runner_schedule is not None:
            disarm()
        router.stop()
        _restore_tracer()

    wall = time.time() - t_start
    report: Dict = {
        "episode": episode,
        "seed": seed,
        "kind": "replica_kill_reroute",
        "wall_s": round(wall, 3),
        "victim": victim,
        "requests": len(accepted),
    }
    import glob as glob_lib

    episode_spans = tracing.load_spans(
        [router_sink]
        + sorted(glob_lib.glob(os.path.join(ep_dir, "spans_replica*.jsonl")))
    )
    try:
        if failure:
            raise SoakInvariantError(failure)
        _check_fleet_invariant(
            accepted, router, registry, victim, health_seen
        )
        if cfg.paged:
            kv_final = _check_block_reclaim(replicas, victim)
            report["kv_blocks"] = kv_final
        trace_stats = _check_trace_invariant(
            episode_spans,
            require_reroute=registry.get(
                "fleet_reroutes_total"
            ).value() >= 1,
        )
    except SoakInvariantError as e:
        dest = _dump_artifacts(
            ep_dir, artifact_dir, schedules, seed, episode, str(e)
        )
        logger.error(
            "FLEET EPISODE FAILED: %s\n  artifacts: %s", e, dest
        )
        raise
    # ---- goodput-shaped accounting (soak report schema) ---------------
    results = [r.result for r in accepted if r.result is not None]
    completed = [r for r in results if r.ok]
    report.update({
        "productive_step_s": round(sum(
            r.latency_s or 0.0 for r in completed
        ), 3),
        "goodput_frac": round(
            len(completed) / max(len(results), 1), 4
        ),
        "completed": len(completed),
        "failed": len(results) - len(completed),
        "reroutes": int(
            registry.get("fleet_reroutes_total").value()
        ),
        "retries": int(registry.get("fleet_retries_total").value()),
        "duplicates": int(
            registry.get("fleet_duplicate_completions_total").value()
        ),
        "stale": int(
            registry.get("fleet_stale_completions_total").value()
        ),
        "restarts": int(
            registry.get("fleet_replica_restarts_total").value()
        ),
        "deaths": 1,
        "recovery_s": [],
        "steps_unique": len(completed),
        "steps_executed": len(results),
        "trace_spans": len(episode_spans),
        "trace_rerouted_trees": trace_stats["rerouted_trees"],
        "trace_phase_sum_checked": trace_stats["phase_sum_checked"],
        "faults": [
            t
            for rid in schedules
            for t in _read_trace(
                os.path.join(ep_dir, f"trace_replica{rid}.jsonl"),
                f"replica{rid}",
            )
        ],
    })
    if not cfg.keep_artifacts_on_success:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return report


def run_migration_episode(
    seed: int,
    episode: int = 6,
    cfg: Optional[FleetSoakConfig] = None,
    work_dir: Optional[str] = None,
    artifact_dir: Optional[str] = None,
    runner_schedule: Optional[FaultSchedule] = None,
) -> Dict:
    """One ``kill_during_migration`` episode (§36): a prefill+decode
    split fleet serves a seeded stream, and the DESTINATION replica is
    SIGKILLed between the source's export and the import ack — the
    moment a migrating request's KV payload exists on the wire but
    nowhere durable. Asserted afterwards:

    - **exactly-once**: every accepted request completes or fails
      exactly once — the killed import's request finishes on its
      never-released SOURCE (option-B fallback), no duplicates;
    - **zero lost blocks**: block conservation holds on every replica
      at every heartbeat, across the victim's kill and restart;
    - **the window actually fired** (fault trace) and the fleet
      healed: victim walked BROKEN -> HALF_OPEN -> HEALTHY, and at
      least one migration SUCCEEDED after the restart (decode-role
      breakers are probed by migration traffic, so the success IS the
      probe).

    Raises SoakInvariantError (artifacts kept) on violation."""
    import tempfile

    cfg = cfg or FleetSoakConfig()
    work_dir = work_dir or tempfile.mkdtemp(prefix="dlrover_migsoak_")
    artifact_dir = artifact_dir or os.path.join(work_dir, "artifacts")
    ep_dir = os.path.join(work_dir, f"mig-s{seed}-e{episode}")
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(ep_dir, exist_ok=True)
    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0x5EED)
    schedules = build_migration_schedules(seed, episode, cfg)
    victim = next(iter(schedules))

    schedule_paths: Dict[str, str] = {}
    for rid, sched in schedules.items():
        path = os.path.join(ep_dir, f"schedule_replica{rid}.json")
        with open(path, "w") as f:
            f.write(sched.to_json())
        schedule_paths[rid] = path

    from dlrover_tpu.observability.registry import MetricsRegistry

    registry = MetricsRegistry()
    prev_tracer = tracing.active_tracer()
    router_sink = os.path.join(ep_dir, "spans_router.jsonl")
    tracing.arm(tracing.Tracer(service="router", sink_path=router_sink))

    def _restore_tracer():
        tracing.disarm()
        if prev_tracer is not None:
            tracing.arm(prev_tracer)

    try:
        replicas = [
            SubprocessReplica(
                str(i), ep_dir,
                slots=cfg.slots, max_len=cfg.max_len,
                prefill_chunk=cfg.prefill_chunk,
                paged=True, block_size=cfg.block_size,
                role="prefill" if i == 0 else "decode",
                # Generation 0 only: post-restart generations run
                # clean so re-admission probes can succeed.
                schedule_path=(
                    [schedule_paths[str(i)]]
                    if str(i) in schedule_paths else ""
                ),
            )
            for i in range(2)
        ]
        router = FleetRouter(
            replicas,
            RouterConfig(
                max_retries=3,
                seed=ep_seed,
                # Short enough that the killed import's pending entry
                # is pruned (reason="timeout") within the episode.
                migration_timeout_s=2.0,
                health=HealthPolicy(
                    heartbeat_timeout_s=2.0,
                    probe_cooldown_s=0.5,
                    probe_successes=2,
                ),
            ),
            registry=registry,
        )
    except BaseException:
        _restore_tracer()
        raise
    if runner_schedule is not None:
        arm(runner_schedule)

    health_seen = {rid: set() for rid in router._replicas}  # noqa: SLF001

    def note_health():
        for rid in health_seen:
            health_seen[rid].add(router.health_state(rid))

    def migrations_ok() -> int:
        return int(registry.get("fleet_migrations_total").value())

    t_start = time.time()
    deadline = t_start + cfg.watchdog_s
    accepted: List = []
    failure: Optional[str] = None
    vocab_hi = 100
    try:
        router.start(timeout_s=min(120.0, cfg.watchdog_s))
        to_submit = [
            (
                [rng.randint(1, vocab_hi) for _ in
                 range(rng.randint(4, 10))],
                cfg.new_tokens_long if rng.random() < 0.5
                else cfg.new_tokens_short,
            )
            for _ in range(cfg.requests)
        ]
        while to_submit or router.pending():
            if time.time() > deadline:
                failure = "watchdog: migration episode deadline exceeded"
                break
            if to_submit:
                prompt, new = to_submit.pop(0)
                accepted.append(router.submit(prompt, new))
            router.step()
            note_health()
            time.sleep(0.005)
        # Recovery half: keep feeding prompts (they prefill on replica
        # 0 and try to migrate) until the victim's breaker closes AND
        # a post-kill migration has actually succeeded — migration
        # traffic is the decode tier's probe path.
        while not failure and (
            router.health_state(victim) != HEALTHY or migrations_ok() < 1
        ):
            if time.time() > deadline:
                failure = (
                    f"watchdog: victim {victim} never re-admitted via "
                    f"migration probes (state "
                    f"{router.health_state(victim)}, "
                    f"migrations_ok={migrations_ok()})"
                )
                break
            if router.pending() == 0:
                accepted.append(router.submit(
                    [rng.randint(1, vocab_hi) for _ in range(5)],
                    cfg.new_tokens_short,
                ))
            router.step()
            note_health()
            time.sleep(0.005)
        if not failure:
            try:
                router.run_until_idle(
                    timeout_s=max(1.0, deadline - time.time())
                )
            except TimeoutError as e:
                failure = f"watchdog: {e}"
    finally:
        if runner_schedule is not None:
            disarm()
        router.stop()
        _restore_tracer()

    wall = time.time() - t_start
    report: Dict = {
        "episode": episode,
        "seed": seed,
        "kind": "kill_during_migration",
        "wall_s": round(wall, 3),
        "victim": victim,
        "requests": len(accepted),
    }
    import glob as glob_lib

    episode_spans = tracing.load_spans(
        [router_sink]
        + sorted(glob_lib.glob(
            os.path.join(ep_dir, "spans_replica*.jsonl")
        ))
    )
    try:
        if failure:
            raise SoakInvariantError(failure)
        _check_fleet_invariant(
            accepted, router, registry, victim, health_seen
        )
        kv_final = _check_block_reclaim(replicas, victim)
        report["kv_blocks"] = kv_final
        # §36 phase-sum law on REAL migrated requests: queue + prefill
        # + migrate + decode ≈ e2e, and at least one verified tree
        # must actually carry the migrate phase — this episode is the
        # one place migrations are guaranteed to have happened.
        report["trace"] = _check_trace_invariant(
            episode_spans,
            require_reroute=registry.get(
                "fleet_reroutes_total"
            ).value() >= 1,
            require_migrate=True,
        )
        # Migration-specific law: the kill window fired (the victim's
        # fault trace says so), the orphaned import was accounted as a
        # failure (timeout or send-error — never a silent loss), and a
        # migration completed end-to-end afterwards.
        fault_trace = _read_trace(
            os.path.join(ep_dir, f"trace_replica{victim}.jsonl"),
            f"replica{victim}",
        )
        fired = [
            t for t in fault_trace
            if t.get("point") == "fleet.replica.import"
            and t.get("action") == "crash"
        ]
        if not fired:
            raise SoakInvariantError(
                "kill_during_migration: the import-window SIGKILL "
                "never fired — the episode tested nothing"
            )
        fails = sum(
            v for _n, _l, v in registry.get(
                "fleet_migration_failures_total"
            ).samples()
        )
        if fails < 1:
            raise SoakInvariantError(
                "destination died holding an unacked import but no "
                "migration failure was recorded"
            )
        if migrations_ok() < 1:
            raise SoakInvariantError(
                "no migration succeeded after the victim's restart"
            )
    except SoakInvariantError as e:
        dest = _dump_artifacts(
            ep_dir, artifact_dir, schedules, seed, episode, str(e)
        )
        logger.error(
            "MIGRATION EPISODE FAILED: %s\n  artifacts: %s", e, dest
        )
        raise
    results = [r.result for r in accepted if r.result is not None]
    completed = [r for r in results if r.ok]
    report.update({
        "productive_step_s": round(sum(
            r.latency_s or 0.0 for r in completed
        ), 3),
        "goodput_frac": round(
            len(completed) / max(len(results), 1), 4
        ),
        "completed": len(completed),
        "failed": len(results) - len(completed),
        "migrations": migrations_ok(),
        "migration_failures": int(sum(
            v for _n, _l, v in registry.get(
                "fleet_migration_failures_total"
            ).samples()
        )),
        "restarts": int(
            registry.get("fleet_replica_restarts_total").value()
        ),
        "duplicates": int(
            registry.get("fleet_duplicate_completions_total").value()
        ),
        "deaths": 1,
        "recovery_s": [],
        "steps_unique": len(completed),
        "steps_executed": len(results),
        "faults": [
            t
            for rid in schedules
            for t in _read_trace(
                os.path.join(ep_dir, f"trace_replica{rid}.jsonl"),
                f"replica{rid}",
            )
        ],
    })
    if not cfg.keep_artifacts_on_success:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return report


def _check_trace_invariant(spans, require_reroute: bool,
                           require_migrate: bool = False) -> Dict:
    """The §29 trace proof: (a) a rerouted request's tree shows the
    failed attempt and the retry as SIBLING spans under one
    fleet.request root; (b) the lifecycle child spans — queue-wait +
    prefill (+ migrate, when the fleet moved the request's KV between
    tiers, §36) + decode — sum to the serving.request e2e duration
    within 10%: the phases TILE the request, so the migrate row in
    ``trace_query.py --serving`` is an honest share of request time,
    not an overlap artifact. With ``require_migrate`` at least one
    phase-sum-verified tree must carry a ``serving.migrate`` child."""
    rerouted = 0
    migrate_checked = 0
    for tree in tracing.build_trees(spans):
        if tree.get("name") != "fleet.request":
            continue
        attempts = [
            c for c in tree["children"] if c.get("name") == "fleet.attempt"
        ]
        failed = [a for a in attempts if a.get("status") == "error"]
        won = [a for a in attempts if a.get("status") == "ok"]
        if len(attempts) >= 2 and failed and won:
            rerouted += 1
    if require_reroute and rerouted == 0:
        raise SoakInvariantError(
            "requests were rerouted but no trace tree shows a failed "
            "attempt and a retry as sibling spans"
        )
    checked = 0
    for record in spans:
        if record.get("name") != "serving.request":
            continue
        if record.get("status") != "ok" or not record.get("dur_s"):
            continue
        children = [
            s for s in spans
            if s.get("parent_id") == record.get("span_id")
            and s.get("dur_s") is not None
        ]
        if len(children) < 3:
            continue  # shed/failed partial trees don't carry all phases
        phase_sum = sum(s["dur_s"] for s in children)
        e2e = record["dur_s"]
        if abs(phase_sum - e2e) > max(0.1 * e2e, 0.005):
            raise SoakInvariantError(
                f"trace {record.get('trace_id')}: queue-wait + prefill "
                f"(+ migrate) + decode sum {phase_sum:.4f}s vs e2e "
                f"{e2e:.4f}s — phases no longer partition the request"
            )
        checked += 1
        if any(s.get("name") == "serving.migrate" for s in children):
            migrate_checked += 1
    if checked == 0:
        raise SoakInvariantError(
            "no completed serving.request span carried its full "
            "queue-wait/prefill/decode phase tree"
        )
    if require_migrate and migrate_checked == 0:
        raise SoakInvariantError(
            "migrations ran but no phase-sum-verified serving.request "
            "tree carries a serving.migrate child span"
        )
    return {
        "rerouted_trees": rerouted,
        "phase_sum_checked": checked,
        "migrate_phase_checked": migrate_checked,
    }


def _check_block_reclaim(replicas, victim) -> Dict:
    """The §31 block-reclaim invariant: every paged replica reported
    allocator stats, none EVER violated conservation (free+used+cached
    == managed pool, checked at each heartbeat's receipt) or went
    refcount-negative — including the victim across its SIGKILL and
    restart, whose post-restart generations must report again."""
    final: Dict = {}
    for replica in replicas:
        rid = replica.replica_id
        if replica.kv_violation is not None:
            raise SoakInvariantError(
                f"block-reclaim invariant violated: "
                f"{replica.kv_violation}"
            )
        kv = replica.last_kv
        if not kv:
            raise SoakInvariantError(
                f"paged replica {rid} never reported allocator stats "
                f"on its heartbeats"
            )
        final[rid] = {
            k: kv.get(k) for k in ("total", "free", "used", "cached")
        }
        if kv["free"] + kv["used"] + kv["cached"] != kv["total"]:
            raise SoakInvariantError(
                f"replica {rid} final block accounting broken: {kv}"
            )
    # The victim respawned at least once: its reporting generation is
    # post-kill, so a leak across the crash would have surfaced either
    # as a survivor's violation (rerouted work) or a missing report.
    if victim not in final:
        raise SoakInvariantError(
            f"victim replica {victim} has no final allocator stats"
        )
    return final


def _check_fleet_invariant(accepted, router, registry, victim,
                           health_seen):
    """Every accepted request: exactly one terminal result; victim
    walked BROKEN -> HALF_OPEN -> HEALTHY; the fault actually fired."""
    silent = [
        r.request_id for r in accepted
        if r.accepted and r.result is None
    ]
    if silent:
        raise SoakInvariantError(
            f"fleet requests neither completed nor explicitly failed: "
            f"{silent}"
        )
    # Exactly-once is structural (one result slot per request_id); what
    # can drift is a completion recorded twice into metrics. Cross-check
    # the counters: completed + failed == terminal results.
    results = [r.result for r in accepted if r.result is not None]
    ok = sum(1 for r in results if r.ok)
    failed = sum(1 for r in results if not r.ok)
    m_completed = registry.get("fleet_requests_total").value(
        outcome="completed"
    )
    m_failed = registry.get("fleet_requests_total").value(
        outcome="failed"
    )
    m_shed = registry.get("fleet_requests_total").value(outcome="shed")
    if m_completed != ok or m_failed + m_shed != failed:
        raise SoakInvariantError(
            f"completion accounting drift: results ok={ok} "
            f"failed={failed} vs metrics completed={m_completed} "
            f"failed={m_failed} shed={m_shed} — a duplicate or lost "
            f"record"
        )
    for r in results:
        if not r.ok and not r.failure_reason:
            raise SoakInvariantError(
                f"request {r.request_id} failed without a "
                f"machine-readable reason"
            )
    seen = health_seen[victim]
    if BROKEN not in seen:
        raise SoakInvariantError(
            f"victim replica {victim} was never marked broken "
            f"(states seen: {sorted(seen)})"
        )
    if HALF_OPEN not in seen:
        raise SoakInvariantError(
            f"victim replica {victim} never reached half_open probes "
            f"(states seen: {sorted(seen)})"
        )
    if router.health_state(victim) != HEALTHY:
        raise SoakInvariantError(
            f"victim replica {victim} not re-admitted: "
            f"{router.health_state(victim)}"
        )
    if registry.get("fleet_replica_restarts_total").value() < 1:
        raise SoakInvariantError("victim replica was never restarted")


def _dump_artifacts(ep_dir, artifact_dir, schedules, seed, episode,
                    reason) -> str:
    import glob
    import json

    os.makedirs(artifact_dir, exist_ok=True)
    dest = os.path.join(artifact_dir, f"fleet_seed{seed}_ep{episode}")
    shutil.rmtree(dest, ignore_errors=True)
    os.makedirs(dest, exist_ok=True)
    for src in glob.glob(os.path.join(ep_dir, "replica*_gen*.log")):
        shutil.copy(src, dest)
    for src in glob.glob(os.path.join(ep_dir, "trace_replica*.jsonl")):
        shutil.copy(src, dest)
    for src in glob.glob(os.path.join(ep_dir, "spans_*.jsonl")):
        shutil.copy(src, dest)
    for rid, sched in schedules.items():
        with open(
            os.path.join(dest, f"schedule_replica{rid}.json"), "w"
        ) as f:
            f.write(sched.to_json())
    with open(os.path.join(dest, "failure.json"), "w") as f:
        json.dump({
            "seed": seed, "episode": episode,
            "kind": "replica_kill_reroute", "reason": reason,
        }, f, indent=2)
    return dest
