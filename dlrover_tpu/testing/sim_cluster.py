"""Simulated cluster backend: multi-node master testing without a cluster.

Parity: reference dlrover/python/testing/ (sim_master_main.py:14-50,
sim_stubs.py SimScaler/SimNodeWatcher) — the pattern for exercising the
full DistributedJobMaster (scale plans, pod events, relaunch, chaos) on
one host. The simulator adds fault injection used by goodput tests:
``fail_node`` / ``preempt_node`` / ``break_node``.

A sim node moves Pending -> Running after ``schedule_delay_s`` unless a
scheduling blackout is configured (to exercise pending-timeout paths).
"""

import itertools
import queue
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeEvent
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base_watcher import NodeWatcher


class SimCluster:
    """In-memory "cloud": holds sim nodes, emits watch events."""

    def __init__(self, schedule_delay_s: float = 0.0):
        self._lock = threading.RLock()
        self._nodes: Dict[int, Node] = {}
        self._events: "queue.Queue[Optional[NodeEvent]]" = queue.Queue()
        self._id_iter = itertools.count(0)
        self.schedule_delay_s = schedule_delay_s
        self.schedulable = True  # False simulates a full cluster

    # ---- backend surface used by scaler/watcher ---------------------------

    def next_node_id(self) -> int:
        with self._lock:
            return next(self._id_iter)

    def create_node(self, node: Node):
        # Own a private copy: the caller (job manager) keeps its record and
        # must learn of changes only through watch events, like a real
        # cluster API.
        node = self._copy(node)
        with self._lock:
            node.status = NodeStatus.PENDING
            node.create_time = time.time()
            self._nodes[node.id] = node
        self._emit(NodeEventType.ADDED, node)
        if self.schedulable:
            if self.schedule_delay_s > 0:
                threading.Timer(
                    self.schedule_delay_s, self._schedule, args=(node.id,)
                ).start()
            else:
                self._schedule(node.id)

    def remove_node(self, node_id: int):
        with self._lock:
            node = self._nodes.pop(node_id, None)
        if node is not None:
            node.status = NodeStatus.DELETED
            self._emit(NodeEventType.DELETED, node)

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return [self._copy(n) for n in self._nodes.values()]

    def events(self):
        return self._events

    def close(self):
        self._events.put(None)

    # ---- fault injection (chaos) ------------------------------------------

    def fail_node(self, node_id: int, exit_reason: str = NodeExitReason.KILLED):
        """Worker process crash (OOM, segfault, kill -9 ...)."""
        self._finish(node_id, NodeStatus.FAILED, exit_reason)

    def preempt_node(self, node_id: int):
        """Cloud preemption / spot reclaim of the host."""
        self._finish(node_id, NodeStatus.DELETED, NodeExitReason.PREEMPTED)

    def break_node(self, node_id: int):
        """Hardware fault: node must be replaced, not restarted."""
        self._finish(node_id, NodeStatus.FAILED, NodeExitReason.HARDWARE_ERROR)

    def succeed_node(self, node_id: int):
        self._finish(node_id, NodeStatus.SUCCEEDED, NodeExitReason.SUCCEEDED)

    # ---- internals ---------------------------------------------------------

    def _schedule(self, node_id: int):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or node.status != NodeStatus.PENDING:
                return
            node.status = NodeStatus.RUNNING
            node.host_name = f"sim-host-{node_id}"
            node.host_ip = f"10.0.0.{node_id % 250 + 1}"
        self._emit(NodeEventType.MODIFIED, node)

    def _finish(self, node_id: int, status: str, exit_reason: str):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            node.status = status
            node.exit_reason = exit_reason
        self._emit(NodeEventType.MODIFIED, node)

    def _copy(self, node: Node) -> Node:
        clone = Node(
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            name=node.name,
            host_name=node.host_name,
            host_ip=node.host_ip,
            status=node.status,
            config_resource=node.config_resource,
        )
        clone.exit_reason = node.exit_reason
        clone.relaunch_count = node.relaunch_count
        return clone

    def _emit(self, event_type: str, node: Node):
        self._events.put(NodeEvent(event_type, self._copy(node)))


class SimScaler(Scaler):
    """Scaler over the in-memory cluster (reference sim_stubs.SimScaler)."""

    def __init__(self, job_name: str, cluster: SimCluster):
        super().__init__(job_name)
        self._cluster = cluster

    def scale(self, plan: ScalePlan):
        with self._lock:
            for group_name, group in plan.node_group_resources.items():
                self._scale_group(group_name, group)
            for node in plan.launch_nodes:
                self._cluster.create_node(node)
            for node in plan.remove_nodes:
                self._cluster.remove_node(node.id)

    def _scale_group(self, node_type: str, group):
        alive = [
            n
            for n in self._cluster.list_nodes()
            if n.type == node_type and n.status not in NodeStatus.end_states()
        ]
        delta = group.count - len(alive)
        if delta > 0:
            used_ranks = {n.rank_index for n in alive}
            rank = 0
            for _ in range(delta):
                while rank in used_ranks:
                    rank += 1
                used_ranks.add(rank)
                node_id = self._cluster.next_node_id()
                self._cluster.create_node(
                    Node(
                        node_type,
                        node_id,
                        rank_index=rank,
                        config_resource=group.node_resource,
                    )
                )
        elif delta < 0:
            for node in sorted(alive, key=lambda n: -n.rank_index)[:-delta]:
                logger.info("sim scale-down removes node %d", node.id)
                self._cluster.remove_node(node.id)


class SimNodeWatcher(NodeWatcher):
    """Watcher over the in-memory cluster (reference sim_stubs)."""

    def __init__(self, job_name: str, cluster: SimCluster):
        super().__init__(job_name)
        self._cluster = cluster
        self._stopped = False

    def watch(self):
        events = self._cluster.events()
        while not self._stopped:
            event = events.get()
            if event is None:
                return
            yield event

    def list(self) -> List[Node]:
        return self._cluster.list_nodes()

    def stop(self):
        self._stopped = True
        self._cluster.close()
