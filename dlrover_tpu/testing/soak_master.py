"""Chaos-soak control-plane master: one crash-restartable generation.

Spawned (and re-spawned after the injected SIGKILL) by the
``master_kill`` episode (:mod:`dlrover_tpu.testing.master_kill_soak`).
Each generation runs the REAL master-side stack as its own process:

- :class:`MasterJournal` (append-only fsynced WAL, DESIGN.md §37) at a
  path that survives the process — generation 1 rehydrates the task
  ledger, kv store and epoch from generation 0's journal;
- :class:`MasterServicer` over the HTTP transport, stamping the
  journal's ``master_epoch`` into every reply (worker-side fencing);
- the ``master.journal.write`` fault point armed from the environment —
  a ``crash`` rule there SIGKILLs this process after a dispatch became
  durable but BEFORE the reply left, the canonical crash window;
- SIGTERM → :meth:`HttpMasterServer.graceful_stop` (drain in-flight,
  flush+close the journal) so the clean-shutdown path is exercised too.

A ready file (atomic replace) publishes ``{port, pid, epoch}`` once the
server accepts connections, so the harness knows both when the master
is up and which incarnation answered.
"""

import argparse
import json
import os
import threading
import time


def _write_ready(path: str, payload: dict):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="chaos soak master")
    parser.add_argument("--port", type=int, default=0,
                        help="0 = ephemeral (published via --ready-file)")
    parser.add_argument("--journal", required=True,
                        help="durable journal path, shared across "
                        "generations")
    parser.add_argument("--ready-file", required=True)
    parser.add_argument("--task-timeout", type=float, default=2.0)
    args = parser.parse_args(argv)

    from dlrover_tpu.fault import arm_from_env

    arm_from_env()

    from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
    from dlrover_tpu.master.elastic_training.sync_service import SyncService
    from dlrover_tpu.master.journal import MasterJournal, restore_master_state
    from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.shard.task_manager import TaskManager
    from dlrover_tpu.rpc.transport import HttpMasterServer

    task_manager = TaskManager(task_timeout=args.task_timeout)
    kv_store = KVStoreService()
    sync_service = SyncService()
    journal = MasterJournal(args.journal)
    # BEFORE the servicer: its replica-token seed check must see the
    # restored token, not journal a fresh one (DESIGN.md §37).
    restore_master_state(
        journal.recovered,
        task_manager=task_manager,
        kv_store=kv_store,
        sync_service=sync_service,
    )
    servicer = MasterServicer(
        rdzv_managers={},
        task_manager=task_manager,
        perf_monitor=PerfMonitor(),
        sync_service=sync_service,
        kv_store=kv_store,
        journal=journal,
    )
    server = HttpMasterServer(args.port, servicer)
    stop = threading.Event()
    server.add_shutdown_hook(journal.close)
    server.add_shutdown_hook(stop.set)
    server.install_sigterm_handler(drain_s=5.0)
    server.start()
    _write_ready(args.ready_file, {
        "port": server.port,
        "pid": os.getpid(),
        "epoch": journal.master_epoch,
        "t": time.time(),
    })

    # Supervision loop: lease-timeout recovery is the mechanism that
    # requeues shards journaled-as-dispatched whose reply died with the
    # previous incarnation (the worker never saw them, so no done-report
    # ever comes).
    while not stop.is_set():
        for mgr in list(task_manager._datasets.values()):  # noqa: SLF001
            mgr.recover_timeout_tasks(args.task_timeout)
        stop.wait(0.5)
    task_manager.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
