"""Live-rescale soak worker: survives N→M world changes in-process.

Spawned by :mod:`dlrover_tpu.testing.rescale_soak`. Unlike the PR-5
crash-restart soak worker, this process is built to NEVER exit across a
world change: it runs the full worker-side rescale protocol
(docs/DESIGN.md §27) against the master's :class:`RescaleCoordinator` —

- plan poll → pause the ShardingClient prefetcher (force-flushing
  done-reports) → "barrier" ack/wait;
- restore EXACTLY its new addressable byte ranges of the sharded
  leaves (params ``w`` AND optimizer ``opt``) at the plan's
  restore_step through :func:`flash_ckpt.engine.load_state_regions`,
  then allgather peers' ranges over the master KV store (the simulated
  interconnect) to rebuild its replica;
- the designated (lowest) rank rewinds the master's dataset cursor to
  the restored checkpoint's shard snapshot — so shards consumed after
  the restore step are re-dispatched exactly once;
- "restored" barrier → ``trainer.rescale(new_dp)`` /
  ``sampler.rescale(rank, world)`` → prefetcher resume → "resumed" ack
  (passing the ``rescale.resume.first_step`` kill window).

The model state is all-integer and order-independent: workers exchange
per-step shard contributions through the KV store and apply the summed
"gradient" identically, so every replica of the state is a pure
function of the SET of consumed shards — after any fault/rescale
sequence the state is bit-identical to a single-host reference run over
the same consumed set, which is what the harness asserts.
"""

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

VEC_LEN = 64
HIST_BUCKETS = 32

# Leaf ids of the sorted-key state pytree {"hist", "opt", "sum", "w"}.
LEAF_HIST, LEAF_OPT, LEAF_SUM, LEAF_W = 0, 1, 2, 3

EXIT_OK = 0
EXIT_INTEGRITY = 3
EXIT_EVICTED = 0  # eviction is a clean, expected exit


def fresh_state(vec_len: int = VEC_LEN) -> Dict[str, np.ndarray]:
    return {
        "hist": np.zeros(HIST_BUCKETS, np.int64),
        "opt": np.zeros(vec_len, np.int64),
        "sum": np.zeros((), np.int64),
        "w": np.zeros(vec_len, np.int64),
    }


def shard_contribution(start: int, end: int, vec_len: int = VEC_LEN):
    """Order-independent integer contribution of records [start, end)."""
    idxs = np.arange(start, end, dtype=np.int64)
    vec = np.zeros(vec_len, np.int64)
    np.add.at(vec, idxs % vec_len, idxs + 1)
    hist = np.zeros(HIST_BUCKETS, np.int64)
    np.add.at(hist, idxs % HIST_BUCKETS, 1)
    return {"vec": vec, "sum": int(idxs.sum()), "hist": hist}


def apply_contribution(state: Dict[str, np.ndarray], c):
    state["w"] += c["vec"]
    state["opt"] += 3 * c["vec"]  # "optimizer" leaf: distinct content
    state["sum"] += c["sum"]
    state["hist"] += c["hist"]


def reference_state(
    dataset_size: int,
    consumed_ranges: List[Tuple[int, int]],
    vec_len: int = VEC_LEN,
) -> Dict[str, np.ndarray]:
    """Single-host reference: the state after consuming exactly
    ``consumed_ranges``, each once. Integer leaves make this bit-exact
    regardless of consumption order or world-size trajectory."""
    state = fresh_state(vec_len)
    for start, end in consumed_ranges:
        apply_contribution(state, shard_contribution(start, end, vec_len))
    return state


def world_lcm(world: int) -> int:
    """lcm(1..world): the grad-accum multiplier making every dp size up
    to ``world`` divide the global batch."""
    import math

    return math.lcm(*range(1, max(world, 1) + 1))


def block_bounds(rank_index: int, world: int, vec_len: int):
    """Contiguous row block rank ``rank_index`` of ``world`` owns."""
    lo = rank_index * vec_len // world
    hi = (rank_index + 1) * vec_len // world
    return lo, hi


def _encode(payload: dict) -> bytes:
    def default(o):
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.integer):
            return int(o)
        raise TypeError(type(o).__name__)

    return json.dumps(payload, default=default).encode()


def _decode(raw: bytes) -> dict:
    return json.loads(raw.decode())


class _Aborted(Exception):
    """A newer plan arrived while gathering — restart the loop on it."""

    def __init__(self, plan):
        super().__init__(f"superseded by plan {plan.plan_id}")
        self.plan = plan


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="live rescale worker")
    parser.add_argument("--master-addr", required=True)
    parser.add_argument("--rank", type=int, required=True)
    parser.add_argument("--world", type=int, required=True,
                        help="bootstrap world size (env NUM_PROCESSES)")
    parser.add_argument("--dataset", default="rescale")
    parser.add_argument("--dataset-size", type=int, required=True)
    parser.add_argument("--shard-size", type=int, default=16)
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--ckpt-every", type=int, default=2)
    parser.add_argument("--events", required=True)
    parser.add_argument("--generation", type=int, default=0)
    parser.add_argument("--vec-len", type=int, default=VEC_LEN)
    parser.add_argument("--step-ms", type=float, default=0.0)
    parser.add_argument("--deadline-s", type=float, default=300.0)
    args = parser.parse_args(argv)

    # Identity env BEFORE any framework import touches the runtime
    # context: the shm segment name keys on NODE_RANK, the checkpoint
    # proc files on PROCESS_ID.
    os.environ["DLROVER_TPU_NODE_RANK"] = str(args.rank)
    os.environ["DLROVER_TPU_PROCESS_ID"] = str(args.rank)
    os.environ["DLROVER_TPU_NUM_PROCESSES"] = str(args.world)
    os.environ["DLROVER_TPU_NODE_RANKS"] = ",".join(
        str(r) for r in range(args.world)
    )

    from dlrover_tpu.fault import arm_from_env

    arm_from_env()

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.flash_ckpt import engine as engine_mod
    from dlrover_tpu.flash_ckpt.engine import CheckpointEngine
    from dlrover_tpu.testing.soak_worker import EventLog, state_crc
    from dlrover_tpu.trainer.elastic.rescale import (
        BARRIER_READY,
        RescaleClient,
    )
    from dlrover_tpu.trainer.elastic.sampler import (
        ElasticDistributedSampler,
    )
    from dlrover_tpu.trainer.elastic.sharding_client import ShardingClient
    from dlrover_tpu.trainer.elastic.trainer import (
        ElasticBatchConfig,
        ElasticTrainer,
    )
    from dlrover_tpu.trainer.runtime import get_context

    vec_len = args.vec_len
    deadline = time.monotonic() + args.deadline_s
    events = EventLog(args.events)
    events.append(kind="worker_start", rank=args.rank,
                  generation=args.generation, pid=os.getpid())

    client = MasterClient(
        args.master_addr, node_id=args.rank, kind="http", timeout=10.0
    )
    rescale = RescaleClient(client, args.rank, poll_interval_s=0.02)
    engine = CheckpointEngine(args.ckpt_dir, standalone=True)
    ctx = get_context()
    ctx.process_id = args.rank

    batch_config = ElasticBatchConfig(
        # Every dp size 1..world must be legal so any scale-down world
        # can form: global = shard * lcm(1..world).
        global_batch_size=args.shard_size * world_lcm(args.world),
        micro_batch_per_device=args.shard_size,
    )
    trainer = ElasticTrainer(batch_config, dp_size=1, master_client=client,
                             report_interval_s=1.0)
    sampler = ElasticDistributedSampler(
        args.dataset_size, rank=0, world_size=1, shuffle=False
    )
    state = fresh_state(vec_len)
    step = 0
    sharding: Optional[ShardingClient] = None
    plan = None
    my_index = 0

    def kv_gather(keys: List[str], current_plan):
        """Poll the KV store until every key is set; abort to a newer
        plan the moment one is broadcast (a dead peer would otherwise
        wedge the gather forever)."""
        last_plan_poll = 0.0
        while True:
            values = client.kv_store_multi_get(keys)
            if len(values) >= len(keys):
                return values
            now = time.monotonic()
            if now - last_plan_poll > 0.1:
                last_plan_poll = now
                newer = rescale.poll_plan(current_plan.plan_id)
                if newer is not None:
                    raise _Aborted(newer)
            if now > deadline:
                raise TimeoutError("worker deadline during kv gather")
            time.sleep(0.02)

    def make_sharding_client() -> ShardingClient:
        return ShardingClient(
            client,
            dataset_name=args.dataset,
            dataset_size=args.dataset_size,
            shard_size=args.shard_size,
            prefetch_depth=4,
            fetch_batch=2,
            report_batch=2,
            report_interval_s=0.2,
            wait_backoff_s=0.05,
            wait_backoff_max_s=0.3,
        )

    def restore_at(plan_view):
        """Partial-restore this rank's NEW byte ranges at the plan step,
        allgather peers' ranges over KV, rebuild the full replica."""
        nonlocal state, step
        world = plan_view.world_size
        k = plan_view.new_rank_index(args.rank)
        lo, hi = block_bounds(k, world, vec_len)
        t0 = time.monotonic()
        result = engine_mod.load_state_regions(
            args.ckpt_dir,
            plan_view.restore_step,
            regions_by_leaf={
                LEAF_OPT: [((lo, hi),)],
                LEAF_W: [((lo, hi),)],
            },
        )
        if result is None:
            events.append(kind="restore_failed", step=plan_view.restore_step,
                          plan=plan_view.plan_id)
            print("partial restore failed", file=sys.stderr)
            sys.exit(EXIT_INTEGRITY)
        _, leaves, user_meta = result
        read_bytes = sum(
            arr.nbytes for regions in leaves.values()
            for arr in regions.values()
        )
        # Publish my block; gather everyone's — the KV store plays the
        # interconnect for the replica rebuild.
        client.kv_store_set(
            f"resh/{plan_view.plan_id}/{args.rank}",
            _encode({
                "lo": lo, "hi": hi,
                "w": leaves[LEAF_W][((lo, hi),)],
                "opt": leaves[LEAF_OPT][((lo, hi),)],
            }),
        )
        keys = [
            f"resh/{plan_view.plan_id}/{r}" for r in plan_view.rank_order
        ]
        values = kv_gather(keys, plan_view)
        new_state = fresh_state(vec_len)
        new_state["hist"] = leaves[LEAF_HIST][((0, HIST_BUCKETS),)].copy()
        new_state["sum"] = leaves[LEAF_SUM][()].copy()
        for key in keys:
            block = _decode(values[key])
            new_state["w"][block["lo"]:block["hi"]] = np.asarray(
                block["w"], np.int64
            )
            new_state["opt"][block["lo"]:block["hi"]] = np.asarray(
                block["opt"], np.int64
            )
        crc = state_crc(new_state)
        want = user_meta.get("state_crc")
        if crc != want:
            events.append(
                kind="restore_crc_mismatch", step=plan_view.restore_step,
                got=crc, want=want, plan=plan_view.plan_id,
            )
            print("restored state failed integrity check", file=sys.stderr)
            sys.exit(EXIT_INTEGRITY)
        state = new_state
        step = plan_view.restore_step
        if "sampler" in user_meta:
            sampler.load_state_dict(user_meta["sampler"])
        events.append(
            kind="restore", step=step, crc=crc, plan=plan_view.plan_id,
            generation=args.generation, bytes_read=read_bytes,
            block=[lo, hi], source="storage_partial",
        )
        return user_meta

    def adopt_plan(new_plan):
        """Run the full worker-side rescale protocol for ``new_plan``.
        Returns the plan actually adopted (a barrier may surface an even
        newer one) or exits if this rank was evicted."""
        nonlocal plan, sharding, my_index, step
        while True:
            t_seen = time.monotonic()
            if not new_plan.includes(args.rank):
                if sharding is not None:
                    sharding.pause_for_rescale()
                events.append(kind="evicted", plan=new_plan.plan_id,
                              rank=args.rank)
                engine.close()
                sys.exit(EXIT_EVICTED)
            if sharding is not None:
                sharding.pause_for_rescale()
            rescale.ack(new_plan.plan_id, "barrier")
            outcome = rescale.wait_barrier(
                new_plan.plan_id, "barrier",
                timeout_s=new_plan.barrier_timeout_s + 15.0,
            )
            if outcome != BARRIER_READY:
                # An expiry may find NO legal replacement world — the
                # coordinator then holds the expired plan until a rejoin
                # restores legality (docs/DESIGN.md §27). Dying here
                # would take the whole job down exactly when the
                # protocol says to wait; keep polling for the
                # superseding plan — the soak watchdog bounds us.
                got = None
                while got is None:
                    got = rescale.wait_for_plan(
                        new_plan.plan_id, timeout_s=30.0
                    )
                new_plan = got
                continue
            t_barrier = time.monotonic()
            # Adopt the new world in the runtime context so checkpoint
            # persist/commit expects exactly the new membership.
            ctx.num_processes = new_plan.world_size
            ctx.node_ranks = tuple(new_plan.rank_order)
            my_index = new_plan.new_rank_index(args.rank)
            designated = args.rank == min(new_plan.world)
            try:
                if new_plan.restore_step >= 0:
                    user_meta = restore_at(new_plan)
                    if sharding is None:
                        sharding = make_sharding_client()
                    if designated:
                        # Rewind the master's dataset cursor to the shard
                        # snapshot matching the restored state: shards
                        # consumed after the restore step are re-queued,
                        # shards done before it never replay.
                        sharding.restore_shard_checkpoint(
                            user_meta.get("shard_ckpt", "")
                        )
                else:
                    # Bootstrap: fresh state + an initial committed
                    # checkpoint so any later rescale has a (state,
                    # snapshot) pair to rewind to. EVERY rank saves —
                    # the commit leader waits for every node's shard
                    # marker before advancing the tracker.
                    if sharding is None:
                        sharding = make_sharding_client()
                    if step == 0:
                        save_checkpoint(new_plan, bootstrap=True)
                t_restore = time.monotonic()
                rescale.ack(new_plan.plan_id, "restored")
                outcome = rescale.wait_barrier(
                    new_plan.plan_id, "restored",
                    timeout_s=new_plan.barrier_timeout_s + 15.0,
                )
            except _Aborted as a:
                new_plan = a.plan
                continue
            if outcome != BARRIER_READY:
                # Same as the 'barrier' phase above: an expiry with no
                # legal replacement world means WAIT for the rejoin
                # re-plan, not die — the watchdog bounds us.
                got = None
                while got is None:
                    got = rescale.wait_for_plan(
                        new_plan.plan_id, timeout_s=30.0
                    )
                new_plan = got
                continue
            trainer.rescale(new_plan.world_size)
            sampler.rescale(my_index, new_plan.world_size)
            if sharding is not None:
                sharding.resume_after_rescale()
            plan = new_plan
            # Ledger entry BEFORE the resume ack: a kill in the
            # restore-to-first-step window must not erase the evidence
            # that the rescale itself completed.
            events.append(
                kind="rescale", plan=new_plan.plan_id,
                world=list(new_plan.rank_order),
                restore_step=new_plan.restore_step,
                reason=new_plan.reason,
                plan_created_at=new_plan.created_at,
                barrier_s=round(t_barrier - t_seen, 4),
                restore_s=round(t_restore - t_barrier, 4),
                total_s=round(time.monotonic() - t_seen, 4),
                generation=args.generation,
            )
            rescale.mark_resumed(new_plan.plan_id)
            return plan

    def save_checkpoint(plan_view, bootstrap=False):
        """Lockstep cadence save: all ranks flush, agree via KV, the
        designated rank snapshots the shard cursor, everyone persists
        the SAME step and the leader commits."""
        designated = args.rank == min(plan_view.world)
        if not bootstrap:
            flushed_ok = True
            try:
                sharding.flush_reports()
                with sharding._report_lock:  # noqa: SLF001
                    flushed_ok = not (
                        sharding._pending_done or sharding._pending_failed
                    )
            except Exception:
                flushed_ok = False
            client.kv_store_set(
                f"ckok/{plan_view.plan_id}/{step}/{args.rank}",
                b"1" if flushed_ok else b"0",
            )
            values = kv_gather(
                [
                    f"ckok/{plan_view.plan_id}/{step}/{r}"
                    for r in plan_view.rank_order
                ],
                plan_view,
            )
            if any(v != b"1" for v in values.values()):
                # Someone could not flush: refusing the checkpoint is
                # the correct degraded behavior (a snapshot over stale
                # accounting would bake a replay in). Retry next tick.
                events.append(kind="ckpt_refused", step=step,
                              plan=plan_view.plan_id)
                return
        if designated:
            snap = (
                sharding.get_shard_checkpoint() if sharding is not None
                else ""
            )
            client.kv_store_set(
                f"snap/{plan_view.plan_id}/{step}", _encode({"snap": snap})
            )
            values = {f"snap/{plan_view.plan_id}/{step}": _encode(
                {"snap": snap}
            )}
        else:
            values = kv_gather(
                [f"snap/{plan_view.plan_id}/{step}"], plan_view
            )
        snap = _decode(values[f"snap/{plan_view.plan_id}/{step}"])["snap"]
        crc = state_crc(state)
        engine.save_to_storage(
            step, state,
            user_meta={
                "state_crc": crc,
                "shard_ckpt": snap,
                "sampler": sampler.state_dict(),
            },
        )
        committed = engine._last_disk_step == step  # noqa: SLF001
        if designated and committed:
            client.report_ckpt_step(step, committed=True)
        events.append(kind="save", step=step, crc=crc, snapshot=snap,
                      committed=bool(committed), plan=plan_view.plan_id,
                      generation=args.generation)

    # ---- bootstrap ---------------------------------------------------------

    rescale.join(local_world_size=1)
    first = rescale.wait_for_plan(-1, timeout_s=60.0)
    if first is None:
        print("no rescale plan within 60s", file=sys.stderr)
        return 1
    try:
        adopt_plan(first)
    except _Aborted as a:
        adopt_plan(a.plan)
    trainer.global_step = step
    trainer.start_training()

    # ---- lockstep training loop -------------------------------------------

    it = 0
    while True:
        if time.monotonic() > deadline:
            print("worker deadline exceeded", file=sys.stderr)
            return 1
        newer = rescale.poll_plan(plan.plan_id)
        if newer is not None:
            try:
                adopt_plan(newer)
            except _Aborted as a:
                adopt_plan(a.plan)
            it = 0
            continue
        status, task = sharding.poll_task(timeout_s=0.1)
        if status == "task":
            payload = {
                "kind": "c",
                **shard_contribution(task.start, task.end, vec_len),
                "range": [task.start, task.end],
            }
        elif status == "end":
            payload = {"kind": "end"}
        else:
            payload = {"kind": "idle"}
        it += 1
        client.kv_store_set(
            f"ar/{plan.plan_id}/{it}/{args.rank}", _encode(payload)
        )
        try:
            values = kv_gather(
                [f"ar/{plan.plan_id}/{it}/{r}" for r in plan.rank_order],
                plan,
            )
        except _Aborted as a:
            adopt_plan(a.plan)
            it = 0
            continue
        contribs = [
            _decode(values[f"ar/{plan.plan_id}/{it}/{r}"])
            for r in plan.rank_order
        ]
        if all(c["kind"] == "end" for c in contribs):
            break
        applied = [c for c in contribs if c["kind"] == "c"]
        if not applied:
            time.sleep(0.02)
            continue
        t_step = time.time()
        records = 0
        for c in applied:
            apply_contribution(state, {
                "vec": np.asarray(c["vec"], np.int64),
                "sum": c["sum"],
                "hist": np.asarray(c["hist"], np.int64),
            })
            records += c["range"][1] - c["range"][0]
        if args.step_ms > 0:
            time.sleep(args.step_ms / 1e3)
        if status == "task":
            sharding.report_task_done(task)
        sampler.record_batch(records)
        trainer.global_step = step  # keep the crash-site step ctx exact
        trainer.step_completed(steps=1)
        step += 1
        events.append(
            kind="step", step=step, dur=time.time() - t_step,
            plan=plan.plan_id, world=len(plan.world),
            shards=[c["range"] for c in applied],
            generation=args.generation,
        )
        if step % max(args.ckpt_every, 1) == 0:
            try:
                save_checkpoint(plan)
            except _Aborted as a:
                adopt_plan(a.plan)
                it = 0
                continue

    sharding.stop()
    final = {
        "sum": int(state["sum"]),
        "hist": state["hist"].tolist(),
        "steps": step,
        "rank": args.rank,
        "generation": args.generation,
        "crc": state_crc(state),
        "plan": plan.plan_id,
        "world": len(plan.world),
    }
    events.append(kind="done", **final)
    engine.close()
    return EXIT_OK


if __name__ == "__main__":
    raise SystemExit(main())
