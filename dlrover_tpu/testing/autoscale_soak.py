"""Autoscaler chaos episode: static vs closed-loop under one schedule.

The ``straggler_evict`` episode kind (chaos soak episode 5) validates
the §30 closed-loop autoscaler the way the ROADMAP demands: the SAME
seeded fault + traffic schedule is run through a deterministic
sim-cluster training job three ways —

- **static**: fixed world, fixed serving fleet, fixed ckpt cadence
  (the baseline every resource brain is judged against);
- **dry_run**: the autoscaler watches and ledgers but actuates
  nothing (must behave exactly like static, with a populated ledger);
- **autoscaled**: the full loop — evict-and-replace the delayed
  straggler via a real ``ScalePlan`` against
  :class:`SimClusterScaler`, retune the flash-ckpt cadence from the
  OBSERVED MTBF (Young/Daly), grow/shrink the serving fleet through
  hysteresis bands as the traffic spike arrives and passes.

The sim job is a lockstep SPMD model over the REAL control plane: real
:class:`TaskManager` shard leases (crash recovery requeues them), real
:class:`PerfMonitor` per-rank step-time EWMAs feeding the §29
straggler report, the real fault plane (a persistent per-rank
``delay`` rule at the ``agent.worker.crash`` step fault point IS the
straggler; ``raise`` rules there are worker deaths), and the real
policy/ledger/actuator code paths. Wall time is real (sleeps), so the
goodput fractions are measured, not computed.

Invariants (docs/DESIGN.md §30):

1. the autoscaled run's goodput fraction STRICTLY beats the static
   run's;
2. the straggler is flagged, evicted and replaced within a bounded
   number of decision windows (time-to-mitigate reported);
3. every ledger decision carries the triggering signal snapshot and an
   explained outcome (no unexplained actions);
4. dry-run mode emits the same leading decision with ZERO actuations;
5. both runs drain the dataset exactly once (TaskManager accounting).

The §34 record→replay→perturb leg extends the episode: the autoscaled
run's signal stream is durably recorded (SignalRecorder), replayed
offline through the SAME PolicyConfig (must reproduce the live ledger
decision-for-decision — the replay identity invariant), and through a
PERTURBED config (must produce a differing, scored counterfactual
ledger). Two more invariants ride along: every actuated decision
carries a realized-outcome annotation, and the per-cause goodput
attribution explains ≥90% of the non-train wall time.
"""

import os
import random
import shutil
import tempfile
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from dlrover_tpu.autoscaler import (
    AutoScaler,
    CadenceController,
    CostModel,
    FaultHistory,
    PolicyConfig,
    ReplayMismatch,
    RulePolicy,
    SignalBus,
    SignalRecorder,
    TrainWorldActuator,
    assert_replay_identity,
    data_source,
    diff_ledgers,
    fault_source,
    load_recording,
    perf_source,
    replay_recording,
    score_ledger,
)
from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import GoodputPhase, NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.fault import FaultInjected, FaultRule, FaultSchedule
from dlrover_tpu.fault.registry import arm, disarm, fault_point
from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.master.scaler.sim_scaler import SimClusterScaler
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.testing.soak import SoakInvariantError

DATASET = "autoscale-train"


@dataclass
class AutoscaleSoakConfig:
    world: int = 4
    capacity: int = 8
    steps: int = 220                  # successful lockstep steps
    base_step_s: float = 0.012        # healthy per-step wall
    restart_s: float = 0.3            # worker replacement / evict pause
    save_block_s: float = 0.008      # blocking cost per ckpt save
    static_ckpt_every_s: float = 3.0  # the fixed baseline cadence
    decision_interval_s: float = 0.08
    mitigate_window_bound: int = 30   # decision windows to evict within
    watchdog_s: float = 75.0
    # serving traffic model (requests per lockstep step)
    serve_replicas: int = 2
    serve_max_replicas: int = 6
    serve_rate_per_replica: float = 3.0
    traffic_base: float = 2.0
    traffic_spike: float = 14.0
    spike_start_frac: float = 0.40
    spike_end_frac: float = 0.65


@dataclass
class AutoscalePlan:
    """Deterministic (seed, episode) -> who lags, who dies, when."""

    straggler_rank: int
    straggler_onset_step: int
    straggler_delay_s: float
    crash_steps: Dict[int, int] = field(default_factory=dict)  # rank->nth
    schedule: Optional[FaultSchedule] = None


def build_autoscale_plan(
    seed: int, episode: int, cfg: Optional[AutoscaleSoakConfig] = None
) -> AutoscalePlan:
    """Randomness in GENERATION, deterministic hit-counter triggers —
    the PR-5 contract. Rules match on the NODE id (stable per
    incarnation), so an evicted straggler's replacement runs clean and
    a dead rank's relaunch is not re-killed. Initial node ids equal
    ranks (the scaler's first group launch allocates 0..world-1)."""
    cfg = cfg or AutoscaleSoakConfig()
    ep_seed = seed * 10007 + episode
    rng = random.Random(ep_seed ^ 0xA5CA1E)
    straggler = rng.randrange(1, cfg.world)
    onset = rng.randint(15, 25)
    delay_s = cfg.base_step_s * rng.uniform(2.4, 3.2)
    others = [r for r in range(cfg.world) if r != straggler]
    rng.shuffle(others)
    lo = cfg.steps
    crash_steps = {
        others[0]: rng.randint(int(lo * 0.25), int(lo * 0.35)),
        others[1 % len(others)]: rng.randint(
            int(lo * 0.50), int(lo * 0.60)
        ),
        others[2 % len(others)]: rng.randint(
            int(lo * 0.80), int(lo * 0.90)
        ),
    }
    rules = [
        # THE satellite fault: a persistent per-node delay at the step
        # fault point — every step of this node is slow from ``onset``
        # until someone does something about it.
        FaultRule(
            "agent.worker.crash", action="delay", delay_s=round(delay_s, 4),
            nth=onset, every=1, match={"node": straggler},
            rule_id="straggler-delay",
        ),
    ]
    for rank, nth in sorted(crash_steps.items()):
        rules.append(FaultRule(
            "agent.worker.crash", action="raise", nth=nth,
            match={"node": rank}, rule_id=f"worker-crash-n{rank}",
        ))
    return AutoscalePlan(
        straggler_rank=straggler,
        straggler_onset_step=onset,
        straggler_delay_s=delay_s,
        crash_steps=crash_steps,
        schedule=FaultSchedule(rules, seed=ep_seed,
                               label=f"autoscale-ep{episode}"),
    )


class SimServingLoad:
    """Deterministic request stream against a replica pool: arrivals
    are a pure function of the step index (identical across the
    static/dry/auto runs), capacity is ``replicas × rate``. Utilization
    saturates at 1.0 while a backlog exists — the signal the fleet
    hysteresis band watches."""

    def __init__(self, cfg: AutoscaleSoakConfig):
        self._cfg = cfg
        self.replicas = cfg.serve_replicas
        self.queue = 0.0
        self.util = 0.0
        self.arrived_total = 0.0
        self.served_total = 0.0
        self.queue_peak = 0.0
        self.grow_events = 0
        self.shrink_events = 0
        self._spike = (
            int(cfg.steps * cfg.spike_start_frac),
            int(cfg.steps * cfg.spike_end_frac),
        )

    def arrivals(self, step: int) -> float:
        lo, hi = self._spike
        return (
            self._cfg.traffic_spike if lo <= step < hi
            else self._cfg.traffic_base
        )

    def tick(self, step: int):
        a = self.arrivals(step)
        self.queue += a
        self.arrived_total += a
        cap = max(self.replicas * self._cfg.serve_rate_per_replica, 1e-9)
        served = min(self.queue, cap)
        self.queue -= served
        self.served_total += served
        self.queue_peak = max(self.queue_peak, self.queue)
        self.util = 1.0 if self.queue > 1e-9 else served / cap

    def as_source(self):
        def fn() -> Dict[str, object]:
            return {
                "replicas": self.replicas,
                "slot_util": round(self.util, 4),
                "queue_depth": round(self.queue, 1),
            }
        return fn

    def grow(self, decision):
        self.replicas = min(
            int(decision.target), self._cfg.serve_max_replicas
        )
        self.grow_events += 1

    def shrink(self, decision):
        self.replicas = max(int(decision.target), 1)
        self.shrink_events += 1


def _policy_config(cfg: AutoscaleSoakConfig) -> PolicyConfig:
    return PolicyConfig(
        straggler_confirm_ticks=2,
        evict_cooldown_s=1.0,
        ckpt_retune_frac=0.2,
        ckpt_min_interval_s=0.05,
        ckpt_cooldown_s=0.5,
        default_save_block_s=cfg.save_block_s,
        max_world=0,                    # world pinned in this scenario
        min_replicas=1,
        max_replicas=cfg.serve_max_replicas,
        fleet_util_grow=0.85,
        fleet_util_shrink=0.30,
        fleet_confirm_ticks=2,
        fleet_cooldown_s=0.3,
    )


def run_sim_job(mode: str, seed: int, episode: int,
                cfg: Optional[AutoscaleSoakConfig] = None,
                record_path: Optional[str] = None) -> Dict:
    """One run of the sim job under (seed, episode)'s fault schedule.
    ``mode``: "static" | "dry_run" | "auto". ``record_path`` arms a
    SignalRecorder on the autoscaler (the §34 replay leg's input).
    Returns the run report."""
    assert mode in ("static", "dry_run", "auto"), mode
    cfg = cfg or AutoscaleSoakConfig()
    plan = build_autoscale_plan(seed, episode, cfg)

    scaler = SimClusterScaler(f"as-s{seed}-e{episode}",
                              capacity=cfg.capacity)
    boot = ScalePlan()
    from dlrover_tpu.common.node import NodeGroupResource

    boot.node_group_resources[NodeType.WORKER] = NodeGroupResource(
        count=cfg.world
    )
    scaler.scale(boot)

    task_manager = TaskManager(task_timeout=30.0)
    task_manager.new_dataset(comm.DatasetShardParams(
        dataset_name=DATASET,
        dataset_size=cfg.steps * cfg.world,
        shard_size=1,
        task_type="training",
    ))
    perf = PerfMonitor()
    cadence = CadenceController(cfg.static_ckpt_every_s,
                                save_block_s=cfg.save_block_s)
    history = FaultHistory()
    serving = SimServingLoad(cfg)
    world_actuator = TrainWorldActuator.for_sim(
        scaler, on_evicted=perf.reset_rank
    )

    autoscaler = None
    if mode in ("dry_run", "auto"):
        from dlrover_tpu.autoscaler import (
            EVICT_STRAGGLER,
            GROW_FLEET,
            SET_CKPT_INTERVAL,
            SHRINK_FLEET,
        )

        bus = (
            SignalBus()
            .add_source("perf", perf_source(perf))
            .add_source("data", data_source(task_manager))
            .add_source("fault", fault_source(history))
            .add_source("fleet", serving.as_source())
            .add_source("world", world_actuator.as_source())
            .add_source("ckpt", cadence.as_source())
        )
        recorder = (
            SignalRecorder(record_path)
            if record_path else None
        )
        autoscaler = AutoScaler(
            bus,
            policy=RulePolicy(_policy_config(cfg)),
            actuators={
                EVICT_STRAGGLER: world_actuator.evict,
                SET_CKPT_INTERVAL: cadence.apply,
                GROW_FLEET: serving.grow,
                SHRINK_FLEET: serving.shrink,
            },
            interval_s=cfg.decision_interval_s,
            dry_run=(mode == "dry_run"),
            recorder=recorder,
            # Realized effects must show within a few decision windows:
            # the eviction's score drop, the fleet grow's backlog drain.
            attribution_window_s=4.0 * cfg.decision_interval_s,
        )

    # ---- the lockstep sim loop --------------------------------------------
    arm(plan.schedule)
    t0 = time.time()
    deadline = t0 + cfg.watchdog_s
    productive_s = stall_s = replay_s = restart_pause_s = save_s = 0.0
    wasted_s = 0.0
    step = 0
    iterations = 0
    deaths = 0
    saves = 0
    last_save_step = 0
    last_save_wall = t0
    last_tick_wall = t0
    ticks = 0
    onset_wall: Optional[float] = None
    onset_tick: Optional[int] = None
    mitigated_wall: Optional[float] = None
    mitigated_tick: Optional[int] = None
    straggler_node = plan.straggler_rank  # node id == rank at boot
    failure: Optional[str] = None
    try:
        while not task_manager.finished():
            if time.time() > deadline:
                failure = f"watchdog: {mode} run exceeded its deadline"
                break
            nodes = scaler.alive_nodes(NodeType.WORKER)
            leases = {}
            for node in nodes:
                task = task_manager.get_task(node.id, DATASET)
                if task.task_id >= 0:
                    leases[node.id] = task
            if not leases:
                time.sleep(0.002)  # leases draining back after a crash
                continue
            iterations += 1
            stepping = [n for n in nodes if n.id in leases]
            t_step = time.time()
            crashed: List[Node] = []
            rank_fault: Dict[int, float] = {}
            for node in stepping:
                f0 = time.time()
                try:
                    fault_point(
                        "agent.worker.crash",
                        step=step, rank=node.rank_index, node=node.id,
                    )
                    rank_fault[node.id] = time.time() - f0
                except FaultInjected:
                    rank_fault[node.id] = time.time() - f0
                    crashed.append(node)
            t_fault_end = time.time()
            if (onset_wall is None
                    and rank_fault.get(straggler_node, 0.0)
                    > cfg.base_step_s):
                onset_wall = time.time()
                onset_tick = ticks
            # §34 attribution: the fault section IS the straggler's
            # stall in this lockstep sim (every rank waits out the
            # delayed one); measured intervals, so sleep overshoot on a
            # loaded box stays attributed too.
            if t_fault_end - t_step > 1e-4 and not crashed:
                for node in stepping:
                    perf.collect_phase(
                        node.rank_index, "stall", t_step, t_fault_end,
                        cause="straggler",
                    )
            t_compute = time.time()
            time.sleep(cfg.base_step_s)  # the world's lockstep compute
            stall = max(rank_fault.values()) if rank_fault else 0.0
            stall_s += stall
            if crashed:
                # The step dies with the worker: nothing is reported
                # done (the leases requeue — exactly-once), the world
                # restarts the seat and replays from the last save.
                wasted_s += cfg.base_step_s
                deaths += len(crashed)
                for node in stepping:
                    task_manager.recover_node_tasks(node.id)
                for node in crashed:
                    history.record_failure()
                    scaler.scale(ScalePlan(
                        remove_nodes=[node],
                        launch_nodes=[Node(
                            NodeType.WORKER, scaler.next_node_id(),
                            rank_index=node.rank_index,
                        )],
                    ))
                time.sleep(cfg.restart_s)
                restart_pause_s += cfg.restart_s
                replay = (step - last_save_step) * cfg.base_step_s
                time.sleep(replay)
                replay_s += replay
                # The dead step + restart + replay is all rescale
                # machinery time, for every lockstep participant.
                t_recovered = time.time()
                for node in stepping:
                    perf.collect_phase(
                        node.rank_index, GoodputPhase.RESTART,
                        t_step, t_recovered, cause="rescale",
                    )
                continue
            now = time.time()
            for node in stepping:
                task_manager.report_task_done(
                    DATASET, leases[node.id].task_id, node.id
                )
                perf.collect_global_step(
                    step + 1, now, node_id=node.rank_index,
                    step_time_s=cfg.base_step_s
                    + rank_fault.get(node.id, 0.0),
                )
                perf.collect_phase(
                    node.rank_index, GoodputPhase.TRAIN,
                    t_compute, now,
                )
            productive_s += cfg.base_step_s
            step += 1
            serving.tick(step)
            if now - last_save_wall >= cadence.interval_s():
                time.sleep(cfg.save_block_s)
                save_s += cfg.save_block_s
                saves += 1
                t_saved = time.time()
                for node in stepping:
                    perf.collect_phase(
                        node.rank_index, GoodputPhase.CKPT,
                        now, t_saved, cause="ckpt",
                    )
                last_save_wall = t_saved
                last_save_step = step
            if (autoscaler is not None
                    and now - last_tick_wall >= cfg.decision_interval_s):
                before_ids = {n.id for n in scaler.alive_nodes()}
                t_tick = time.time()
                autoscaler.tick()
                ticks += 1
                last_tick_wall = time.time()
                after_ids = {n.id for n in scaler.alive_nodes()}
                if after_ids != before_ids:
                    # An actuated membership change (the eviction):
                    # the surviving world pays one rescale pause —
                    # attributed to the straggler that forced it.
                    time.sleep(cfg.restart_s)
                    restart_pause_s += cfg.restart_s
                    t_evicted = time.time()
                    for node in stepping:
                        perf.collect_phase(
                            node.rank_index, GoodputPhase.RESTART,
                            t_tick, t_evicted, cause="straggler",
                        )
                    if (straggler_node not in after_ids
                            and mitigated_wall is None):
                        mitigated_wall = time.time()
                        mitigated_tick = ticks
    finally:
        disarm()
        task_manager.stop()
        if autoscaler is not None:
            # Resolves still-open attribution windows against the last
            # snapshot (truncated) and closes the recorder — the ledger
            # read below must carry every realized outcome.
            autoscaler.stop()
    wall = time.time() - t0
    # MEASURED shard accounting (shard_size=1: shards == records) —
    # the exactly-once invariant reads this, not the config constant.
    mgr = task_manager.get_dataset(DATASET)
    records_done = int(mgr.checkpoint().get("completed", 0))
    fires: Dict[str, int] = {}
    for entry in plan.schedule.trace:
        fires[entry["rule_id"]] = fires.get(entry["rule_id"], 0) + 1
    report: Dict = {
        "mode": mode,
        "failure": failure,
        "wall_s": round(wall, 3),
        "productive_step_s": round(productive_s, 3),
        "goodput_frac": round(productive_s / max(wall, 1e-9), 4),
        "stall_s": round(stall_s, 3),
        "replay_s": round(replay_s, 3),
        "restart_pause_s": round(restart_pause_s, 3),
        "save_s": round(save_s, 3),
        "wasted_s": round(wasted_s, 3),
        "steps": step,
        "iterations": iterations,
        "deaths": deaths,
        "saves": saves,
        "ckpt_interval_final_s": round(cadence.interval_s(), 4),
        "ckpt_retunes": cadence.retunes,
        "fault_fires": fires,
        "serve_replicas_final": serving.replicas,
        "serve_queue_peak": round(serving.queue_peak, 1),
        "serve_backlog_end": round(serving.queue, 1),
        "serve_grow_events": serving.grow_events,
        "serve_shrink_events": serving.shrink_events,
        "records_done": records_done,
        "records_expected": cfg.steps * cfg.world,
        "decision_ticks": ticks,
    }
    if onset_wall is not None:
        report["straggler_onset_s"] = round(onset_wall - t0, 3)
    if mitigated_wall is not None and onset_wall is not None:
        report["time_to_mitigate_s"] = round(
            mitigated_wall - onset_wall, 3
        )
        report["mitigate_windows"] = mitigated_tick - (onset_tick or 0)
    report["goodput_attribution"] = perf.goodput_attribution()
    if autoscaler is not None:
        report["decisions"] = [
            d.to_dict() for d in autoscaler.ledger.entries()
        ]
        report["decisions_total"] = autoscaler.ledger.decisions_total
        report["actuations_total"] = autoscaler.ledger.actuations_total
        report["outcomes_attached"] = autoscaler.ledger.outcomes_total
        report["outcome_misses"] = (
            autoscaler.ledger.outcome_misses_total
        )
        if record_path:
            report["record_path"] = record_path
    if failure:
        raise SoakInvariantError(failure)
    return report


def _check_invariants(static: Dict, auto: Dict,
                      plan: AutoscalePlan, cfg: AutoscaleSoakConfig,
                      dry: Optional[Dict] = None):
    """Invariants 1/2/3/5 need only the static+auto pair and always
    run; the dry-run contract (4) is checked when a dry run exists."""
    # Invariant 5: every run drained the dataset exactly once — the
    # MEASURED shard completions equal the dataset size (crash requeues
    # must neither lose nor double-count leases).
    for run in filter(None, (static, dry, auto)):
        if run["records_done"] != run["records_expected"]:
            raise SoakInvariantError(
                f"{run['mode']} run: exactly-once violated — "
                f"{run['records_done']} shard completions vs "
                f"{run['records_expected']} expected"
            )
    if auto["goodput_frac"] <= static["goodput_frac"]:
        raise SoakInvariantError(
            f"closed loop did not pay: autoscaled goodput "
            f"{auto['goodput_frac']} <= static "
            f"{static['goodput_frac']}"
        )
    if "time_to_mitigate_s" not in auto:
        raise SoakInvariantError(
            f"straggler rank {plan.straggler_rank} was never evicted "
            f"(decisions: {[d['action'] for d in auto['decisions']]})"
        )
    if auto["mitigate_windows"] > cfg.mitigate_window_bound:
        raise SoakInvariantError(
            f"straggler mitigation took {auto['mitigate_windows']} "
            f"decision windows (> {cfg.mitigate_window_bound})"
        )
    evicts = [
        d for d in auto["decisions"] if d["action"] == "evict_straggler"
    ]
    if not evicts or evicts[0]["target"] != plan.straggler_rank:
        raise SoakInvariantError(
            f"eviction targeted {evicts and evicts[0]['target']}, "
            f"expected straggler rank {plan.straggler_rank}"
        )
    for run in filter(None, (dry, auto)):
        for d in run["decisions"]:
            if not d["signals"]:
                raise SoakInvariantError(
                    f"unexplained action: decision #{d['seq']} "
                    f"({d['action']}) carries no signal snapshot"
                )
            if d["outcome"].startswith("error"):
                raise SoakInvariantError(
                    f"actuation error in ledger: {d}"
                )
    if not auto["decisions"]:
        raise SoakInvariantError("autoscaled run took no decisions")
    if any(d["outcome"] != "actuated" for d in auto["decisions"]):
        raise SoakInvariantError(
            "autoscaled run recorded non-actuated decisions: "
            f"{[d['outcome'] for d in auto['decisions']]}"
        )
    # §34 outcome coverage: every actuated decision in the autoscaled
    # run carries a realized-outcome annotation (its attribution window
    # resolved in-run, or force-resolved, truncated, at stop).
    unannotated = [
        d["seq"] for d in auto["decisions"]
        if d["outcome"] == "actuated" and "realized" not in d
    ]
    if unannotated:
        raise SoakInvariantError(
            f"actuated decisions without realized outcomes: "
            f"{unannotated}"
        )
    # §34 attribution coverage: ≥90% of the non-train wall time is
    # explained by a taxonomy cause; unattributed is the only residual.
    attribution = auto.get("goodput_attribution") or {}
    attributed = attribution.get("attributed_frac", 0.0)
    if attributed < 0.9:
        raise SoakInvariantError(
            f"goodput attribution too coarse: {attributed:.3f} of "
            f"non-train wall attributed (< 0.9): "
            f"{attribution.get('causes')}"
        )
    # Dry-run contract: same brain, zero hands — a populated ledger
    # whose leading decision matches the live run's, and NO actuations.
    if dry is None:
        return
    if dry["actuations_total"] != 0:
        raise SoakInvariantError(
            f"dry-run actuated {dry['actuations_total']} times"
        )
    if not dry["decisions"]:
        raise SoakInvariantError("dry-run ledger is empty")
    if any(d["outcome"] != "dry_run" for d in dry["decisions"]):
        raise SoakInvariantError(
            "dry-run ledger carries non-dry outcomes: "
            f"{[d['outcome'] for d in dry['decisions']]}"
        )
    d0, a0 = dry["decisions"][0], auto["decisions"][0]
    if (d0["action"], d0["target"]) != (a0["action"], a0["target"]):
        raise SoakInvariantError(
            f"dry-run and live runs diverge on the first decision: "
            f"{(d0['action'], d0['target'])} vs "
            f"{(a0['action'], a0['target'])}"
        )
    # The straggler's delay rule must stop firing once the node is
    # evicted: the live run sees strictly fewer delay injections.
    if (auto["fault_fires"].get("straggler-delay", 0)
            >= static["fault_fires"].get("straggler-delay", 1)):
        raise SoakInvariantError(
            "eviction did not silence the straggler: delay fired "
            f"{auto['fault_fires'].get('straggler-delay')}x live vs "
            f"{static['fault_fires'].get('straggler-delay')}x static"
        )


def perturbed_config(cfg: AutoscaleSoakConfig) -> PolicyConfig:
    """A deliberately passive candidate for the perturb leg: eviction
    needs an unreachable confirmation streak and the fleet band never
    triggers — given the same stream it must decide DIFFERENTLY from
    the live policy (which provably evicted and grew)."""
    return replace(
        _policy_config(cfg),
        straggler_confirm_ticks=10_000,
        fleet_util_grow=1.01,       # util saturates at 1.0: never grows
        fleet_util_shrink=-1.0,     # and never shrinks
        ckpt_retune_frac=10.0,      # dead band swallows every retune
    )


def run_whatif_leg(auto: Dict, cfg: AutoscaleSoakConfig) -> Dict:
    """The §34 record→replay→perturb leg over the autoscaled run's
    recording. Asserts:

    - **identity**: the recorded policy replayed over the recorded
      snapshots reproduces the live decision ledger exactly;
    - **perturbation**: a different PolicyConfig produces a DIFFERENT
      counterfactual ledger, and both score under the goodput model
      (calibrated from this episode's measured actuation costs).
    """
    record_path = auto.get("record_path")
    if not record_path or not os.path.exists(record_path):
        raise SoakInvariantError("autoscaled run produced no recording")
    recording = load_recording(record_path)
    if not recording.snapshots:
        raise SoakInvariantError("recording carries no snapshots")
    if recording.corrupt_lines:
        raise SoakInvariantError(
            f"recording has {recording.corrupt_lines} corrupt lines "
            f"in a run that was never killed"
        )
    try:
        identity = assert_replay_identity(recording)
    except ReplayMismatch as e:
        raise SoakInvariantError(f"replay identity violated: {e}")
    t0 = time.monotonic()
    perturbed = replay_recording(recording, perturbed_config(cfg))
    replay_elapsed = max(time.monotonic() - t0, 1e-9)
    diff = diff_ledgers(recording.decisions, perturbed)
    if diff["identical"]:
        raise SoakInvariantError(
            "perturbed policy replayed IDENTICALLY to the live one — "
            "the counterfactual engine is not counterfactual"
        )
    cost = CostModel(
        rescale_to_first_step_s=cfg.restart_s,
        evict_pause_s=cfg.restart_s,
        save_block_s=cfg.save_block_s,
    )
    recorded_score = score_ledger(
        recording.snapshots, recording.decisions, cost
    )
    perturbed_score = score_ledger(
        recording.snapshots, perturbed, cost
    )
    for name, score in (("recorded", recorded_score),
                        ("perturbed", perturbed_score)):
        frac = score.get("est_goodput_frac")
        if frac is None or not (0.0 <= frac <= 1.0):
            raise SoakInvariantError(
                f"{name} counterfactual ledger not scored: {score}"
            )
    return {
        "whatif_identity_ok": True,
        "whatif_snapshots": len(recording.snapshots),
        "whatif_replay_snapshots_per_s": round(
            len(recording.snapshots) / replay_elapsed, 1
        ),
        "whatif_recorded_decisions": identity["recorded_total"],
        "whatif_perturbed_decisions": diff["replayed_total"],
        "whatif_first_divergence": diff["first_divergence"],
        "whatif_recorded_est_goodput": recorded_score[
            "est_goodput_frac"
        ],
        "whatif_perturbed_est_goodput": perturbed_score[
            "est_goodput_frac"
        ],
    }


def run_autoscale_episode(
    seed: int,
    episode: int = 5,
    cfg: Optional[AutoscaleSoakConfig] = None,
    include_dry_run: bool = True,
    record_dir: Optional[str] = None,
) -> Dict:
    """The full A/B(/C): static, dry-run, autoscaled under one seeded
    schedule; asserts the §30 invariants; then the §34 leg: record the
    autoscaled run, replay it (identity), perturb it (counterfactual).
    Returns a soak-shaped report with the autoscale extras the bench
    keeps."""
    cfg = cfg or AutoscaleSoakConfig()
    plan = build_autoscale_plan(seed, episode, cfg)
    logger.info(
        "autoscale episode s%d e%d: straggler rank %d (onset step %d, "
        "+%.0fms/step), crashes %s",
        seed, episode, plan.straggler_rank, plan.straggler_onset_step,
        plan.straggler_delay_s * 1e3, plan.crash_steps,
    )
    static = run_sim_job("static", seed, episode, cfg)
    dry = (
        run_sim_job("dry_run", seed, episode, cfg)
        if include_dry_run else None
    )
    owned_record_dir = record_dir is None
    if owned_record_dir:
        record_dir = tempfile.mkdtemp(prefix="autoscale-rec-")
    record_path = os.path.join(
        record_dir, f"signals-s{seed}-e{episode}.jsonl"
    )
    try:
        auto = run_sim_job("auto", seed, episode, cfg,
                           record_path=record_path)
        _check_invariants(static, auto, plan, cfg, dry=dry)
        whatif = run_whatif_leg(auto, cfg)
    finally:
        if owned_record_dir:
            # Caller gave us nowhere durable to put it: the replay leg
            # has consumed the recording, don't leak ~MBs per episode.
            shutil.rmtree(record_dir, ignore_errors=True)
    report: Dict = {
        "episode": episode,
        "seed": seed,
        "kind": "straggler_evict",
        # soak report schema (run_soak aggregates these): wall/productive
        # describe the AUTOSCALED run — the static and dry-run halves of
        # the A/B are reference runs, not the episode's goodput story.
        "wall_s": auto["wall_s"],
        "ab_wall_s": round(static["wall_s"] + auto["wall_s"]
                           + (dry["wall_s"] if dry else 0.0), 3),
        "productive_step_s": auto["productive_step_s"],
        "goodput_frac": auto["goodput_frac"],
        "deaths": auto["deaths"],
        "recovery_s": [],
        "steps_unique": auto["steps"],
        "steps_executed": auto["iterations"],
        "generations": 1,
        "faults": [
            {"origin": "sim", "rule_id": rid, "fires": n,
             "point": "agent.worker.crash",
             "action": ("delay" if rid == "straggler-delay"
                        else "raise"),
             "hit": n}
            for rid, n in sorted(auto["fault_fires"].items())
        ],
        # the autoscale A/B headline
        "autoscale_goodput_frac": auto["goodput_frac"],
        "static_goodput_frac": static["goodput_frac"],
        "autoscale_decisions_total": auto["decisions_total"],
        "autoscale_actuations_total": auto["actuations_total"],
        "autoscale_time_to_mitigate_s": auto.get("time_to_mitigate_s"),
        "autoscale_mitigate_windows": auto.get("mitigate_windows"),
        "autoscale_ckpt_interval_s": auto["ckpt_interval_final_s"],
        "autoscale_ckpt_retunes": auto["ckpt_retunes"],
        "autoscale_stall_s": auto["stall_s"],
        "static_stall_s": static["stall_s"],
        "autoscale_replay_s": auto["replay_s"],
        "static_replay_s": static["replay_s"],
        "autoscale_serve_backlog_end": auto["serve_backlog_end"],
        "static_serve_backlog_end": static["serve_backlog_end"],
        "autoscale_serve_replicas_final": auto["serve_replicas_final"],
        "autoscale_fleet_grow_events": auto["serve_grow_events"],
        "autoscale_fleet_shrink_events": auto["serve_shrink_events"],
        # §34: outcome coverage + per-cause attribution + what-if leg
        "autoscale_outcomes_attached": auto["outcomes_attached"],
        "autoscale_outcome_misses": auto["outcome_misses"],
        "goodput_attributed_frac": auto["goodput_attribution"][
            "attributed_frac"
        ],
        "goodput_causes": {
            c: v["frac"]
            for c, v in auto["goodput_attribution"]["causes"].items()
        },
        **whatif,
        "invariants": "pass",
    }
    if dry is not None:
        report["dry_run_decisions_total"] = dry["decisions_total"]
        report["dry_run_actuations_total"] = dry["actuations_total"]
    return report
