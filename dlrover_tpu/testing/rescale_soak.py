"""Live-rescale soak runner: N workers, seeded faults, rescale invariants.

One episode = an in-process master carrying the full rescale plane
(:class:`RescaleCoordinator` + task manager + KV store + servicer over
HTTP) and N :mod:`rescale_worker` subprocesses. Scenarios:

- ``live`` — train at world N, SIGKILL one worker, assert the survivors
  rescale to N-1 **in-process** (no respawn), then spawn a fresh worker
  that joins mid-run and scales the world back to N. The acceptance
  test for ROADMAP item 2's "no job restart" claim.
- ``kill_during_rescale`` — a worker dies mid-step (plan #2 is cut),
  and a second worker is SIGKILLed inside the restore-to-first-step
  window of that plan (the ``rescale.resume.first_step`` fault site);
  the coordinator must re-plan around it and the respawned generation
  must finish the dataset. Runs as chaos-soak episode kind 4.

Invariants asserted after every episode (extending docs/DESIGN.md §26
with the PR-6 fifth assertion):

1. **Exactly-once** — every finishing worker's final state equals the
   whole-dataset reference (no shard lost or double-consumed), and all
   replicas are bit-identical.
2. **Reference-replay bit-exactness** — for every checkpoint save, the
   state CRC equals a single-host replay over exactly the shards the
   save's shard snapshot marks consumed; every restore's CRC equals the
   corresponding save's.
3. **Live process tree** — in the ``live`` scenario the surviving
   ranks' processes never restart (one generation each).
4. **Watchdog** — the episode is wall-clock bounded.
"""

import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import FaultRule, FaultSchedule, arm, disarm
from dlrover_tpu.fault.registry import SCHEDULE_ENV, TRACE_ENV
from dlrover_tpu.testing import rescale_worker as rw
from dlrover_tpu.testing.soak import (
    SoakInvariantError,
    _read_events,
    _read_trace,
    _repo_root,
)


@dataclass
class RescaleSoakConfig:
    world: int = 2
    dataset_size: int = 192
    shard_size: int = 16
    ckpt_every: int = 2
    vec_len: int = 64
    step_ms: float = 0.0
    watchdog_s: float = 150.0
    barrier_timeout_s: float = 20.0
    task_timeout_s: float = 60.0
    keep_artifacts_on_success: bool = False


@dataclass
class _Runner:
    """Master-side state for one episode."""

    server: object
    coordinator: object
    task_manager: object
    port: int
    ep_dir: str
    cfg: RescaleSoakConfig
    procs: Dict[int, subprocess.Popen] = field(default_factory=dict)
    generations: Dict[int, int] = field(default_factory=dict)
    deaths: List[Dict] = field(default_factory=list)


def expected_ranges(dataset_size: int, shard_size: int):
    return [
        (s, min(s + shard_size, dataset_size))
        for s in range(0, dataset_size, shard_size)
    ]


def _events_path(ep_dir: str, rank: int) -> str:
    return os.path.join(ep_dir, f"events_r{rank}.jsonl")


def _spawn_worker(r: _Runner, rank: int, schedule_path: str = "") -> None:
    cfg = r.cfg
    generation = r.generations.get(rank, -1) + 1
    r.generations[rank] = generation
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # A kill landing while this worker is the commit leader must
        # cost a short wait, not 30s of blindness to the rescale plan.
        "DLROVER_TPU_CKPT_COMMIT_TIMEOUT_S": "5",
        "DLROVER_TPU_JOB_NAME": os.path.basename(r.ep_dir),
        "DLROVER_TPU_FLIGHT_DIR": os.path.join(r.ep_dir, "flight"),
        TRACE_ENV: os.path.join(r.ep_dir, f"trace_r{rank}.jsonl"),
        "PYTHONPATH": _repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
    })
    if schedule_path:
        env[SCHEDULE_ENV] = schedule_path
    else:
        env.pop(SCHEDULE_ENV, None)
    args = [
        sys.executable, "-m", "dlrover_tpu.testing.rescale_worker",
        "--master-addr", f"localhost:{r.port}",
        "--rank", str(rank),
        "--world", str(cfg.world),
        "--dataset-size", str(cfg.dataset_size),
        "--shard-size", str(cfg.shard_size),
        "--ckpt-dir", os.path.join(r.ep_dir, "ckpt"),
        "--ckpt-every", str(cfg.ckpt_every),
        "--events", _events_path(r.ep_dir, rank),
        "--generation", str(generation),
        "--vec-len", str(cfg.vec_len),
        "--step-ms", str(cfg.step_ms),
        "--deadline-s", str(cfg.watchdog_s),
    ]
    log = open(
        os.path.join(r.ep_dir, f"worker_r{rank}_g{generation}.log"), "w"
    )
    with log:
        r.procs[rank] = subprocess.Popen(
            args, env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=_repo_root(),
        )


def _build_master(cfg: RescaleSoakConfig, ep_dir: str) -> _Runner:
    from dlrover_tpu.master.elastic_training.rescale_coordinator import (
        RescaleCoordinator,
    )
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.shard.task_manager import TaskManager
    from dlrover_tpu.rpc.transport import HttpMasterServer
    from dlrover_tpu.trainer.elastic.trainer import ElasticBatchConfig

    batch_config = ElasticBatchConfig(
        # Must mirror the worker's config: lcm(1..world) keeps every
        # scale-down world size legal.
        global_batch_size=cfg.shard_size * rw.world_lcm(cfg.world),
        micro_batch_per_device=cfg.shard_size,
    )
    coordinator = RescaleCoordinator(
        legal_counts_fn=batch_config.legal_node_counts_fn(),
        barrier_timeout_s=cfg.barrier_timeout_s,
        bootstrap_min=cfg.world,
    )
    task_manager = TaskManager(task_timeout=cfg.task_timeout_s)
    servicer = MasterServicer(
        rdzv_managers={},
        task_manager=task_manager,
        rescale_coordinator=coordinator,
    )
    server = HttpMasterServer(0, servicer)
    server.start()
    return _Runner(
        server=server,
        coordinator=coordinator,
        task_manager=task_manager,
        port=server.port,
        ep_dir=ep_dir,
        cfg=cfg,
    )


def _poll_deaths(r: _Runner) -> List[int]:
    """Reap dead workers; route deaths into the rescale plane exactly
    like the agent's node-failure report would."""
    died = []
    for rank, proc in list(r.procs.items()):
        rc = proc.poll()
        if rc is None or rc == rw.EXIT_OK:
            continue
        del r.procs[rank]
        died.append(rank)
        r.deaths.append({
            "t": time.time(), "rank": rank, "rc": rc,
            "generation": r.generations[rank],
            "signal": -rc if rc < 0 else None,
        })
        r.coordinator.note_worker_lost(rank)
        r.task_manager.recover_node_tasks(rank)
    return died


def _all_events(r: _Runner) -> List[Dict]:
    events = []
    for rank in r.generations:
        for e in _read_events(_events_path(r.ep_dir, rank)):
            e["rank"] = e.get("rank", rank)
            events.append(e)
    events.sort(key=lambda e: e.get("t", 0.0))
    return events


def _wait_for(r: _Runner, predicate, deadline: float, what: str):
    while time.time() < deadline:
        if predicate(_all_events(r)):
            return
        _poll_deaths(r)
        time.sleep(0.1)
    raise SoakInvariantError(f"watchdog: timed out waiting for {what}")


# ---------------------------------------------------------------------------
# Invariants
# ---------------------------------------------------------------------------


def check_rescale_invariants(events: List[Dict], cfg: RescaleSoakConfig):
    """Invariants 1 and 2 over the merged per-rank ledgers."""
    from dlrover_tpu.testing.soak_worker import state_crc

    dones = [e for e in events if e.get("kind") == "done"]
    if not dones:
        raise SoakInvariantError("no worker reported completion")
    ref_full = rw.reference_state(cfg.dataset_size, expected_ranges(
        cfg.dataset_size, cfg.shard_size
    ), cfg.vec_len)
    want_crc = state_crc(ref_full)
    for d in dones:
        if d["sum"] != int(ref_full["sum"]):
            raise SoakInvariantError(
                f"exactly-once violated: rank {d['rank']} final sum "
                f"{d['sum']} != {int(ref_full['sum'])}"
            )
        if d["hist"] != ref_full["hist"].tolist():
            raise SoakInvariantError(
                f"exactly-once violated: rank {d['rank']} per-bucket "
                "record counts diverge"
            )
        if d["crc"] != want_crc:
            raise SoakInvariantError(
                f"rank {d['rank']} final state not bit-identical to the "
                f"single-host reference (crc {d['crc']} != {want_crc})"
            )
    # Reference replay: each save's state must be bit-identical to a
    # single-host run over exactly the shards its snapshot marks
    # consumed; lockstep replicas must agree per (plan, step) — step
    # numbers alone recur across plans because a rescale rolls the
    # counter back to the restore step.
    all_shards = expected_ranges(cfg.dataset_size, cfg.shard_size)
    saves_by_plan_step: Dict[tuple, int] = {}
    save_history: List[tuple] = []  # (t, step, crc) in ledger order
    for e in events:
        if e.get("kind") != "save":
            continue
        step, crc = e["step"], e["crc"]
        key = (e.get("plan"), step)
        if saves_by_plan_step.setdefault(key, crc) != crc:
            raise SoakInvariantError(
                f"replicas diverged: plan {key[0]} step {step} saved "
                f"with different CRCs across ranks"
            )
        save_history.append((e.get("t", 0.0), step, crc))
        snap = e.get("snapshot", "")
        if not snap:
            consumed = []
        else:
            snap_d = json.loads(snap)
            if snap_d.get("epoch", 0) == 0:
                consumed = []  # pre-split snapshot: nothing consumed
            else:
                undone = {
                    (u[0], u[1]) for u in snap_d.get("undone_shards", [])
                }
                consumed = [s for s in all_shards if s not in undone]
        ref = rw.reference_state(cfg.dataset_size, consumed, cfg.vec_len)
        if state_crc(ref) != crc:
            raise SoakInvariantError(
                f"save at step {step} not bit-identical to the "
                f"single-host reference over its consumed shard set "
                f"({len(consumed)} shards)"
            )
    for e in events:
        if e.get("kind") == "restore":
            step = e["step"]
            # The save this restore read is the newest COMMITTED save of
            # that step before the restore happened.
            prior = [
                crc for (t, s, crc) in save_history
                if s == step and t <= e.get("t", 0.0)
            ]
            if not prior:
                raise SoakInvariantError(
                    f"restored step {step} was never saved"
                )
            if e["crc"] != prior[-1]:
                raise SoakInvariantError(
                    f"restore of step {step} is not bit-identical to its "
                    f"save (crc {e['crc']} != {prior[-1]})"
                )
        elif e.get("kind") == "restore_crc_mismatch":
            raise SoakInvariantError(
                f"restore failed integrity at step {e.get('step')}"
            )


def rescale_timings(events: List[Dict]) -> List[Dict]:
    """Per-(rank, plan) rescale latencies incl. plan→first-step."""
    out = []
    steps = [e for e in events if e.get("kind") == "step"]
    for e in events:
        if e.get("kind") != "rescale":
            continue
        first_step = next(
            (
                s for s in steps
                if s.get("plan") == e["plan"]
                and s.get("rank") == e.get("rank")
                and s.get("t", 0) >= e.get("t", 0)
            ),
            None,
        )
        entry = {
            "rank": e.get("rank"),
            "plan": e["plan"],
            "reason": e.get("reason"),
            "world": len(e.get("world", [])),
            "barrier_s": e.get("barrier_s"),
            "restore_s": e.get("restore_s"),
            "rescale_s": e.get("total_s"),
        }
        if first_step is not None and e.get("plan_created_at"):
            entry["plan_to_first_step_s"] = round(
                first_step["t"] - e["plan_created_at"], 4
            )
        out.append(entry)
    return out


# ---------------------------------------------------------------------------
# Episode execution
# ---------------------------------------------------------------------------


def _terminate_workers(r: _Runner):
    """SIGTERM first (the flight recorder dumps its ring on SIGTERM),
    escalate to SIGKILL. Idempotent."""
    for proc in r.procs.values():
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)


def _cleanup(r: _Runner):
    _terminate_workers(r)
    disarm()
    r.server.stop()
    r.task_manager.stop()
    job = os.path.basename(r.ep_dir)
    for rank in r.generations:
        try:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(
                name=f"dlrover_tpu_ckpt_{job}_n{rank}_0"
            )
            seg.close()
            seg.unlink()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass


def _dump_artifacts(r: _Runner, artifact_dir: str, seed: int,
                    scenario: str, reason: str,
                    runner_schedule: Optional[FaultSchedule] = None) -> str:
    os.makedirs(artifact_dir, exist_ok=True)
    dest = os.path.join(artifact_dir, f"rescale_seed{seed}_{scenario}")
    shutil.rmtree(dest, ignore_errors=True)
    os.makedirs(dest, exist_ok=True)
    for pattern in ("events_r*.jsonl", "trace_r*.jsonl", "worker_r*.log",
                    "schedule_*.json"):
        for src in glob.glob(os.path.join(r.ep_dir, pattern)):
            shutil.copy(src, dest)
    # The §26 artifact contract: the flight rings the SIGTERMed workers
    # dumped, plus EVERY armed schedule — the in-process runner one has
    # no on-disk copy unless serialized here.
    flight_src = os.path.join(r.ep_dir, "flight")
    if os.path.isdir(flight_src):
        shutil.copytree(
            flight_src, os.path.join(dest, "flight"), dirs_exist_ok=True
        )
    if runner_schedule is not None:
        with open(os.path.join(dest, "schedule_runner.json"), "w") as f:
            f.write(runner_schedule.to_json())
    with open(os.path.join(dest, "failure.json"), "w") as f:
        json.dump({"seed": seed, "scenario": scenario, "reason": reason},
                  f, indent=2)
    return dest


def run_rescale_episode(
    seed: int,
    cfg: Optional[RescaleSoakConfig] = None,
    scenario: str = "live",
    work_dir: Optional[str] = None,
    artifact_dir: Optional[str] = None,
    runner_schedule: Optional[FaultSchedule] = None,
    rank_schedules: Optional[Dict[int, FaultSchedule]] = None,
) -> Dict:
    """Run one live-rescale episode; returns a soak-style report dict.
    Raises :class:`SoakInvariantError` (after dumping artifacts) on any
    invariant breach."""
    cfg = cfg or RescaleSoakConfig()
    if scenario == "live" and cfg.step_ms <= 0:
        # Unpaced steps are sub-millisecond: the N-1 survivor drains the
        # whole dataset during the joiner's ~2s process bootstrap, the
        # scale-up barrier expires against an exited worker, and the
        # watchdog fires without ever exercising scale-up. Pace the run
        # so a world change can actually land mid-epoch (the integration
        # test uses step_ms=80 over a 960-record dataset).
        raise ValueError(
            "scenario='live' needs cfg.step_ms > 0 so the survivor "
            "cannot finish the epoch before the scale-up joiner boots"
        )
    work_dir = work_dir or tempfile.mkdtemp(prefix="dlrover_rescale_")
    artifact_dir = artifact_dir or os.path.join(work_dir, "artifacts")
    ep_dir = os.path.join(work_dir, f"rescale-s{seed}-{scenario}")
    shutil.rmtree(ep_dir, ignore_errors=True)
    os.makedirs(os.path.join(ep_dir, "flight"), exist_ok=True)
    os.makedirs(os.path.join(ep_dir, "ckpt"), exist_ok=True)

    schedule_paths: Dict[int, str] = {}
    for rank, sched in (rank_schedules or {}).items():
        path = os.path.join(ep_dir, f"schedule_r{rank}.json")
        with open(path, "w") as f:
            f.write(sched.to_json())
        schedule_paths[rank] = path

    r = _build_master(cfg, ep_dir)
    if runner_schedule is not None:
        arm(runner_schedule)
    t_start = time.time()
    deadline = t_start + cfg.watchdog_s
    report: Dict = {"seed": seed, "scenario": scenario,
                    "world": cfg.world}
    try:
        for rank in range(cfg.world):
            _spawn_worker(r, rank, schedule_paths.get(rank, ""))
        if scenario == "live":
            _run_live_scenario(r, deadline)
        elif scenario == "kill_during_rescale":
            _run_kill_during_rescale(r, deadline)
        else:
            raise ValueError(f"unknown scenario {scenario!r}")
        # Wait for every remaining worker to finish the dataset.
        while r.procs and time.time() < deadline:
            for rank, proc in list(r.procs.items()):
                rc = proc.poll()
                if rc == rw.EXIT_OK:
                    del r.procs[rank]
            if _poll_deaths(r):
                continue
            time.sleep(0.1)
        if r.procs:
            raise SoakInvariantError(
                f"watchdog: workers {sorted(r.procs)} never finished"
            )
        events = _all_events(r)
        check_rescale_invariants(events, cfg)
        if scenario == "live":
            _check_live_process_tree(r, events)
    except SoakInvariantError as e:
        # Workers go down (SIGTERM → flight rings dump) BEFORE the
        # artifact copy, so the bundle actually contains the rings.
        _terminate_workers(r)
        dest = _dump_artifacts(
            r, artifact_dir, seed, scenario, str(e),
            runner_schedule=runner_schedule,
        )
        print(
            f"RESCALE EPISODE FAILED: {e}\n  artifacts: {dest}",
            file=sys.stderr, flush=True,
        )
        raise
    finally:
        _cleanup(r)

    wall = time.time() - t_start
    events = _all_events(r)
    step_events = [e for e in events if e.get("kind") == "step"]
    # Keyed by STEP, not (rank, step): lockstep ranks execute the same
    # global step in parallel, and rolled-back replays count once (the
    # last execution wins) — the same productive-time semantics as the
    # PR-5 single-worker soak, so aggregate goodput stays comparable.
    last_dur: Dict[int, float] = {}
    for e in step_events:
        last_dur[e["step"]] = e.get("dur", 0.0)
    productive_s = sum(last_dur.values())
    recoveries = []
    for death in r.deaths:
        after = [e for e in step_events if e["t"] > death["t"]]
        if after:
            recoveries.append(after[0]["t"] - death["t"])
    trace = []
    for rank in r.generations:
        trace += _read_trace(
            os.path.join(ep_dir, f"trace_r{rank}.jsonl"), f"rank{rank}"
        )
    if runner_schedule is not None:
        trace += [
            {
                "origin": "runner", "point": t["point"],
                "action": t["action"], "rule_id": t["rule_id"],
                "hit": t["hit"],
            }
            for t in runner_schedule.trace
        ]
    trace.sort(key=lambda t: (t["origin"], str(t["rule_id"])))
    timings = rescale_timings(events)
    report.update({
        "wall_s": round(wall, 3),
        "productive_step_s": round(productive_s, 3),
        "goodput_frac": round(
            min(productive_s / max(wall, 1e-9), 1.0), 4
        ),
        "faults": trace,
        "deaths": len(r.deaths),
        "recovery_s": [round(x, 3) for x in recoveries],
        "rescales": timings,
        "plans": max(
            (e.get("plan", 0) for e in events if e.get("kind") == "rescale"),
            default=0,
        ),
        "steps_executed": len(step_events),
        "steps_unique": len(last_dur),
        "generations": dict(r.generations),
    })
    if not cfg.keep_artifacts_on_success:
        shutil.rmtree(ep_dir, ignore_errors=True)
    return report


def _crash_ready_step(cfg: RescaleSoakConfig) -> int:
    """A step by which at least two checkpoint intervals committed."""
    return 2 * max(cfg.ckpt_every, 1) + 1


def _run_live_scenario(r: _Runner, deadline: float):
    cfg = r.cfg
    victim = cfg.world - 1
    ready = _crash_ready_step(cfg)

    def trained(events):
        per_rank = {}
        for e in events:
            if e.get("kind") == "step":
                per_rank[e["rank"]] = max(
                    per_rank.get(e["rank"], 0), e["step"]
                )
        return len(per_rank) >= cfg.world and min(
            per_rank.values()
        ) >= ready

    _wait_for(r, trained, deadline, f"world={cfg.world} to reach "
              f"step {ready}")
    os.kill(r.procs[victim].pid, signal.SIGKILL)
    _poll_deaths_until(r, victim, deadline)

    def rescaled_down(events):
        return any(
            e.get("kind") == "rescale"
            and len(e.get("world", [])) == cfg.world - 1
            and e.get("rank") != victim
            for e in events
        )

    _wait_for(r, rescaled_down, deadline,
              f"live rescale to world={cfg.world - 1}")
    # Scale back UP: a fresh worker joins mid-run and steals leases.
    # Spawned immediately after the scale-down completes — the joiner's
    # ~2s process bootstrap is exactly the window in which the survivor
    # proves it trains at world N-1 (asserted post-hoc from the ledger).
    _spawn_worker(r, victim, "")

    def rescaled_up(events):
        return any(
            e.get("kind") == "rescale"
            and len(e.get("world", [])) == cfg.world
            and e.get("generation", 0) >= 1
            for e in events
        )

    _wait_for(r, rescaled_up, deadline,
              f"scale-up back to world={cfg.world}")


def _poll_deaths_until(r: _Runner, rank: int, deadline: float):
    while time.time() < deadline:
        if rank in [d["rank"] for d in r.deaths]:
            return
        _poll_deaths(r)
        time.sleep(0.05)
    raise SoakInvariantError(f"watchdog: rank {rank} death never observed")


def _run_kill_during_rescale(r: _Runner, deadline: float):
    """The armed schedules do the killing: rank 1 crashes mid-step
    (cutting the scale-down plan), rank 0 is SIGKILLed inside that
    plan's restore-to-first-step window (``rescale.resume.first_step``).
    The runner respawns only rank 0 — the fresh generation joins the
    rescale plane and must finish the dataset alone. Returns once both
    planned kills landed and the respawn is up; the caller's drain loop
    handles the rest."""
    while time.time() < deadline:
        died = _poll_deaths(r)
        for rank in died:
            if rank == 0:
                # The mid-rescale victim comes back as a fresh
                # generation joining the rescale plane.
                _spawn_worker(r, rank, "")
        if len(r.deaths) >= 2 and 0 in r.procs:
            return
        time.sleep(0.05)
    raise SoakInvariantError(
        "watchdog: kill_during_rescale kills never completed "
        f"(deaths={len(r.deaths)})"
    )


def _check_live_process_tree(r: _Runner, events: List[Dict]):
    """Survivors must have exactly ONE generation (never restarted) and
    the victim exactly two (the scale-up join)."""
    cfg = r.cfg
    victim = cfg.world - 1
    for rank, gen in r.generations.items():
        if rank == victim:
            if gen != 1:
                raise SoakInvariantError(
                    f"victim rank {rank} expected 1 respawn, got {gen}"
                )
        elif gen != 0:
            raise SoakInvariantError(
                f"survivor rank {rank} restarted ({gen} respawns) — the "
                "job process tree must survive a live rescale"
            )
    starts = [
        e for e in events if e.get("kind") == "worker_start"
    ]
    by_rank: Dict[int, int] = {}
    for e in starts:
        by_rank[e["rank"]] = by_rank.get(e["rank"], 0) + 1
    for rank, count in by_rank.items():
        want = 2 if rank == victim else 1
        if count != want:
            raise SoakInvariantError(
                f"rank {rank} recorded {count} process starts, want {want}"
            )
    if not any(
        e.get("kind") == "step" and e.get("world") == cfg.world - 1
        for e in events
    ):
        raise SoakInvariantError(
            f"no training step recorded at world={cfg.world - 1}: the "
            "job never actually trained in the scaled-down world"
        )
