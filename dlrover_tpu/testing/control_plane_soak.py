"""Control-plane saturation harness: 1k sim workers vs one master (§32).

The paper's headline — goodput on *thousands* of GPUs — rests on a
master whose own limits this repo had never measured. This harness
turns "max sustainable world size" into a tracked bench number by
driving hundreds to thousands of **lightweight in-process worker
clients** (the ``sim_cluster``/``soak_worker`` pattern: an in-process
master served over the real HTTP transport, real :class:`MasterClient`
verbs on the wire) through three phases:

1. **Ramp** — closed-loop concurrency doubling over a production-mix
   verb schedule (lease fetch + batched done-reports + step/goodput
   telemetry + KV + resource stats + span pushes). Each stage reports
   achieved RPCs/s and client-side p99; the knee — p99 through the
   ceiling or throughput gains flattening — defines
   ``max_sustainable_rps``. Master CPU per 1k RPCs comes from the §32
   ``master_rpc_cpu_seconds_total`` thread-CPU counter, so the number
   is master-side even though the clients share the process.
2. **Quorum** — rendezvous time-to-quorum at world sizes
   {8, 64, 256, 1024}: a fresh rendezvous per world, every rank joined
   over the wire, wall time from first join to the full world forming.
3. **Shed** — the overload governor's watermarks are dropped so load
   shedding engages deterministically, then lease + rendezvous +
   diagnostic traffic runs concurrently.

Invariants (raise :class:`ControlPlaneInvariantError`):

- **Shed ordering law** — diagnostic classes were shed (counted), and
  ZERO task-lease / rendezvous / any-other-critical verb was ever
  dropped: ``master_rpc_dropped_total`` is 0 for every verb outside
  the diagnostic/telemetry classes, and lease responses stayed
  well-formed throughout the shed window.
- **Buffer accounting** — every bounded buffer on
  ``/api/control_plane`` reports ``occupancy`` and ``drops``.
- **Metric/span agreement** — for every verb where the per-verb
  histogram and the ``master.<verb>`` server spans saw the same
  population, mean latencies agree within 15% (both are supposed to
  measure the SAME dispatch window; drift means one of them lies).
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
)
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
from dlrover_tpu.master.overload import (
    DIAGNOSTIC_VERBS,
    TELEMETRY_VERBS,
    OverloadGovernor,
)
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.observability import tracing
from dlrover_tpu.rpc.transport import HttpMasterServer


class ControlPlaneInvariantError(AssertionError):
    pass


@dataclass
class ControlPlaneSoakConfig:
    workers: int = 64              # logical worker clients (node ids)
    driver_threads: int = 8        # OS threads multiplexing them
    stage_duration_s: float = 1.0  # per ramp stage
    max_stages: int = 5            # concurrency 1,2,4,... x driver_threads
    knee_p99_s: float = 0.10       # p99 past this = saturated
    knee_gain_frac: float = 0.05   # <5% RPS gain = flat = saturated
    quorum_worlds: Tuple[int, ...] = (8, 64)
    shed_duration_s: float = 0.8
    dataset_size: int = 1 << 16
    shard_size: int = 4
    num_epochs: int = 1 << 16      # todo refills for the whole run
    agree_tolerance: float = 0.15
    agree_min_count: int = 50
    lease_batch: int = 2


@dataclass
class _SpanAgg:
    """on_finish aggregation of ``master.<verb>`` server spans — an
    O(1) fold per span so a 100k-RPC run costs no memory."""

    lock: threading.Lock = field(default_factory=threading.Lock)
    count: Dict[str, int] = field(default_factory=dict)
    total_s: Dict[str, float] = field(default_factory=dict)

    def __call__(self, record: Dict):
        name = record.get("name", "")
        if not name.startswith("master.") or record.get("dur_s") is None:
            return
        verb = name[len("master."):]
        with self.lock:
            self.count[verb] = self.count.get(verb, 0) + 1
            self.total_s[verb] = (
                self.total_s.get(verb, 0.0) + record["dur_s"]
            )

    def means(self) -> Dict[str, Tuple[int, float]]:
        with self.lock:
            return {
                verb: (n, self.total_s[verb] / n)
                for verb, n in self.count.items()
                if n > 0
            }


def _seconds_snapshot(seconds) -> Dict[str, Tuple[float, float]]:
    """{verb: (count, sum)} of the global master_rpc_seconds family at
    a point in time — the agreement check's subtraction baseline."""
    out: Dict[str, List[float]] = {}
    for name, labels, value in seconds.samples():
        verb = labels.get("verb")
        if verb is None:
            continue
        entry = out.setdefault(verb, [0.0, 0.0])
        if name.endswith("_count"):
            entry[0] = value
        elif name.endswith("_sum"):
            entry[1] = value
    return {verb: (c, s) for verb, (c, s) in out.items()}


class SimMaster:
    """In-process master over the real HTTP transport (the soak
    pattern), with the §32 governor injected so the harness can move
    its watermarks."""

    def __init__(self, cfg: ControlPlaneSoakConfig):
        self.cfg = cfg
        # Pure construction first — nothing below this block mutates
        # process-global state, so a failure here leaks nothing.
        self.perf_monitor = PerfMonitor()
        self.task_manager = TaskManager(
            task_timeout=3600.0, perf_monitor=self.perf_monitor
        )
        self.rdzv_managers = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
        }
        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.trace_aggregator = tracing.TraceAggregator()
        self.governor = OverloadGovernor()
        self.servicer = MasterServicer(
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            perf_monitor=self.perf_monitor,
            sync_service=self.sync_service,
            kv_store=self.kv_store,
            trace_aggregator=self.trace_aggregator,
            overload_governor=self.governor,
        )
        self.span_agg = _SpanAgg()
        # The metric families are process-global and cumulative;
        # snapshot this servicer's per-verb baseline so the
        # metric-vs-span agreement check compares DELTAS against the
        # per-run span aggregator (earlier phases/tests in the same
        # process would otherwise desynchronize the populations).
        self.seconds_baseline = _seconds_snapshot(
            self.servicer.telemetry.seconds
        )
        # Global mutations LAST, rolled back on any failure (the
        # fleet_soak bug class: a constructor that dies half-armed
        # poisons every later phase in the process).
        import logging

        self._prev_log_level = logger.level
        self._prev_tracer = tracing.active_tracer()
        self._server = None
        try:
            # 1024 joins x 4 worlds = thousands of INFO lines; the
            # harness is the one caller where per-join logging is pure
            # noise.
            logger.setLevel(logging.WARNING)
            self._tracer = tracing.arm(tracing.Tracer(service="cp-master"))
            self._tracer.set_on_finish(self.span_agg)
            self._server = HttpMasterServer(0, self.servicer)
            self._server.start()
            self.addr = f"localhost:{self._server.port}"
            self.task_manager.new_dataset(comm.DatasetShardParams(
                dataset_name="cp",
                dataset_size=cfg.dataset_size,
                shard_size=cfg.shard_size,
                num_epochs=cfg.num_epochs,
                task_type="training",
                storage_type="text",
                shuffle=False,
            ))
        except Exception:
            self.close()
            raise

    def fresh_rdzv(self, world: int) -> ElasticTrainingRendezvousManager:
        """A clean rendezvous per quorum measurement (the servicer sees
        the swap — it holds the same dict object)."""
        mgr = ElasticTrainingRendezvousManager()
        mgr.update_rdzv_params(
            min_nodes=world, max_nodes=world, waiting_timeout=1.0
        )
        self.rdzv_managers[RendezvousName.TRAINING] = mgr
        return mgr

    def close(self):
        try:
            if self._server is not None:
                self._server.stop()
        finally:
            self.task_manager.stop()
            if self._prev_tracer is not None:
                tracing.arm(self._prev_tracer)
            else:
                tracing.disarm()
            logger.setLevel(self._prev_log_level)


class _SimWorkerPool:
    """``workers`` logical clients multiplexed over
    ``driver_threads`` OS threads. Each thread owns ONE keep-alive
    HTTP stub (one TCP connection) and stamps the logical worker's
    node id onto the envelope per call — 1024 workers cost 8-32
    connections, not 1024 server threads."""

    def __init__(self, addr: str, cfg: ControlPlaneSoakConfig):
        from dlrover_tpu.agent.master_client import MasterClient

        self.cfg = cfg
        self._clients = [
            MasterClient(addr, node_id=0, kind="http", timeout=30.0)
            for _ in range(cfg.driver_threads)
        ]
        # thread index -> disjoint slice of logical worker ids.
        per = max(cfg.workers // cfg.driver_threads, 1)
        self._slices = [
            list(range(i * per, min((i + 1) * per, cfg.workers)))
            or [i % max(cfg.workers, 1)]
            for i in range(cfg.driver_threads)
        ]

    def close(self):
        for c in self._clients:
            c.close()

    # ---- the production verb mix ------------------------------------------

    def _one_cycle(self, client, worker_id: int, seq: int,
                   lat: List[float], errors: List[str],
                   lease_ok: List[int]):
        """One mixed-verb burst for one logical worker: lease fetch +
        done report + telemetry + kv + diagnostics, deterministic mix
        by sequence number."""
        client._node_id = worker_id  # noqa: SLF001 — same-thread stamp
        t0 = time.monotonic()
        try:
            mix = seq % 8
            if mix <= 2:
                tasks, _wait = client.get_tasks(
                    "cp", count=self.cfg.lease_batch
                )
                lease_ok.append(1)
                done = [t.task_id for t in tasks if t.task_id >= 0]
                if done:
                    lat.append(time.monotonic() - t0)
                    t0 = time.monotonic()
                    client.report_tasks_done_batch("cp", done)
                    lease_ok.append(1)
            elif mix == 3:
                client.report_global_step(
                    step=seq, elapsed_train_secs=0.01,
                    step_time_s=0.01,
                )
            elif mix == 4:
                client.kv_store_set(
                    f"cp/{worker_id}", str(seq).encode()
                )
            elif mix == 5:
                client.kv_store_get(f"cp/{worker_id}")
            elif mix == 6:
                client.report_used_resource(50.0, 1024.0)
            else:
                client.report_diagnosis_data(
                    "trace_spans", {"spans": []}
                )
            lat.append(time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 — count, keep driving
            errors.append(f"{type(e).__name__}: {e}"[:120])

    def drive(self, duration_s: float, threads: Optional[int] = None):
        """Closed-loop load for ``duration_s`` from ``threads`` driver
        threads (default: all). Returns (rpc_latencies, errors,
        lease_ok_count, wall_s)."""
        n = min(threads or len(self._clients), len(self._clients))
        stop_at = time.monotonic() + duration_s
        lats: List[List[float]] = [[] for _ in range(n)]
        errs: List[List[str]] = [[] for _ in range(n)]
        leases: List[List[int]] = [[] for _ in range(n)]

        def loop(i: int):
            client = self._clients[i]
            my_workers = self._slices[i]
            seq = 0
            while time.monotonic() < stop_at:
                worker = my_workers[seq % len(my_workers)]
                self._one_cycle(
                    client, worker, seq, lats[i], errs[i], leases[i]
                )
                seq += 1

        t_start = time.monotonic()
        ts = [
            threading.Thread(target=loop, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.monotonic() - t_start
        flat = [x for part in lats for x in part]
        flat_err = [x for part in errs for x in part]
        lease_count = sum(len(part) for part in leases)
        return flat, flat_err, lease_count, wall


def _percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(int(q * len(ordered)), len(ordered) - 1)
    return ordered[idx]


# ---------------------------------------------------------------------------
# Phases
# ---------------------------------------------------------------------------


def _ramp_phase(master: SimMaster, pool: _SimWorkerPool,
                cfg: ControlPlaneSoakConfig) -> Dict:
    """Concurrency-doubling closed loop; the knee defines max
    sustainable RPCs/s."""
    tm = master.servicer.telemetry
    stages = []
    best_rps = 0.0
    prev_rps = 0.0
    concurrency = 1
    for _stage in range(cfg.max_stages):
        n_threads = min(concurrency, cfg.driver_threads)
        rpcs_before = tm.rpcs_total()
        cpu_before = tm.cpu_seconds_total()
        lat, errors, _leases, wall = pool.drive(
            cfg.stage_duration_s, threads=n_threads
        )
        rpcs = tm.rpcs_total() - rpcs_before
        cpu = tm.cpu_seconds_total() - cpu_before
        rps = rpcs / max(wall, 1e-9)
        p99 = _percentile(lat, 0.99)
        stage = {
            "threads": n_threads,
            "rpcs": rpcs,
            "rps": round(rps, 1),
            "client_p50_s": round(_percentile(lat, 0.5), 6),
            "client_p99_s": round(p99, 6),
            "errors": len(errors),
            "cpu_s_per_1k_rpcs": round(cpu / max(rpcs / 1000.0, 1e-9), 4),
        }
        stages.append(stage)
        saturated = p99 > cfg.knee_p99_s or (
            prev_rps > 0
            and rps < prev_rps * (1.0 + cfg.knee_gain_frac)
        )
        if p99 <= cfg.knee_p99_s:
            best_rps = max(best_rps, rps)
        prev_rps = rps
        if saturated or n_threads >= cfg.driver_threads:
            break
        concurrency *= 2
    if best_rps <= 0 and stages:
        # Every stage was past the p99 knee (slow shared box): the
        # best achieved closed-loop throughput is still the honest
        # capacity number — 0 would read as a broken master.
        best_rps = max(s["rps"] for s in stages)
    total_rpcs = tm.rpcs_total()
    total_cpu = tm.cpu_seconds_total()
    return {
        "stages": stages,
        "max_sustainable_rps": round(best_rps, 1),
        "cpu_s_per_1k_rpcs": round(
            total_cpu / max(total_rpcs / 1000.0, 1e-9), 4
        ),
        "inflight_high_water": tm.high_water(),
    }


def _quorum_phase(master: SimMaster, pool: _SimWorkerPool,
                  cfg: ControlPlaneSoakConfig) -> Dict:
    """Time-to-quorum per world size: every rank joins over the wire,
    then one ``get_comm_world`` completes the round."""
    out = {}
    for world in cfg.quorum_worlds:
        mgr = master.fresh_rdzv(world)
        clients = pool._clients  # noqa: SLF001 — same harness
        n = len(clients)
        quorum_hist = mgr._metrics["quorum"]  # noqa: SLF001
        sum_before = quorum_hist.sum(rdzv=RendezvousName.TRAINING)
        t0 = time.monotonic()

        def join_range(i: int):
            client = clients[i]
            for rank in range(i, world, n):  # noqa: B023 — joined below
                client._node_id = rank  # noqa: SLF001
                client.join_rendezvous(
                    rank, 1, RendezvousName.TRAINING
                )

        ts = [
            threading.Thread(target=join_range, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # One get completes the round (the manager forms the world on
        # query once all ranks wait) — poll bounded for robustness.
        formed = {}
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            clients[0]._node_id = 0  # noqa: SLF001
            _round, _group, formed, _order, _groups = (
                clients[0].get_comm_world(RendezvousName.TRAINING, 0)
            )
            if len(formed) >= world:
                break
            time.sleep(0.01)
        wall = time.monotonic() - t0
        if len(formed) != world:
            raise ControlPlaneInvariantError(
                f"world {world}: quorum never formed "
                f"({len(formed)}/{world})"
            )
        # The family is registry-global and cumulative across rounds;
        # ONE round landed for this world, so the sum delta is its
        # exact server-side first-join -> completion time.
        server_s = (
            quorum_hist.sum(rdzv=RendezvousName.TRAINING) - sum_before
        )
        out[str(world)] = {
            "time_to_quorum_s": round(server_s, 4),
            "wall_with_client_s": round(wall, 4),
        }
        logger.info(
            "control_plane quorum world=%d: server %.3fs wall %.3fs",
            world, server_s, wall,
        )
    return out


def _shed_phase(master: SimMaster, pool: _SimWorkerPool,
                cfg: ControlPlaneSoakConfig) -> Dict:
    """Force the governor into shedding and drive lease + rendezvous +
    diagnostic traffic concurrently; the ordering law is asserted by
    ``_check_shed_correctness`` afterwards."""
    state_before = master.servicer.control_plane_state()
    shed_before = dict(state_before["overload"]["shed_total"])
    prev_latency_high = state_before["overload"]["latency_high_s"]
    # Watermark at zero latency: the very next observe() escalates to
    # level 2 (load factor = ewma/1e-9 >> level2_factor), so both
    # diagnostic AND telemetry classes shed while every critical verb
    # keeps flowing — the deterministic worst case.
    master.governor.set_thresholds(latency_high_s=1e-9)
    try:
        _lat, errors, lease_count, _wall = pool.drive(
            cfg.shed_duration_s
        )
    finally:
        master.governor.set_thresholds(
            latency_high_s=prev_latency_high
        )
    state = master.servicer.control_plane_state()
    shed_after = state["overload"]["shed_total"]
    return {
        "level_reached": state["overload"]["level"],
        "shed_diagnostic": (
            shed_after["diagnostic"] - shed_before["diagnostic"]
        ),
        "shed_telemetry": (
            shed_after["telemetry"] - shed_before["telemetry"]
        ),
        "lease_rpcs_during_shed": lease_count,
        "client_errors": len(errors),
    }


# ---------------------------------------------------------------------------
# Invariants
# ---------------------------------------------------------------------------


def _check_shed_correctness(master: SimMaster, shed_report: Dict):
    if shed_report["shed_diagnostic"] <= 0:
        raise ControlPlaneInvariantError(
            "shed stage shed zero diagnostic RPCs — governor never "
            "engaged"
        )
    if shed_report["lease_rpcs_during_shed"] <= 0:
        raise ControlPlaneInvariantError(
            "no lease RPCs completed during the shed window"
        )
    if shed_report["client_errors"] > 0:
        raise ControlPlaneInvariantError(
            f"{shed_report['client_errors']} client errors during "
            "shed — critical verbs must keep succeeding"
        )
    sheddable = DIAGNOSTIC_VERBS | TELEMETRY_VERBS
    dropped = master.servicer.telemetry.dropped
    for _name, labels, value in dropped.samples():
        verb = labels.get("verb", "")
        if value > 0 and verb not in sheddable:
            raise ControlPlaneInvariantError(
                f"critical verb {verb!r} was shed {value:.0f}x — "
                "the ordering law (diagnostics before data, data "
                "never before leases) is broken"
            )


def _check_buffers(master: SimMaster) -> Dict:
    buffers = master.servicer.control_plane_state()["buffers"]
    if not buffers:
        raise ControlPlaneInvariantError("no bounded buffers reported")
    for name, stats in buffers.items():
        if "occupancy" not in stats or "drops" not in stats:
            raise ControlPlaneInvariantError(
                f"buffer {name!r} does not report occupancy + drops: "
                f"{sorted(stats)}"
            )
    return {
        name: {"occupancy": s["occupancy"], "drops": s["drops"]}
        for name, s in buffers.items()
    }


def _check_metric_span_agreement(
    master: SimMaster, cfg: ControlPlaneSoakConfig
) -> Dict:
    """Per-verb mean latency: histogram vs ``master.<verb>`` server
    spans, same run. The metric family is process-global, so counts
    and sums are DELTAS against the baseline snapshotted at SimMaster
    construction; only verbs whose populations then match the per-run
    span aggregator are comparable (a handler error is counted by
    both; a no-handler request opens no span)."""
    span_means = master.span_agg.means()
    seconds = master.servicer.telemetry.seconds
    checked = {}
    worst = 0.0
    for verb, (span_n, span_mean) in span_means.items():
        base_n, base_sum = master.seconds_baseline.get(verb, (0.0, 0.0))
        metric_n = int(seconds.count(verb=verb) - base_n)
        if metric_n != span_n or metric_n < cfg.agree_min_count:
            continue
        metric_mean = (seconds.sum(verb=verb) - base_sum) / metric_n
        rel = abs(metric_mean - span_mean) / max(span_mean, 1e-12)
        worst = max(worst, rel)
        checked[verb] = {
            "count": metric_n,
            "metric_mean_s": round(metric_mean, 7),
            "span_mean_s": round(span_mean, 7),
            "rel_diff": round(rel, 4),
        }
        if rel > cfg.agree_tolerance:
            raise ControlPlaneInvariantError(
                f"verb {verb}: metric mean {metric_mean:.6f}s vs span "
                f"mean {span_mean:.6f}s differ {rel:.1%} "
                f"(> {cfg.agree_tolerance:.0%})"
            )
    if not checked:
        raise ControlPlaneInvariantError(
            "metric/span agreement had nothing to compare — tracing "
            "was not armed or every verb was below the count floor"
        )
    return {"verbs_checked": len(checked), "worst_rel_diff":
            round(worst, 4), "detail": checked}


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def run_control_plane_soak(
    cfg: Optional[ControlPlaneSoakConfig] = None,
) -> Dict:
    cfg = cfg or ControlPlaneSoakConfig()
    master = SimMaster(cfg)
    pool = None
    t0 = time.monotonic()
    try:
        # Inside the try: SimMaster already armed a global tracer and
        # muted the logger — a pool-construction failure must not leak
        # them into the rest of the process (the fleet_soak bug class).
        pool = _SimWorkerPool(master.addr, cfg)
        ramp = _ramp_phase(master, pool, cfg)
        quorum = _quorum_phase(master, pool, cfg)
        shed = _shed_phase(master, pool, cfg)

        _check_shed_correctness(master, shed)
        buffers = _check_buffers(master)
        agreement = _check_metric_span_agreement(master, cfg)

        state = master.servicer.control_plane_state()
        report = {
            "workers": cfg.workers,
            "driver_threads": cfg.driver_threads,
            "max_sustainable_rps": ramp["max_sustainable_rps"],
            "cpu_s_per_1k_rpcs": ramp["cpu_s_per_1k_rpcs"],
            "inflight_high_water": ramp["inflight_high_water"],
            "stages": ramp["stages"],
            "quorum": quorum,
            "shed": shed,
            "buffers": buffers,
            "metric_span_agreement": agreement,
            "rpcs_total": state["rpc"]["rpcs_total"],
            "dispatch_p99_s": (
                state["buffers"]
                .get("task_queues", {})
                .get("dispatch_p99_s")
            ),
            "elapsed_s": round(time.monotonic() - t0, 2),
            "invariants": "pass",
        }
        return report
    finally:
        if pool is not None:
            pool.close()
        master.close()
