"""Operational chaos / fault-injection harness.

Parity: reference examples/pytorch/mnist/start_chaos.sh:18-30 (the
kill-a-random-worker loop used to demo fault tolerance on a live
deployment). Three injection surfaces:

- ``local``: find the job's worker processes on this host (by the
  DLROVER_TPU_* env the agent injects) and SIGKILL one per interval —
  drives the agent's restart/rendezvous/flash-restore path on a real
  run, exactly like a host fault.
- ``k8s``: delete a random worker pod of the job through the K8sApi —
  drives the master's relaunch path (and block relaunch when
  node groups are on).
- probe rigging (env, no CLI): DLROVER_TPU_CHAOS_CHECK_FAIL_RANKS /
  _SLOW_RANKS make specific ranks fail or straggle the network check
  (agent/node_check_worker.py), driving bisection/eviction.

Usage::

    python -m dlrover_tpu.testing.chaos --job myjob --interval 60
    python -m dlrover_tpu.testing.chaos --mode k8s --job myjob \\
        --namespace default --rounds 5
"""

import argparse
import os
import random
import signal
import time
from typing import List, Optional, Tuple

from dlrover_tpu.common.constants import NodeEnv, WorkerEnv
from dlrover_tpu.common.log import logger


def _read_environ(pid: str) -> dict:
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            raw = f.read()
    except OSError:
        return {}
    env = {}
    for entry in raw.split(b"\0"):
        if b"=" in entry:
            k, _, v = entry.partition(b"=")
            env[k.decode(errors="replace")] = v.decode(errors="replace")
    return env


def find_local_workers(job_name: str) -> List[Tuple[int, int]]:
    """(pid, process_id) of the job's training workers on this host.
    Workers are the processes carrying the agent-injected PROCESS_ID;
    the agent/master themselves don't, so they are never targets."""
    me = os.getpid()
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        env = _read_environ(pid)
        if env.get(NodeEnv.JOB_NAME) != job_name:
            continue
        if WorkerEnv.PROCESS_ID not in env:
            continue
        out.append((int(pid), int(env[WorkerEnv.PROCESS_ID])))
    return sorted(out)


def kill_one_local(job_name: str, sig: int = signal.SIGKILL) -> Optional[int]:
    workers = find_local_workers(job_name)
    if not workers:
        logger.info("chaos: no local workers of job %s found", job_name)
        return None
    pid, proc_id = random.choice(workers)
    logger.warning(
        "chaos: killing worker process_id=%d pid=%d (sig %d)",
        proc_id,
        pid,
        sig,
    )
    try:
        os.kill(pid, sig)
        return pid
    except ProcessLookupError:
        return None


def delete_one_pod(
    job_name: str, namespace: str = "default", api=None
) -> Optional[str]:
    from dlrover_tpu.master.scheduler.k8s_client import get_k8s_api

    api = api or get_k8s_api()
    pods = [
        p["metadata"]["name"]
        for p in api.list_pods(namespace, f"job-name={job_name}")
        if p.get("metadata", {}).get("labels", {}).get("role")
        != "dlrover-master"
        and p.get("status", {}).get("phase") == "Running"
    ]
    if not pods:
        logger.info("chaos: no running worker pods of %s", job_name)
        return None
    victim = random.choice(pods)
    logger.warning("chaos: deleting pod %s", victim)
    api.delete_pod(namespace, victim)
    return victim


def run_chaos(
    job_name: str,
    mode: str = "local",
    interval_s: float = 60.0,
    rounds: int = 0,
    namespace: str = "default",
    seed: Optional[int] = None,
):
    """Kill loop: one victim per interval; rounds=0 runs forever."""
    if seed is not None:
        random.seed(seed)
    n = 0
    while rounds <= 0 or n < rounds:
        if mode == "k8s":
            delete_one_pod(job_name, namespace)
        else:
            kill_one_local(job_name)
        n += 1
        if rounds > 0 and n >= rounds:
            break
        time.sleep(interval_s)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="chaos harness")
    parser.add_argument("--job", required=True, help="job name to attack")
    parser.add_argument("--mode", choices=["local", "k8s"], default="local")
    parser.add_argument("--interval", type=float, default=60.0)
    parser.add_argument(
        "--rounds", type=int, default=0, help="0 = run until stopped"
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args(argv)
    run_chaos(
        args.job,
        mode=args.mode,
        interval_s=args.interval,
        rounds=args.rounds,
        namespace=args.namespace,
        seed=args.seed,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
