"""Control-plane RPC transport.

Two unary methods — ``get`` and ``report`` — carrying an opaque pickled
:class:`dlrover_tpu.common.comm.Message` envelope, mirroring the
reference's wire protocol (proto/elastic_training.proto:26-29,
master/servicer.py:912 GrpcMasterServicer, elastic_agent/master_client.py).

Implemented with gRPC *generic* method handlers so no protoc-generated stubs
are required; bytes in, bytes out. An HTTP transport with the same two-verb
surface is provided for environments without gRPC (reference
servicer.py:994 HttpMasterServicer).
"""

import abc
import http.client
import os
import signal
import socket
import threading
import time
from concurrent import futures
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

import grpc

from dlrover_tpu.common.comm import Message
from dlrover_tpu.common.log import logger

SERVICE_NAME = "dlrover_tpu.Master"
GET_METHOD = f"/{SERVICE_NAME}/get"
REPORT_METHOD = f"/{SERVICE_NAME}/report"

GRPC_MAX_MESSAGE = 512 * 1024 * 1024  # checkpoints metadata can be chunky

# wait_ready is bounded on both stubs (blocking-wait audit, ISSUE 5):
# the default below caps how long a worker stalls on an absent master,
# and every expiry ticks a counter so "could not reach the master in
# time" shows up on /metrics instead of only in scattered caller logs.
WAIT_READY_TIMEOUT_S = 60.0

# Env-tunable socket phases for the HTTP stub: connect (TCP handshake
# to the master) and read (waiting on a reply over an established
# connection) fail differently — a hung master accepts connections and
# then never answers, so a single coarse timeout either stalls workers
# or flakes connects. Either unset falls back to the stub's ctor
# timeout; a hung master then surfaces as a bounded socket.timeout (a
# retryable transport error) instead of a stuck thread.
CONNECT_TIMEOUT_ENV = "DLROVER_TPU_RPC_CONNECT_TIMEOUT_S"
READ_TIMEOUT_ENV = "DLROVER_TPU_RPC_READ_TIMEOUT_S"


def _env_timeout(name: str) -> Optional[float]:
    raw = os.getenv(name, "")
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return None
    return val if val > 0 else None


def _wait_ready_expired_counter():
    from dlrover_tpu.observability.registry import default_registry

    return default_registry().counter(
        "rpc_wait_ready_expired_total",
        "bounded master wait_ready calls that timed out",
    )


class MasterService(abc.ABC):
    """What a master must implement to be served over any transport."""

    @abc.abstractmethod
    def get(self, message: Message) -> Message:
        ...

    @abc.abstractmethod
    def report(self, message: Message) -> Message:
        ...


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, service: MasterService):
        self._service = service

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == GET_METHOD:
            return grpc.unary_unary_rpc_method_handler(self._handle_get)
        if method == REPORT_METHOD:
            return grpc.unary_unary_rpc_method_handler(self._handle_report)
        return None

    def _handle_get(self, request: bytes, context) -> bytes:
        try:
            msg = Message.deserialize(request)
            return self._service.get(msg).serialize()
        except Exception:
            logger.exception("error handling get RPC")
            context.abort(grpc.StatusCode.INTERNAL, "get failed")

    def _handle_report(self, request: bytes, context) -> bytes:
        try:
            msg = Message.deserialize(request)
            return self._service.report(msg).serialize()
        except Exception:
            logger.exception("error handling report RPC")
            context.abort(grpc.StatusCode.INTERNAL, "report failed")


class GrpcMasterServer:
    def __init__(self, port: int, service: MasterService, max_workers: int = 64):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE),
            ],
        )
        self._server.add_generic_rpc_handlers([_GenericHandler(service)])
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        if self.port == 0:
            raise RuntimeError(f"failed to bind master RPC port {port}")

    def start(self):
        self._server.start()

    def stop(self, grace: float = 1.0):
        self._server.stop(grace)


class GrpcMasterStub:
    """Client side of the two-verb protocol."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._addr = addr
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE),
                # No transparent transport retries: mutations (kv add,
                # rendezvous join) must be applied at most once per call.
                ("grpc.enable_retries", 0),
            ],
        )
        self._get = self._channel.unary_unary(GET_METHOD)
        self._report = self._channel.unary_unary(REPORT_METHOD)

    def get(self, message: Message, timeout: Optional[float] = None) -> Message:
        data = self._get(message.serialize(), timeout=timeout or self._timeout)
        return Message.deserialize(data)

    def report(self, message: Message, timeout: Optional[float] = None) -> Message:
        data = self._report(
            message.serialize(), timeout=timeout or self._timeout
        )
        return Message.deserialize(data)

    def wait_ready(self, timeout: float = WAIT_READY_TIMEOUT_S) -> bool:
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            return True
        except grpc.FutureTimeoutError:
            _wait_ready_expired_counter().inc()
            return False

    def close(self):
        self._channel.close()


# --------------------------------------------------------------------------
# HTTP transport (same two-verb surface, stdlib only)
# --------------------------------------------------------------------------


class _HttpHandler(BaseHTTPRequestHandler):
    service: MasterService = None  # class attr injected by server factory
    # HTTP/1.1: responses carry Content-Length (set below) and the
    # connection stays open between requests — required for the stub's
    # keep-alive to actually keep anything alive (1.0 closes per call).
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            msg = Message.deserialize(body)
            if self.path == "/get":
                resp = self.service.get(msg)
            elif self.path == "/report":
                resp = self.service.report(msg)
            else:
                self.send_error(404)
                return
            payload = resp.serialize()
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except Exception:
            logger.exception("error handling HTTP RPC %s", self.path)
            self.send_error(500)


class _FleetHTTPServer(ThreadingHTTPServer):
    # The stdlib default listen backlog is 5: a fleet of workers (or a
    # rendezvous storm of 1k joiners) opening connections together gets
    # its SYNs dropped and the clients burn ~1s retry backoffs — the
    # §32 load harness measured exactly that. 128 rides the kernel's
    # somaxconn clamp.
    request_queue_size = 128

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._open_conns: set = set()
        self._conns_mu = threading.Lock()

    def process_request_thread(self, request, client_address):
        with self._conns_mu:
            self._open_conns.add(request)
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._conns_mu:
                self._open_conns.discard(request)

    def close_open_connections(self):
        """Sever established keep-alive connections. shutdown() only
        stops the accept loop — handler threads parked on persistent
        client connections would otherwise keep answering for a stopped
        master generation (epoch fencing, DESIGN.md §37: a stub must
        fail over to the restarted master, not a zombie thread)."""
        with self._conns_mu:
            conns = list(self._open_conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class HttpMasterServer:
    def __init__(self, port: int, service: MasterService):
        handler = type("BoundHandler", (_HttpHandler,), {"service": service})
        self._httpd = _FleetHTTPServer(("", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._service = service
        self._shutdown_hooks: List[Callable[[], None]] = []
        self._stopped = False

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-master"
        )
        self._thread.start()

    def add_shutdown_hook(self, fn: Callable[[], None]):
        """Run ``fn`` during graceful_stop AFTER in-flight requests have
        drained — the journal flush/close hook goes here so the last
        handled verb's records are durable before the process exits."""
        self._shutdown_hooks.append(fn)

    def graceful_stop(self, drain_s: float = 5.0):
        """SIGTERM-quality shutdown (DESIGN.md §37): stop accepting new
        connections, wait (bounded) for in-flight handlers to drain,
        then run shutdown hooks (journal flush+fsync) and close. Idem-
        potent; plain stop() remains the abrupt path."""
        if self._stopped:
            return
        self._stopped = True
        # shutdown() stops the accept loop; handler threads already
        # spawned by ThreadingHTTPServer keep running their request.
        self._httpd.shutdown()
        inflight = getattr(
            getattr(self._service, "telemetry", None), "inflight_now", None
        )
        if callable(inflight):
            deadline = time.monotonic() + max(drain_s, 0.0)
            while inflight() > 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            left = inflight()
            if left:
                logger.warning(
                    "graceful stop: %d RPCs still in flight after %.1fs "
                    "drain window",
                    left,
                    drain_s,
                )
        for hook in self._shutdown_hooks:
            try:
                hook()
            except Exception:
                logger.exception("shutdown hook %s failed", hook)
        self._httpd.close_open_connections()
        self._httpd.server_close()

    def install_sigterm_handler(self, drain_s: float = 5.0):
        """Route SIGTERM to graceful_stop (main thread only; signal
        module refuses elsewhere). Chains to any previous handler so
        process-level cleanup still runs."""
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            self.graceful_stop(drain_s=drain_s)
            if callable(prev) and prev not in (
                signal.SIG_IGN,
                signal.SIG_DFL,
            ):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, _on_term)

    def stop(self, grace: float = 1.0):
        if self._stopped:
            return
        self._stopped = True
        self._httpd.shutdown()
        self._httpd.close_open_connections()
        self._httpd.server_close()


class HttpMasterStub:
    """Keep-alive client: one persistent TCP connection per calling
    thread (http.client connections are not thread-safe, and the
    prefetcher/heartbeat/training threads all share a stub), reconnecting
    on error. The old connection-per-call behavior cost a TCP handshake
    on every control RPC — measurable at the data path's per-shard
    cadence."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._host, port = addr.rsplit(":", 1)
        self._port = int(port)
        self._timeout = timeout
        # Env overrides (read once at construction so a long-lived stub
        # is consistent): connect bounds the TCP handshake, read bounds
        # each wait for reply bytes on the established socket.
        self._connect_timeout = _env_timeout(CONNECT_TIMEOUT_ENV)
        self._read_timeout = _env_timeout(READ_TIMEOUT_ENV)
        self._local = threading.local()
        self._closed = False

    def _connection(self, timeout=None):
        """(conn, reused): reused tells the caller whether a failure may
        be a stale keep-alive socket rather than a dead master."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            if (
                self._read_timeout is not None
                and getattr(conn, "sock", None) is None
            ):
                # The peer closed the keep-alive socket. With split
                # timeouts, http.client's silent auto-reconnect would
                # stamp the (short) connect timeout on the new socket
                # and apply it to reads — rebuild through the eager-
                # connect path below instead.
                self._drop_connection()
            else:
                return conn, True
        base = timeout or self._timeout
        conn = http.client.HTTPConnection(
            self._host, self._port,
            timeout=self._connect_timeout or base,
        )
        # http.client stamps the connection timeout onto the socket at
        # connect(); connecting eagerly here lets the read phase get its
        # own (usually longer) bound — a master that accepts but never
        # answers surfaces as socket.timeout instead of a stuck thread.
        read_timeout = self._read_timeout or base
        if read_timeout != (self._connect_timeout or base):
            conn.connect()
            conn.sock.settimeout(read_timeout)
        self._local.conn = conn
        return conn, False

    def _drop_connection(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    # A reused connection dying with one of these before any response
    # bytes means the server idled the socket out before reading the
    # request — it was never processed, so ONE transparent retry on a
    # fresh connection preserves at-most-once semantics. Anything else
    # (or the same failure on a fresh connection) propagates: mutations
    # must not be transparently re-sent (mirrors the gRPC stub's
    # enable_retries=0).
    _STALE_ERRORS = (
        http.client.RemoteDisconnected,
        http.client.BadStatusLine,
        BrokenPipeError,
        ConnectionResetError,
    )

    def _call(self, path: str, message: Message, timeout=None) -> Message:
        body = message.serialize()
        for attempt in (1, 2):
            if attempt > 1:
                # The transparent stale-keep-alive re-send below is the
                # SAME logical RPC: the active span (opened by the
                # client's retry wrapper or any caller) records it as
                # an incremented retry attr, never a sibling span — so
                # the at-most-once story stays legible in one trace.
                from dlrover_tpu.observability import tracing

                tracing.bump_current("retry")
            conn, reused = self._connection(timeout)
            try:
                conn.request("POST", path, body=body)
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    raise RuntimeError(
                        f"RPC {path} failed: HTTP {resp.status}"
                    )
                return Message.deserialize(resp.read())
            except RuntimeError:
                self._drop_connection()
                raise
            except self._STALE_ERRORS:
                self._drop_connection()
                if not reused or self._closed:
                    raise
            except Exception:
                self._drop_connection()
                raise
        raise RuntimeError(f"RPC {path} failed after reconnect")

    def get(self, message: Message, timeout=None) -> Message:
        return self._call("/get", message, timeout)

    def report(self, message: Message, timeout=None) -> Message:
        return self._call("/report", message, timeout)

    def wait_ready(self, timeout: float = WAIT_READY_TIMEOUT_S) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self._call("/get", Message())
                return True
            except Exception:
                time.sleep(0.5)
        _wait_ready_expired_counter().inc()
        return False

    def close(self):
        self._closed = True
        self._drop_connection()


def create_master_server(port: int, service: MasterService, kind: str = "grpc"):
    if kind == "http":
        return HttpMasterServer(port, service)
    return GrpcMasterServer(port, service)


def build_master_stub(addr: str, kind: str = "grpc", timeout: float = 10.0):
    if kind == "http":
        return HttpMasterStub(addr, timeout)
    return GrpcMasterStub(addr, timeout)
