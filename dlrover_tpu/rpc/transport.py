"""Control-plane RPC transport.

Two unary methods — ``get`` and ``report`` — carrying an opaque pickled
:class:`dlrover_tpu.common.comm.Message` envelope, mirroring the
reference's wire protocol (proto/elastic_training.proto:26-29,
master/servicer.py:912 GrpcMasterServicer, elastic_agent/master_client.py).

Implemented with gRPC *generic* method handlers so no protoc-generated stubs
are required; bytes in, bytes out. An HTTP transport with the same two-verb
surface is provided for environments without gRPC (reference
servicer.py:994 HttpMasterServicer).
"""

import abc
import http.client
import threading
import time
from concurrent import futures
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import grpc

from dlrover_tpu.common.comm import Message
from dlrover_tpu.common.log import logger

SERVICE_NAME = "dlrover_tpu.Master"
GET_METHOD = f"/{SERVICE_NAME}/get"
REPORT_METHOD = f"/{SERVICE_NAME}/report"

GRPC_MAX_MESSAGE = 512 * 1024 * 1024  # checkpoints metadata can be chunky


class MasterService(abc.ABC):
    """What a master must implement to be served over any transport."""

    @abc.abstractmethod
    def get(self, message: Message) -> Message:
        ...

    @abc.abstractmethod
    def report(self, message: Message) -> Message:
        ...


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, service: MasterService):
        self._service = service

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == GET_METHOD:
            return grpc.unary_unary_rpc_method_handler(self._handle_get)
        if method == REPORT_METHOD:
            return grpc.unary_unary_rpc_method_handler(self._handle_report)
        return None

    def _handle_get(self, request: bytes, context) -> bytes:
        try:
            msg = Message.deserialize(request)
            return self._service.get(msg).serialize()
        except Exception:
            logger.exception("error handling get RPC")
            context.abort(grpc.StatusCode.INTERNAL, "get failed")

    def _handle_report(self, request: bytes, context) -> bytes:
        try:
            msg = Message.deserialize(request)
            return self._service.report(msg).serialize()
        except Exception:
            logger.exception("error handling report RPC")
            context.abort(grpc.StatusCode.INTERNAL, "report failed")


class GrpcMasterServer:
    def __init__(self, port: int, service: MasterService, max_workers: int = 64):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE),
            ],
        )
        self._server.add_generic_rpc_handlers([_GenericHandler(service)])
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        if self.port == 0:
            raise RuntimeError(f"failed to bind master RPC port {port}")

    def start(self):
        self._server.start()

    def stop(self, grace: float = 1.0):
        self._server.stop(grace)


class GrpcMasterStub:
    """Client side of the two-verb protocol."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._addr = addr
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE),
                # No transparent transport retries: mutations (kv add,
                # rendezvous join) must be applied at most once per call.
                ("grpc.enable_retries", 0),
            ],
        )
        self._get = self._channel.unary_unary(GET_METHOD)
        self._report = self._channel.unary_unary(REPORT_METHOD)

    def get(self, message: Message, timeout: Optional[float] = None) -> Message:
        data = self._get(message.serialize(), timeout=timeout or self._timeout)
        return Message.deserialize(data)

    def report(self, message: Message, timeout: Optional[float] = None) -> Message:
        data = self._report(
            message.serialize(), timeout=timeout or self._timeout
        )
        return Message.deserialize(data)

    def wait_ready(self, timeout: float = 60.0) -> bool:
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            return True
        except grpc.FutureTimeoutError:
            return False

    def close(self):
        self._channel.close()


# --------------------------------------------------------------------------
# HTTP transport (same two-verb surface, stdlib only)
# --------------------------------------------------------------------------


class _HttpHandler(BaseHTTPRequestHandler):
    service: MasterService = None  # class attr injected by server factory

    def log_message(self, *args):  # quiet
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            msg = Message.deserialize(body)
            if self.path == "/get":
                resp = self.service.get(msg)
            elif self.path == "/report":
                resp = self.service.report(msg)
            else:
                self.send_error(404)
                return
            payload = resp.serialize()
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except Exception:
            logger.exception("error handling HTTP RPC %s", self.path)
            self.send_error(500)


class HttpMasterServer:
    def __init__(self, port: int, service: MasterService):
        handler = type("BoundHandler", (_HttpHandler,), {"service": service})
        self._httpd = ThreadingHTTPServer(("", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-master"
        )
        self._thread.start()

    def stop(self, grace: float = 1.0):
        self._httpd.shutdown()
        self._httpd.server_close()


class HttpMasterStub:
    def __init__(self, addr: str, timeout: float = 10.0):
        self._host, port = addr.rsplit(":", 1)
        self._port = int(port)
        self._timeout = timeout

    def _call(self, path: str, message: Message, timeout=None) -> Message:
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=timeout or self._timeout
        )
        try:
            conn.request("POST", path, body=message.serialize())
            resp = conn.getresponse()
            if resp.status != 200:
                raise RuntimeError(f"RPC {path} failed: HTTP {resp.status}")
            return Message.deserialize(resp.read())
        finally:
            conn.close()

    def get(self, message: Message, timeout=None) -> Message:
        return self._call("/get", message, timeout)

    def report(self, message: Message, timeout=None) -> Message:
        return self._call("/report", message, timeout)

    def wait_ready(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self._call("/get", Message())
                return True
            except Exception:
                time.sleep(0.5)
        return False

    def close(self):
        pass


def create_master_server(port: int, service: MasterService, kind: str = "grpc"):
    if kind == "http":
        return HttpMasterServer(port, service)
    return GrpcMasterServer(port, service)


def build_master_stub(addr: str, kind: str = "grpc", timeout: float = 10.0):
    if kind == "http":
        return HttpMasterStub(addr, timeout)
    return GrpcMasterStub(addr, timeout)
