"""dlrover_tpu: a TPU-native elastic training operations framework.

A from-scratch rebuild of the capabilities of DLRover (reference:
/root/reference, Mu-L/dlrover) designed for JAX/XLA on TPU slices:

- Job master (per-job control plane): rendezvous, node lifecycle, dynamic
  data sharding, diagnosis, auto-scaling.
- Elastic agent (per-host control plane): supervises JAX worker processes,
  injects ``jax.distributed`` coordination env, restarts/relaunches on
  failure, hosts the async flash-checkpoint saver.
- Flash checkpoint: JAX pytrees -> host shared memory in O(100ms), async
  persist to storage, memory-first resume, resharding restore across mesh
  changes.
- Node/network check: MXU matmul + ICI/DCN collective probes with pairwise
  fault isolation and straggler detection.
- Training stack: models/, ops/ (Pallas kernels), parallel/ (dp/fsdp/tp/
  pp/sp/ep shardings over ``jax.sharding.Mesh``).

The control plane mirrors the reference's layering (SURVEY.md section 1) but
every data-plane mechanism is JAX-idiomatic rather than a port.
"""

__version__ = "0.1.0"
