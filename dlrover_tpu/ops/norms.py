"""Normalization ops.

RMSNorm computed in float32 regardless of input dtype (bf16-safe on TPU:
the reduction runs in f32 on the VPU, the scale-multiply fuses into the
surrounding matmul epilogue under XLA).
"""

import jax.numpy as jnp
from jax import lax


def rms_norm(x, scale, eps: float = 1e-6):
    """RMSNorm with a (1 + scale) parameterization (zero-init friendly)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * lax.rsqrt(var + eps)
    return (normed * (1.0 + scale.astype(jnp.float32))).astype(dtype)
