"""Attention ops (XLA path).

Layout convention throughout the framework: [batch, seq, heads, head_dim]
("BSHD"). GQA is supported by ``kv_heads <= heads``; KV heads are
broadcast by reshape, never materialized ``heads/kv_heads`` times — XLA
keeps the broadcast virtual inside the einsum.

The Pallas flash kernel (ops/pallas_attention.py) and the ring-attention
shard_map island (ops/ring_attention.py) share this op's semantics; tests
cross-check all three.
"""

from typing import Optional

import jax.numpy as jnp

NEG_INF = -2.0 ** 30  # large-but-finite: avoids NaN from (-inf) - (-inf)


def dot_product_attention(
    q,
    k,
    v,
    causal: bool = True,
    q_positions=None,
    kv_positions=None,
    softmax_scale: Optional[float] = None,
):
    """Multi-head attention with optional GQA and causal masking.

    q: [b, sq, h, d]; k, v: [b, skv, hkv, d]. Positions (global token
    indices, shape [sq]/[skv] or per-row [b, sq]/[b, skv]) drive the
    causal mask, so sequence-parallel / packed callers pass the true
    offsets of their shards. Query rows with no visible key (a shard
    entirely in the future) produce exactly zero output, which is what
    ring attention's combine step requires.
    """
    b, sq, h, d = q.shape
    _, skv, hkv, _ = k.shape
    if h % hkv:
        raise ValueError(f"heads {h} not a multiple of kv_heads {hkv}")
    groups = h // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    q32 = (q * scale).astype(jnp.float32)
    qg = q32.reshape(b, sq, hkv, groups, d)
    # [b, hkv, g, sq, skv]
    logits = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k.astype(jnp.float32)
    )
    if causal:
        if q_positions is None:
            q_positions = jnp.arange(sq)
        if kv_positions is None:
            kv_positions = jnp.arange(skv)
        q_pos = jnp.broadcast_to(q_positions, (b, sq))
        kv_pos = jnp.broadcast_to(kv_positions, (b, skv))
        mask = q_pos[:, :, None] >= kv_pos[:, None, :]  # [b, sq, skv]
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    row_max = jnp.max(logits, axis=-1, keepdims=True)
    probs = jnp.exp(logits - row_max)
    probs = probs / (jnp.sum(probs, axis=-1, keepdims=True) + 1e-30)
    # fully-masked rows (row_max still at NEG_INF) must contribute zero,
    # not a uniform average of the illegal keys
    probs = jnp.where(row_max > NEG_INF / 2, probs, 0.0)
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32)
    )
    return out.reshape(b, sq, h, d).astype(q.dtype)
