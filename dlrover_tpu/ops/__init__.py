"""TPU-native ops: fused normalization, rotary embeddings, attention
(XLA fallback, Pallas flash kernel, ring attention for sequence
parallelism). All ops are pure functions over jnp arrays, safe under jit,
static shapes only.
"""

from dlrover_tpu.ops.norms import rms_norm  # noqa: F401
from dlrover_tpu.ops.rope import apply_rope, rope_frequencies  # noqa: F401
from dlrover_tpu.ops.attention import dot_product_attention  # noqa: F401
