"""Fused sort-based MoE dispatch: tile-blocked gather→GEMM→scatter
over the expert-sorted row order, Pallas.

The dropless MoE path (models/moe.py ``_dropless_core``) previously
moved every token copy through HBM **five** times around the grouped
matmuls: an XLA gather materializes the expert-sorted ``[m, d]`` copy
(write + read), megablox ``gmm`` reads it, the ``[m, 2f]`` silu
intermediate round-trips between the two gmm calls, and a second
``[m, d]`` gather unsorts the result for the combine. At the bench
shape (m = 32k rows, d = 1024) that is the difference between 24.9%
and >40% active-MFU — the MXU starves behind permutation traffic.

Here the permutation never touches HBM as data. The sorted row order
rides as a scalar-prefetch vector and the kernels walk per-expert row
segments in a group-aligned padded layout (every ``tile_m``-row tile
belongs to exactly ONE expert, megablox-style but with the gather
folded in):

- **forward** (one kernel): per tile, DMA the tile's source rows
  straight from the token-major input into VMEM (``row_ids`` names
  them; all ``tile_m`` copies are issued before the first wait, so the
  DMA engine pipelines the row reads), run gate|up GEMM → silu·mul →
  down GEMM on the MXU while the next tile's rows stream in, and DMA
  the result rows to their copy-major positions (``dest_ids``). One
  HBM read of x-rows, one HBM write of y-rows — nothing else.
- **backward** (custom VJP): the SAME permutation vectors drive three
  kernels — dx (gather x and dy rows, recompute h/a flash-style,
  chain through both GEMMs transposed, scatter dx rows), and two
  per-expert weight-gradient kernels that accumulate ``dw = lhsᵀ @
  rhs`` into expert-indexed output blocks (consecutive tiles of one
  expert revisit the same block, so the accumulator lives in VMEM).
  XLA's transpose-of-gather — a scatter-add, the dominant cost of the
  old backward — never appears.

The layout is static-shaped: ``m`` copies pad to
``(cdiv(m, tile_m) + n_groups) * tile_m`` slots (each expert wastes at
most one tile), padding slots carry ``row_id = -1`` and are masked to
zero rows / skipped scatters. Group sizes are data-dependent VALUES,
never shapes — the whole thing jits once.

VMEM budget note: the kernels hold one expert's weights (w_gu
``[d, 2f]``, w_down ``[f, d]``) plus ``tile_m``-row tiles in VMEM; at
the bench shape (d = f = 1024, bf16, tile_m = 128) the worst kernel
(dwgu: ``[d, 2f]`` f32 accumulator) sits at ~9 MB of the 16 MB core
budget. Larger mlp_dim wants an f-tiled grid axis — out of scope here.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def default_tile_m(m: int) -> int:
    """Row-tile size: the MXU-shaped 128 once there is enough work,
    the bf16 16-sublane minimum for test-sized inputs (covers the f32
    minimum of 8 too)."""
    return 128 if m >= 1024 else 16


def build_dispatch_layout(
    flat_expert, n_groups: int, tile_m: int, copies_per_src: int
):
    """Group-aligned padded layout for ``m`` copies sorted by expert.

    ``flat_expert`` [m] int32 holds each copy's routed group in
    ``[0, n_groups)``; entries >= n_groups are SENTINEL copies (the ep
    path's exchange padding) that get no slot. Returns

    - ``row_ids``  [m_pad]: source row (``sorted_copy // copies_per_src``)
      per padded slot, -1 on padding;
    - ``dest_ids`` [m_pad]: copy-major output row per slot, -1 on
      padding (= the sorted copy's original index, so the scatter IS
      the unsort);
    - ``tile_expert`` [T]: the one group every row of tile ``t``
      belongs to.

    with ``T = cdiv(m, tile_m) + n_groups + 1`` static (each group
    wastes < 1 tile; +1 absorbs sentinel copies) and
    ``m_pad = T * tile_m``. All data-dependent quantities are VALUES
    under jit — shapes depend only on ``m``/``tile_m``/``n_groups``.
    """
    m = flat_expert.shape[0]
    T = -(-m // tile_m) + n_groups + 1
    m_pad = T * tile_m
    fe = jnp.asarray(flat_expert, jnp.int32)
    # Sentinel copies sort into an internal trailing group and are
    # dropped from the slot scatter below.
    fe_int = jnp.minimum(fe, n_groups)
    order = jnp.argsort(fe_int, stable=True)              # [m]
    sorted_grp = fe_int[order]
    counts = jnp.bincount(fe_int, length=n_groups + 1)
    padded = -(-counts // tile_m) * tile_m
    # Every REAL group gets >= 1 tile even when empty: a dw output
    # block that no grid step visits would keep its backing buffer's
    # garbage — an empty expert's all-padding tile initializes it to
    # the zero gradient instead.
    padded = padded.at[:n_groups].max(tile_m)
    pad_off = jnp.cumsum(padded) - padded                 # [g+1]
    grp_start = jnp.cumsum(counts) - counts
    j = jnp.arange(m)
    slot = pad_off[sorted_grp] + (j - grp_start[sorted_grp])
    valid = sorted_grp < n_groups
    slot = jnp.where(valid, slot, m_pad)  # out of range -> dropped
    row_ids = jnp.full((m_pad,), -1, jnp.int32)
    dest_ids = jnp.full((m_pad,), -1, jnp.int32)
    src = (order // copies_per_src).astype(jnp.int32)
    row_ids = row_ids.at[slot].set(src, mode="drop")
    dest_ids = dest_ids.at[slot].set(
        order.astype(jnp.int32), mode="drop"
    )
    ends = jnp.cumsum(padded)
    tile_expert = jnp.searchsorted(
        ends, jnp.arange(T, dtype=jnp.int32) * tile_m, side="right"
    ).astype(jnp.int32)
    tile_expert = jnp.clip(tile_expert, 0, n_groups - 1)
    return row_ids, dest_ids, tile_expert


def _gather_rows(src_hbm, ids_ref, base, dst_ref, sems, tile_m):
    """DMA ``tile_m`` rows ``src_hbm[ids[base + r]]`` into ``dst_ref``;
    all copies start before the first wait so the DMA engine pipelines
    the row reads. Padding ids (< 0) fetch row 0 and are masked by the
    caller."""

    def start(r, _):
        rid = jnp.maximum(ids_ref[base + r], 0)
        pltpu.make_async_copy(
            src_hbm.at[rid], dst_ref.at[r], sems.at[r]
        ).start()
        return 0

    def wait(r, _):
        rid = jnp.maximum(ids_ref[base + r], 0)
        pltpu.make_async_copy(
            src_hbm.at[rid], dst_ref.at[r], sems.at[r]
        ).wait()
        return 0

    jax.lax.fori_loop(0, tile_m, start, 0)
    jax.lax.fori_loop(0, tile_m, wait, 0)


def _scatter_rows(src_ref, ids_ref, base, dst_hbm, sems, tile_m):
    """DMA rows of ``src_ref`` out to ``dst_hbm[ids[base + r]]``,
    skipping padding ids (< 0)."""

    def start(r, _):
        d = ids_ref[base + r]

        @pl.when(d >= 0)
        def _():
            pltpu.make_async_copy(
                src_ref.at[r], dst_hbm.at[d], sems.at[r]
            ).start()
        return 0

    def wait(r, _):
        d = ids_ref[base + r]

        @pl.when(d >= 0)
        def _():
            pltpu.make_async_copy(
                src_ref.at[r], dst_hbm.at[d], sems.at[r]
            ).wait()
        return 0

    jax.lax.fori_loop(0, tile_m, start, 0)
    jax.lax.fori_loop(0, tile_m, wait, 0)


def _valid_mask(ids_ref, base, tile_m):
    ids = jax.lax.dynamic_slice(ids_ref[:], (base,), (tile_m,))
    return (ids >= 0)[:, None]


def _silu_bwd(hg, hu, da):
    """d(silu(hg) * hu) pulled back through the elementwise gate."""
    sg = jax.nn.sigmoid(hg)
    silu = hg * sg
    dhu = da * silu
    dhg = da * hu * (sg * (1.0 + hg * (1.0 - sg)))
    return dhg, dhu


def _fwd_kernel(
    row_ids, dest_ids, te, x_hbm, wgu_ref, wdn_ref, y_hbm,
    xt, yt, gsem, ssem, *, tile_m,
):
    i = pl.program_id(0)
    base = i * tile_m
    _gather_rows(x_hbm, row_ids, base, xt, gsem, tile_m)
    mask = _valid_mask(row_ids, base, tile_m)
    xm = jnp.where(mask, xt[:], 0)
    h = jax.lax.dot_general(
        xm, wgu_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    f = h.shape[-1] // 2
    a = (jax.nn.silu(h[:, :f]) * h[:, f:]).astype(xt.dtype)
    yt[:] = jax.lax.dot_general(
        a, wdn_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(yt.dtype)
    _scatter_rows(yt, dest_ids, base, y_hbm, ssem, tile_m)


def _dx_kernel(
    row_ids, dest_ids, te, x_hbm, dy_hbm, wgu_ref, wdn_ref,
    dx_hbm, dh_ref, a_ref,
    xt, dyt, dxt, gsem, dsem, ssem, *, tile_m,
):
    i = pl.program_id(0)
    base = i * tile_m
    _gather_rows(x_hbm, row_ids, base, xt, gsem, tile_m)
    _gather_rows(dy_hbm, dest_ids, base, dyt, dsem, tile_m)
    mask = _valid_mask(row_ids, base, tile_m)
    xm = jnp.where(mask, xt[:], 0)
    dy = jnp.where(mask, dyt[:], 0).astype(jnp.float32)
    h = jax.lax.dot_general(
        xm, wgu_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    f = h.shape[-1] // 2
    hg, hu = h[:, :f], h[:, f:]
    a = jax.nn.silu(hg) * hu
    a_ref[:] = a.astype(a_ref.dtype)
    # da = dy @ w_downᵀ  (contract the d axis of both)
    da = jax.lax.dot_general(
        dy.astype(wdn_ref.dtype), wdn_ref[0],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dhg, dhu = _silu_bwd(hg, hu, da)
    dh = jnp.concatenate([dhg, dhu], axis=-1)
    dh_ref[:] = dh.astype(dh_ref.dtype)
    # dx = dh @ w_guᵀ  (contract the 2f axis)
    dxt[:] = jax.lax.dot_general(
        dh.astype(wgu_ref.dtype), wgu_ref[0],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dxt.dtype)
    _scatter_rows(dxt, dest_ids, base, dx_hbm, ssem, tile_m)


def _dw_accum_kernel(
    gather_ids, te, lhs_hbm, rhs_ref, dw_ref, lt, gsem, *, tile_m,
):
    """dw[te[i]] += gathered(lhs)ᵀ @ rhs_tile, accumulated across the
    consecutive tiles of each expert (same output block stays resident
    in VMEM; ``init`` detects the group edge from the prefetch vector
    itself)."""
    i = pl.program_id(0)
    base = i * tile_m
    _gather_rows(lhs_hbm, gather_ids, base, lt, gsem, tile_m)
    mask = _valid_mask(gather_ids, base, tile_m)
    lhs = jnp.where(mask, lt[:], 0)
    contrib = jax.lax.dot_general(
        lhs, rhs_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[None]
    init = jnp.logical_or(
        i == 0, te[i] != te[jnp.maximum(i - 1, 0)]
    )
    dw_ref[:] = jnp.where(
        init, contrib, dw_ref[:] + contrib
    ).astype(dw_ref.dtype)


def _dw_sorted_lhs_kernel(
    gather_ids, te, lhs_ref, rhs_hbm, dw_ref, rt, gsem, *, tile_m,
):
    """dw[te[i]] += lhs_tileᵀ @ gathered(rhs) — the mirrored variant
    (sorted lhs read as a regular block, rhs gathered per row)."""
    i = pl.program_id(0)
    base = i * tile_m
    _gather_rows(rhs_hbm, gather_ids, base, rt, gsem, tile_m)
    mask = _valid_mask(gather_ids, base, tile_m)
    rhs = jnp.where(mask, rt[:], 0)
    contrib = jax.lax.dot_general(
        lhs_ref[:], rhs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[None]
    init = jnp.logical_or(
        i == 0, te[i] != te[jnp.maximum(i - 1, 0)]
    )
    dw_ref[:] = jnp.where(
        init, contrib, dw_ref[:] + contrib
    ).astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def grouped_ffn(
    x, w_gu, w_down, row_ids, dest_ids, tile_expert,
    n_out: int, copies_per_src: int, tile_m: int, interpret: bool,
):
    """Fused dispatch-FFN over expert-sorted row segments.

    ``y[dest_ids[j]] = ffn(x[row_ids[j]], w_*[tile_expert[j // tile_m]])``
    for every non-padding slot ``j`` — gather, gate|up GEMM, silu·mul,
    down GEMM, and the unsorting scatter in ONE kernel. ``x`` is
    ``[n_src, d]`` token-major; the result is ``[n_out, d]``
    copy-major (callers combine the ``top_k`` copies densely).

    The custom VJP reuses ``row_ids``/``dest_ids`` verbatim: dx is a
    mirrored gather-GEMM-scatter (h/a recomputed flash-style, never
    stored), dw a pair of per-expert segment accumulations — no XLA
    scatter-of-gathers anywhere in fwd+bwd. Requires the invariant
    ``row_ids[j] == dest_ids[j] // copies_per_src`` (true for both the
    local sort layout and the ep exchange layout), which lets the VJP
    reduce the per-copy dx densely."""
    y, _ = _grouped_ffn_fwd(
        x, w_gu, w_down, row_ids, dest_ids, tile_expert,
        n_out, copies_per_src, tile_m, interpret,
    )
    return y


def _grouped_ffn_fwd(
    x, w_gu, w_down, row_ids, dest_ids, tile_expert,
    n_out, copies_per_src, tile_m, interpret,
):
    T = tile_expert.shape[0]
    d = x.shape[-1]
    two_f = w_gu.shape[-1]
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, tile_m=tile_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(T,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(
                    (1, d, two_f), lambda i, ri, di, te: (te[i], 0, 0)
                ),
                pl.BlockSpec(
                    (1, two_f // 2, d),
                    lambda i, ri, di, te: (te[i], 0, 0),
                ),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.SemaphoreType.DMA((tile_m,)),
                pltpu.SemaphoreType.DMA((tile_m,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_out, d), x.dtype),
        interpret=interpret,
    )(row_ids, dest_ids, tile_expert, x, w_gu, w_down)
    return y, (x, w_gu, w_down, row_ids, dest_ids, tile_expert)


def _grouped_ffn_bwd(n_out, copies_per_src, tile_m, interpret, res, g):
    import numpy as np

    x, w_gu, w_down, row_ids, dest_ids, tile_expert = res
    T = tile_expert.shape[0]
    m_pad = T * tile_m
    n_src, d = x.shape
    two_f = w_gu.shape[-1]
    f = two_f // 2
    g = g.astype(x.dtype)
    dx_c, dh_sorted, a_sorted = pl.pallas_call(
        functools.partial(_dx_kernel, tile_m=tile_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(T,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(
                    (1, d, two_f), lambda i, ri, di, te: (te[i], 0, 0)
                ),
                pl.BlockSpec(
                    (1, f, d), lambda i, ri, di, te: (te[i], 0, 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(
                    (tile_m, two_f), lambda i, ri, di, te: (i, 0)
                ),
                pl.BlockSpec(
                    (tile_m, f), lambda i, ri, di, te: (i, 0)
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.SemaphoreType.DMA((tile_m,)),
                pltpu.SemaphoreType.DMA((tile_m,)),
                pltpu.SemaphoreType.DMA((tile_m,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n_out, d), x.dtype),
            jax.ShapeDtypeStruct((m_pad, two_f), x.dtype),
            jax.ShapeDtypeStruct((m_pad, f), x.dtype),
        ],
        interpret=interpret,
    )(row_ids, dest_ids, tile_expert, x, g, w_gu, w_down)
    e = w_gu.shape[0]
    dwgu = pl.pallas_call(
        functools.partial(_dw_accum_kernel, tile_m=tile_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(T,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(
                    (tile_m, two_f), lambda i, ri, te: (i, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, d, two_f), lambda i, ri, te: (te[i], 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.SemaphoreType.DMA((tile_m,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((e, d, two_f), jnp.float32),
        interpret=interpret,
    )(row_ids, tile_expert, x, dh_sorted)
    dwdn = pl.pallas_call(
        functools.partial(_dw_sorted_lhs_kernel, tile_m=tile_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(T,),
            in_specs=[
                pl.BlockSpec(
                    (tile_m, f), lambda i, di, te: (i, 0)
                ),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, f, d), lambda i, di, te: (te[i], 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((tile_m, d), x.dtype),
                pltpu.SemaphoreType.DMA((tile_m,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((e, f, d), jnp.float32),
        interpret=interpret,
    )(dest_ids, tile_expert, a_sorted, g)
    # Per-copy dx reduces densely over the k copies of each source row
    # (the row_ids == dest_ids // copies invariant): no scatter.
    dx = jnp.sum(
        dx_c.reshape(n_src, copies_per_src, d).astype(jnp.float32),
        axis=1,
    ).astype(x.dtype)
    return (
        dx,
        dwgu.astype(w_gu.dtype),
        dwdn.astype(w_down.dtype),
        np.zeros(row_ids.shape, jax.dtypes.float0),
        np.zeros(dest_ids.shape, jax.dtypes.float0),
        np.zeros(tile_expert.shape, jax.dtypes.float0),
    )


grouped_ffn.defvjp(_grouped_ffn_fwd, _grouped_ffn_bwd)
