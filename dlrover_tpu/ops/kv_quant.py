"""Int8 KV-cache quantization: per-(row, head) scales, symmetric.

Decode is HBM-bandwidth-bound and the KV cache is the stream that
grows with context: BENCH_SELF pins the decode step at 1.33–1.46× the
HBM roofline with bf16 KV. Storing the cache as int8 halves the bytes
every decode step must move — the direct lever on that gap — and
doubles how many paged-KV blocks fit in the same HBM (the serving
capacity axis of docs/DESIGN.md §31).

Scheme: one f32 scale per KV **head per cache row** (``amax / 127``
over the head_dim vector — the finest granularity that adds no
per-element metadata). A head's K row is written once and never
updated, so the scale is computed at append time and immutable after;
d=128 int8 values + one f32 scale = 132 bytes/head/row vs 256 for
bf16 (1.94×). Dequantization happens at the READ site — folded into
the attention math (scales applied to logits / probabilities, never
materializing a dequantized cache) in the XLA append-free step, and
in-kernel in the Pallas decode kernels (ops/decode_attention.py).

The quantizer is round-to-nearest (deterministic — the cache must be
bit-stable across replays); clipping is impossible by construction
(values are scaled by their own amax).
"""

import jax.numpy as jnp

# Scales of all-zero rows would be 0 -> 0/0 at dequant; clamp to a
# denormal-free floor instead (the quantized values are 0 either way).
_SCALE_FLOOR = 1e-20


def quantize_kv(x):
    """x [..., d] float -> (q int8 [..., d], scale f32 [...]).

    ``q * scale[..., None]`` reconstructs x to within amax/254 per
    element (symmetric round-to-nearest over the head_dim vector)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, _SCALE_FLOOR)
    q = jnp.round(xf / scale[..., None])
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Materializing inverse (tests / prefill views); the hot decode
    paths fold ``scale`` into logits/probabilities instead."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def bytes_per_head_row(
    head_dim: int, kv_dtype: str, fp_itemsize: int = 2
) -> int:
    """HBM bytes one KV head's cache row costs under this scheme —
    int8 values plus the one f32 scale, or ``head_dim * fp_itemsize``
    for fp caches. The ONE definition shared by the paged engine's
    block gauge, the equal-HBM serving bench sizing, and the decode
    roofline, so the three byte accounts can never drift."""
    if kv_dtype == "int8":
        return head_dim + 4
    return head_dim * fp_itemsize
