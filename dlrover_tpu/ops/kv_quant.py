"""Int8 KV-cache quantization: per-(row, head) scales, symmetric.

Decode is HBM-bandwidth-bound and the KV cache is the stream that
grows with context: BENCH_SELF pins the decode step at 1.33–1.46× the
HBM roofline with bf16 KV. Storing the cache as int8 halves the bytes
every decode step must move — the direct lever on that gap — and
doubles how many paged-KV blocks fit in the same HBM (the serving
capacity axis of docs/DESIGN.md §31).

Scheme: one f32 scale per KV **head per cache row** (``amax / 127``
over the head_dim vector — the finest granularity that adds no
per-element metadata). A head's K row is written once and never
updated, so the scale is computed at append time and immutable after;
d=128 int8 values + one f32 scale = 132 bytes/head/row vs 256 for
bf16 (1.94×). Dequantization happens at the READ site — folded into
the attention math (scales applied to logits / probabilities, never
materializing a dequantized cache) in the XLA append-free step, and
in-kernel in the Pallas decode kernels (ops/decode_attention.py).

The quantizer is round-to-nearest (deterministic — the cache must be
bit-stable across replays); clipping is impossible by construction
(values are scaled by their own amax).
"""

import json
import struct

import jax.numpy as jnp
import numpy as np

# Scales of all-zero rows would be 0 -> 0/0 at dequant; clamp to a
# denormal-free floor instead (the quantized values are 0 either way).
_SCALE_FLOOR = 1e-20


def quantize_kv(x):
    """x [..., d] float -> (q int8 [..., d], scale f32 [...]).

    ``q * scale[..., None]`` reconstructs x to within amax/254 per
    element (symmetric round-to-nearest over the head_dim vector)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, _SCALE_FLOOR)
    q = jnp.round(xf / scale[..., None])
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Materializing inverse (tests / prefill views); the hot decode
    paths fold ``scale`` into logits/probabilities instead."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def bytes_per_head_row(
    head_dim: int, kv_dtype: str, fp_itemsize: int = 2
) -> int:
    """HBM bytes one KV head's cache row costs under this scheme —
    int8 values plus the one f32 scale, or ``head_dim * fp_itemsize``
    for fp caches. The ONE definition shared by the paged engine's
    block gauge, the equal-HBM serving bench sizing, and the decode
    roofline, so the three byte accounts can never drift."""
    if kv_dtype == "int8":
        return head_dim + 4
    return head_dim * fp_itemsize


# ---------------------------------------------------------------------------
# Pure-bytes wire format (block migration between fleet replicas)
# ---------------------------------------------------------------------------
#
# Layout: MAGIC (4B) | header_len (u32 LE) | json header | kq | vq | ks | vs
# with kq/vq int8 C-order and ks/vs f32 LE C-order. The header records
# the int8 payload shape, the scale shape, and the SOURCE cache dtype so
# the importer knows whether dequantization reconstructs the original
# cache exactly (int8 source: bit-exact passthrough) or to within the
# amax/254 quantization bound (fp source: wire cost roughly halves).

_WIRE_MAGIC = b"KVW1"


def kv_to_wire(k, v, k_scale=None, v_scale=None):
    """Pack a (k, v) KV span into a self-describing byte string.

    Floating inputs are int8-quantized here (``quantize_kv``), scales
    inline; int8 inputs must arrive WITH their scales and pass through
    bit-exact (the idempotent-roundtrip contract). Shapes are arbitrary
    ``[..., d]`` as long as k and v match."""
    k = np.asarray(k)
    v = np.asarray(v)
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if k.dtype == np.int8:
        if k_scale is None or v_scale is None:
            raise ValueError("int8 KV requires k_scale and v_scale")
        kq, ks = k, np.asarray(k_scale, np.float32)
        vq, vs = v, np.asarray(v_scale, np.float32)
        src_dtype = "int8"
    else:
        if k_scale is not None or v_scale is not None:
            raise ValueError("scales only accompany int8 KV")
        kq, ks = quantize_kv(jnp.asarray(k))
        vq, vs = quantize_kv(jnp.asarray(v))
        kq, ks = np.asarray(kq), np.asarray(ks, np.float32)
        vq, vs = np.asarray(vq), np.asarray(vs, np.float32)
        src_dtype = str(k.dtype)
    if ks.shape != kq.shape[:-1] or vs.shape != vq.shape[:-1]:
        raise ValueError(
            f"scale shape {ks.shape} does not match KV rows {kq.shape[:-1]}"
        )
    header = json.dumps(
        {
            "v": 1,
            "shape": list(kq.shape),
            "scale_shape": list(ks.shape),
            "src_dtype": src_dtype,
        }
    ).encode()
    return b"".join(
        [
            _WIRE_MAGIC,
            struct.pack("<I", len(header)),
            header,
            np.ascontiguousarray(kq).tobytes(),
            np.ascontiguousarray(vq).tobytes(),
            np.ascontiguousarray(ks).tobytes(),
            np.ascontiguousarray(vs).tobytes(),
        ]
    )


def kv_from_wire(buf):
    """Inverse of :func:`kv_to_wire`.

    Returns ``(kq, vq, ks, vs, header)`` — always int8 values + f32
    scales; the importer dequantizes (``dequantize_kv``) only when its
    destination cache is fp. ``kv_to_wire(*kv_from_wire(b)[:4])`` is
    byte-identical to ``b`` (idempotent roundtrip)."""
    if buf[:4] != _WIRE_MAGIC:
        raise ValueError("bad KV wire magic")
    (hlen,) = struct.unpack_from("<I", buf, 4)
    off = 8
    header = json.loads(buf[off : off + hlen].decode())
    off += hlen
    shape = tuple(header["shape"])
    scale_shape = tuple(header["scale_shape"])
    n_q = int(np.prod(shape, dtype=np.int64)) if shape else 1
    n_s = int(np.prod(scale_shape, dtype=np.int64)) if scale_shape else 1
    want = off + 2 * n_q + 2 * 4 * n_s
    if len(buf) != want:
        raise ValueError(f"KV wire truncated: {len(buf)} != {want}")
    kq = np.frombuffer(buf, np.int8, n_q, off).reshape(shape)
    off += n_q
    vq = np.frombuffer(buf, np.int8, n_q, off).reshape(shape)
    off += n_q
    ks = np.frombuffer(buf, "<f4", n_s, off).reshape(scale_shape)
    off += 4 * n_s
    vs = np.frombuffer(buf, "<f4", n_s, off).reshape(scale_shape)
    return kq, vq, ks, vs, header
