"""Pallas TPU flash-attention forward kernel.

Online-softmax tiling: grid (batch*heads, q_blocks, kv_blocks) with the
kv dimension innermost — TPU grids run sequentially, so the running
(acc, m, l) live in VMEM scratch across kv iterations and the output
block is written once on the last one. Q/K/V blocks stream HBM→VMEM via
BlockSpec; the [block_q, block_k] logits tile hits the MXU. GQA is
handled in the index map (query head -> kv head), never materialized.

Backward: custom_vjp that recomputes through the XLA reference op
(ops/attention.py) — numerically identical semantics (tests cross-check
all three paths), trading backward FLOPs for O(seq^2) logits memory only
inside the bwd pass. A fused Pallas backward is a later optimization.

Used for the per-device block of full attention; ring attention
(ops/ring_attention.py) handles the sequence-parallel case.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlrover_tpu.ops.attention import NEG_INF, dot_product_attention


def _pick_block(s: int, target: int = 256) -> int:
    for cand in (target, 128, 64, 32, 16, 8):
        if s % cand == 0 and cand <= s:
            return cand
    return s


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    def body():
        # Blocks are (1, bq, d) or (1, 1, bq, d) depending on the layout
        # path; normalize to 2D for the math.
        q = q_ref[...].reshape(block_q, -1).astype(jnp.float32) * scale
        k = k_ref[...].reshape(block_k, -1).astype(jnp.float32)
        v = v_ref[...].reshape(block_k, -1).astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(rows >= cols, logits, NEG_INF)

        m_prev = m_ref[:, :1]                       # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(logits - m_new)
        p = jnp.where(m_blk > NEG_INF / 2, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Whole kv block in the future -> skip the tile entirely.
        pl.when(k_start <= q_start + block_q - 1)(body)
    else:
        body()

    @pl.when(ki == nk - 1)
    def _():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-30)
        out = jnp.where(m > NEG_INF / 2, out, 0.0)
        o_ref[...] = out.astype(o_ref.dtype).reshape(o_ref.shape)


def _flash_forward(q, k, v, causal, softmax_scale, interpret):
    b, sq, h, d = q.shape
    _, skv, hkv, _ = k.shape
    groups = h // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    block_q = _pick_block(sq)
    block_k = _pick_block(skv)
    grid = (b * h, sq // block_q, skv // block_k)

    # Mosaic requires the BLOCK's last two dims to be divisible by
    # (8, 128) or equal to the full array dims; a head-dim block of 1 in
    # the sublane position never qualifies. Two legal layouts:
    # - d % 128 == 0: fold heads into the minor axis ([b, s, h*d] is a
    #   FREE reshape of the contiguous layout) and block the per-head
    #   d-slice — zero data movement;
    # - otherwise (d=64 etc.): transpose to [b, h, s, d] so the minor
    #   block dim equals the full array d — costs one HBM copy per
    #   operand, still far cheaper than materialized s^2 logits.
    if d % 128 == 0 or h == 1:
        # Fold heads into the minor axis: free reshape, per-head d-slice
        # picked by the block index map.
        operands = (
            q.reshape(b, sq, h * d),
            k.reshape(b, skv, hkv * d),
            v.reshape(b, skv, hkv * d),
        )
        q_block = (1, block_q, d)
        kv_block = (1, block_k, d)

        def q_map(bh, qi, ki):
            return (bh // h, qi, bh % h)

        def kv_map(bh, qi, ki):
            return (bh // h, ki, (bh % h) // groups)

        def post(out):
            return out.reshape(b, sq, h, d)

    else:
        # Transpose to [b, h, s, d]: minor block dim equals the array's
        # full d. One HBM copy per operand.
        operands = (
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
        )
        q_block = (1, 1, block_q, d)
        kv_block = (1, 1, block_k, d)

        def q_map(bh, qi, ki):
            return (bh // h, bh % h, qi, 0)

        def kv_map(bh, qi, ki):
            return (bh // h, (bh % h) // groups, ki, 0)

        def post(out):
            return out.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(operands[0].shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(kv_block, kv_map),
            pl.BlockSpec(kv_block, kv_map),
        ],
        out_specs=pl.BlockSpec(q_block, q_map),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return post(out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """Drop-in for ``dot_product_attention`` with contiguous positions.

    q [b, sq, h, d]; k/v [b, skv, hkv, d]; h % hkv == 0. ``interpret``
    defaults to True off-TPU so tests run on CPU.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_forward(q, k, v, causal, softmax_scale, interpret)


def _fwd(q, k, v, causal, softmax_scale, interpret):
    out = flash_attention(q, k, v, causal, softmax_scale, interpret)
    return out, (q, k, v)


def _bwd(causal, softmax_scale, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(
            q, k, v, causal=causal, softmax_scale=softmax_scale
        ),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attention(interpret: Optional[bool] = None):
    """attention_fn factory for ``llama.forward``. Ignores explicit
    positions (assumes contiguous [0..s) per call) — use ring attention
    when the sequence axis is sharded."""

    def attention_fn(
        q, k, v, causal=True, q_positions=None, kv_positions=None,
        softmax_scale=None,
    ):
        return flash_attention(
            q, k, v, causal, softmax_scale, interpret
        )

    return attention_fn
