"""Pallas TPU flash-attention: fused forward AND backward kernels.

Online-softmax tiling: grid (batch*heads, q_blocks, kv_blocks) with the
kv dimension innermost — TPU grids run sequentially, so the running
(acc, m, l) live in VMEM scratch across kv iterations and the output
block is written once on the last one. Q/K/V blocks stream HBM→VMEM via
BlockSpec; the [block_q, block_k] logits tile hits the MXU in the input
dtype (bf16 at full MXU rate) with f32 accumulation. GQA is handled in
the index maps (query head -> kv head), never materialized.

Backward (FlashAttention-2 style): the forward additionally writes the
row log-sum-exp ``lse`` ([b*h, sq, 128] lane-broadcast, the layout trick
of the official jax pallas kernel); the backward recomputes P per tile
from (q, k, lse) and runs two kernels — one accumulating dq over kv
blocks, one accumulating dk/dv over (group, q-block) pairs so GQA
gradients sum across the query heads sharing a kv head. No O(s^2)
tensor ever hits HBM in either direction.

Used for the per-device block of full attention; ring attention
(ops/ring_attention.py) handles the sequence-parallel case.

Parity note: the reference delegates attention entirely to torch
frameworks (SURVEY.md §2.9); this kernel is the TPU-native compute path
its elastic machinery would supervise.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlrover_tpu.ops.attention import NEG_INF, dot_product_attention

LANES = 128  # lane-broadcast width for per-row stats (lse, delta)


def _pick_block(s: int, target: int = 1024) -> int:
    for cand in (target, 512, 256, 128, 64, 32, 16, 8):
        if s % cand == 0 and cand <= s:
            return cand
    return s


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    def body(masked: bool):
        # Blocks are (1, bq, d) or (1, 1, bq, d) depending on the layout
        # path; normalize to 2D for the math. Matmuls keep the input
        # dtype (bf16 on TPU — full-rate MXU) and accumulate in f32;
        # softmax math happens on the f32 logits.
        q = q_ref[...].reshape(block_q, -1)
        k = k_ref[...].reshape(block_k, -1)
        v = v_ref[...].reshape(block_k, -1)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        if masked:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(rows >= cols, logits, NEG_INF)

        m_prev = m_ref[:, :1]                       # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        # Fully-masked ROWS can exist in a diagonal tile when
        # block_q > block_k (rows q_start..k_start-1 see only future
        # columns). The invariant that makes this safe without a -inf
        # guard: the FIRST k-tile of every row's sweep contributes at
        # least one valid column (k_start=0 <= row), so m_prev is
        # finite by the time any fully-masked tile-row is processed,
        # and its exp(NEG_INF - m_new) underflows to exactly 0. Keep
        # that ordering (ki=0 first) if the grid or NEG_INF changes.
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Three tile classes: fully past (no mask math — the iota/
        # compare/where VPU passes rival the tile's MXU time at d=128),
        # diagonal (masked), fully future (skipped).
        q_end = q_start + block_q - 1
        k_end = k_start + block_k - 1
        pl.when(k_end <= q_start)(lambda: body(False))
        pl.when((k_start <= q_end) & (k_end > q_start))(
            lambda: body(True)
        )
    else:
        body(False)

    @pl.when(ki == nk - 1)
    def _():
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-30)
        out = jnp.where(m > NEG_INF / 2, out, 0.0)
        o_ref[...] = out.astype(o_ref.dtype).reshape(o_ref.shape)
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _flash_forward(q, k, v, causal, softmax_scale, interpret):
    b, sq, h, d = q.shape
    _, skv, hkv, _ = k.shape
    groups = h // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    block_q = _pick_block(sq)
    block_k = _pick_block(skv)
    grid = (b * h, sq // block_q, skv // block_k)

    # Mosaic requires the BLOCK's last two dims to be divisible by
    # (8, 128) or equal to the full array dims; a head-dim block of 1 in
    # the sublane position never qualifies. Two legal layouts:
    # - d % 128 == 0: fold heads into the minor axis ([b, s, h*d] is a
    #   FREE reshape of the contiguous layout) and block the per-head
    #   d-slice — zero data movement;
    # - otherwise (d=64 etc.): transpose to [b, h, s, d] so the minor
    #   block dim equals the full array d — costs one HBM copy per
    #   operand, still far cheaper than materialized s^2 logits.
    # NOTE: clamping kv/q block indices to the causal diagonal (so
    # compute-skipped future tiles revisit the resident block instead
    # of streaming one they never read, Mosaic eliding the copy on an
    # unchanged index) was swept on v5e at s in {8k, 32k} across all
    # three kernels and REJECTED: every apparent win (best 42 -> 37.5
    # ms fwd+bwd at 32k in one session) failed to reproduce across
    # fresh sessions — the deltas sat inside the ±8% session-to-session
    # spread, while the non-affine index maps measurably slowed the
    # forward (18.1 -> 19.1 ms). Simple affine maps win.
    if d % 128 == 0 or h == 1:
        operands = (
            q.reshape(b, sq, h * d),
            k.reshape(b, skv, hkv * d),
            v.reshape(b, skv, hkv * d),
        )
        q_block = (1, block_q, d)
        kv_block = (1, block_k, d)

        def q_map(bh, qi, ki):
            return (bh // h, qi, bh % h)

        def kv_map(bh, qi, ki):
            return (bh // h, ki, (bh % h) // groups)

        def post(out):
            return out.reshape(b, sq, h, d)

    else:
        operands = (
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
        )
        q_block = (1, 1, block_q, d)
        kv_block = (1, 1, block_k, d)

        def q_map(bh, qi, ki):
            return (bh // h, bh % h, qi, 0)

        def kv_map(bh, qi, ki):
            return (bh // h, (bh % h) // groups, ki, 0)

        def post(out):
            return out.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(operands[0].shape, q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(kv_block, kv_map),
            pl.BlockSpec(kv_block, kv_map),
        ],
        out_specs=(
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return post(out), lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
#
# Operands are pre-transposed to [b, h, s, d] (one HBM copy each — simple
# uniform layout for both d%128==0 and d=64). Per-row stats (lse, delta)
# ride as [b*h, sq, LANES] lane-broadcast f32.


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def body(masked: bool):
        q = q_ref[...].reshape(block_q, -1)
        k = k_ref[...].reshape(block_k, -1)
        v = v_ref[...].reshape(block_k, -1)
        do = do_ref[...].reshape(block_q, -1)
        lse = lse_ref[...].reshape(block_q, LANES)[:, :1]
        di = di_ref[...].reshape(block_q, LANES)[:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - di) * scale).astype(q.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Mask math only on diagonal tiles (see _flash_kernel).
        pl.when(k_start + block_k - 1 <= q_start)(lambda: body(False))
        pl.when(
            (k_start <= q_start + block_q - 1)
            & (k_start + block_k - 1 > q_start)
        )(lambda: body(True))
    else:
        body(False)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[...] = dq_acc[:].astype(dq_ref.dtype).reshape(dq_ref.shape)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, block_q: int, block_k: int, nq: int,
):
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    k_start = pl.program_id(1) * block_k
    q_start = (j % nq) * block_q

    @pl.when(j == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def body(masked: bool):
        q = q_ref[...].reshape(block_q, -1)
        k = k_ref[...].reshape(block_k, -1)
        v = v_ref[...].reshape(block_k, -1)
        do = do_ref[...].reshape(block_q, -1)
        lse = lse_ref[...].reshape(block_q, LANES)[:, :1]
        di = di_ref[...].reshape(block_q, LANES)[:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        # dv += P^T @ dO
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - di) * scale).astype(q.dtype)
        # dk += dS^T @ Q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Mask math only on diagonal tiles; q blocks entirely before the
        # kv block contribute nothing and are skipped.
        pl.when(k_start + block_k - 1 <= q_start)(lambda: body(False))
        pl.when(
            (q_start + block_q - 1 >= k_start)
            & (k_start + block_k - 1 > q_start)
        )(lambda: body(True))
    else:
        body(False)

    @pl.when(j == nj - 1)
    def _():
        dk_ref[...] = dk_acc[:].astype(dk_ref.dtype).reshape(dk_ref.shape)
        dv_ref[...] = dv_acc[:].astype(dv_ref.dtype).reshape(dv_ref.shape)


def flash_backward_delta(g, out):
    """delta_i = rowsum(dO * O), lane-broadcast to the stats layout —
    loop-invariant for ring attention, so exposed separately."""
    b, sq, h, _ = g.shape
    di = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [b, sq, h]
    return jnp.broadcast_to(
        di.transpose(0, 2, 1).reshape(b * h, sq, 1), (b * h, sq, LANES)
    )


def _flash_backward(q, k, v, out, lse, g, causal, softmax_scale, interpret):
    """Grad wrt (q, k, v) in the model's [b, s, h, d] layout."""
    di = flash_backward_delta(g, out)
    dqT, dkT, dvT = flash_backward_T(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        g.transpose(0, 2, 1, 3),
        lse,
        di,
        causal,
        softmax_scale,
        interpret,
    )
    return (
        dqT.transpose(0, 2, 1, 3),
        dkT.transpose(0, 2, 1, 3),
        dvT.transpose(0, 2, 1, 3),
    )


def flash_backward_T(qT, kT, vT, doT, lse, di, causal, softmax_scale,
                     interpret):
    """Backward core on PRE-TRANSPOSED [b, h, s, d] operands with a
    precomputed delta — ring attention hoists the transposes and delta
    out of its per-hop loop and calls this directly."""
    b, h, sq, d = qT.shape
    _, hkv, skv, _ = kT.shape
    groups = h // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # 1024 blocks measure ~10% faster than 512 on v5e at d<=128 (same
    # sweep result as the forward: grid-step overhead dominates below
    # ~1024) and were verified to compile/run on hardware at d=128,
    # s=4096. The backward holds roughly twice the forward's live tiles
    # (s/p/dp f32 + two accumulators), so larger head dims — unverified
    # and with proportionally bigger blocks — keep the conservative 512
    # cap to stay inside VMEM.
    bwd_target = 1024 if d <= 128 else 512
    block_q = _pick_block(sq, target=bwd_target)
    block_k = _pick_block(skv, target=bwd_target)
    nq = sq // block_q

    q_block = (1, 1, block_q, d)
    kv_block = (1, 1, block_k, d)
    stat_block = (1, block_q, LANES)

    # ---- dq: grid (b*h, q_blocks, kv_blocks) --------------------------
    def q_map(bh, qi, ki):
        return (bh // h, bh % h, qi, 0)

    def kv_map(bh, qi, ki):
        return (bh // h, (bh % h) // groups, ki, 0)

    def stat_map(bh, qi, ki):
        return (bh, qi, 0)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        out_shape=jax.ShapeDtypeStruct(qT.shape, qT.dtype),
        grid=(b * h, nq, skv // block_k),
        in_specs=[
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(kv_block, kv_map),
            pl.BlockSpec(kv_block, kv_map),
            pl.BlockSpec(q_block, q_map),
            pl.BlockSpec(stat_block, stat_map),
            pl.BlockSpec(stat_block, stat_map),
        ],
        out_specs=pl.BlockSpec(q_block, q_map),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qT, kT, vT, doT, lse, di)

    # ---- dk/dv: grid (b*hkv, kv_blocks, groups*q_blocks) --------------
    # The innermost axis walks every query head in the kv head's group
    # and every q block, accumulating into one (dk, dv) tile — GQA
    # gradients need exactly this cross-head sum.
    def kv_map2(bkv, ki, j):
        return (bkv // hkv, bkv % hkv, ki, 0)

    def q_map2(bkv, ki, j):
        return (bkv // hkv, (bkv % hkv) * groups + j // nq, j % nq, 0)

    def stat_map2(bkv, ki, j):
        bh = (bkv // hkv) * h + (bkv % hkv) * groups + j // nq
        return (bh, j % nq, 0)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nq=nq,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(kT.shape, kT.dtype),
            jax.ShapeDtypeStruct(vT.shape, vT.dtype),
        ),
        grid=(b * hkv, skv // block_k, groups * nq),
        in_specs=[
            pl.BlockSpec(q_block, q_map2),
            pl.BlockSpec(kv_block, kv_map2),
            pl.BlockSpec(kv_block, kv_map2),
            pl.BlockSpec(q_block, q_map2),
            pl.BlockSpec(stat_block, stat_map2),
            pl.BlockSpec(stat_block, stat_map2),
        ],
        out_specs=(
            pl.BlockSpec(kv_block, kv_map2),
            pl.BlockSpec(kv_block, kv_map2),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qT, kT, vT, doT, lse, di)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
):
    """Drop-in for ``dot_product_attention`` with contiguous positions.

    q [b, sq, h, d]; k/v [b, skv, hkv, d]; h % hkv == 0. ``interpret``
    defaults to True off-TPU so tests run on CPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal, softmax_scale, interpret)
    return out


def _fwd(q, k, v, causal, softmax_scale, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, softmax_scale, interpret)
    # Residual lse is stored COMPACT [b*h, sq] — the kernel's
    # lane-broadcast [b*h, sq, LANES] layout would pin 128x the bytes
    # (64MB/layer at the flagship shape) across the whole backward.
    return out, (q, k, v, out, lse[:, :, 0])


def _bwd(causal, softmax_scale, interpret, res, g):
    q, k, v, out, lse2d = res
    lse = jnp.broadcast_to(lse2d[:, :, None], lse2d.shape + (LANES,))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if os.environ.get("DLROVER_TPU_FLASH_BWD", "pallas").lower() == "xla":
        # Debug fallback: rebuild grads through the XLA reference op.
        _, vjp = jax.vjp(
            lambda q, k, v: dot_product_attention(
                q, k, v, causal=causal, softmax_scale=softmax_scale
            ),
            q, k, v,
        )
        return vjp(g)
    return _flash_backward(
        q, k, v, out, lse, g, causal, softmax_scale, interpret
    )


flash_attention.defvjp(_fwd, _bwd)


def make_flash_attention(interpret: Optional[bool] = None):
    """attention_fn factory for ``llama.forward``. Ignores explicit
    positions (assumes contiguous [0..s) per call) — use ring attention
    when the sequence axis is sharded."""

    def attention_fn(
        q, k, v, causal=True, q_positions=None, kv_positions=None,
        softmax_scale=None,
    ):
        return flash_attention(
            q, k, v, causal, softmax_scale, interpret
        )

    # Backward residuals are O(s*d) (q/k/v/out + compact lse), so the
    # "mlp_only" remat policy may exempt this impl from rematerialization.
    attention_fn.saveable_residuals = True
    # Plain contiguous-position flash with DEFAULT interpret
    # resolution: eligible for llama's lite attention block (attn_save
    # saves only x/out/lse and re-derives q/k/v in the backward). An
    # explicit interpret override opts out — the lite block resolves
    # interpret from the backend and must not silently discard the
    # caller's choice. Ring attention sets saveable_residuals but not
    # this — its hop structure can't be re-derived from x.
    attention_fn.is_plain_flash = interpret is None
    return attention_fn
