"""Single-query (decode-step) attention over a KV cache, Pallas.

The generate loop's per-step attention previously ran the plain XLA
``dot_product_attention`` over the FULL pre-allocated cache — every
step reads ``max_len`` KV rows even when only ``length`` are filled,
and the masked softmax touches the padding too. Decode is HBM-bound,
so those wasted reads are wasted milliseconds.

This kernel is length-aware PER ROW: the fill lengths ride as a [b]
scalar-prefetch vector (a scalar is broadcast, so uniform-fill callers
are unchanged), the KV block index map CLAMPS past-the-end blocks to
the row's own last valid block (Mosaic skips the HBM copy when a block
index repeats), and ``pl.when`` skips their compute. Per (batch,
kv-head) grid cell the query group (GQA: n_heads // n_kv_heads rows,
padded to the 8-sublane minimum) runs an online-softmax sweep over KV
blocks — flash attention with a 1-token query.

Ragged fills are where the kernel earns its keep: the continuous-
batching serving engine (serving/engine.py) holds slots at wildly
different fill lengths, and a padded whole-cache XLA read wastes HBM
bandwidth proportional to the raggedness, while this grid clamps each
slot to its own fill.

Parity note: the reference delegates decode to vLLM/torch kernels
(paged attention); :func:`decode_attention` is the TPU-native analogue
for this repo's single-slab cache, and :func:`paged_decode_attention`
is the block-table generalization for the paged KV pool
(serving/kvpool): the per-row block table rides as a SECOND
scalar-prefetch operand and the kv index map dereferences it, so grid
step ``j`` of row ``ib`` DMAs pool block ``table[ib, j]`` — gather
through the table with zero extra HBM traffic for the indirection.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def spec_verify_attention(
    q,            # [b, T, h, d] — T = 1 fed token + K drafted tokens
    k_cache,      # [b, S, kh, d] read-only; rows >= cache_len unfilled
    v_cache,
    k_new,        # [b, T, kh, d] full-precision K/V of the T new tokens
    v_new,
    cache_len,    # [] or [b] int32 — committed (visible) cache rows
    k_scale=None,      # [b, S, kh] f32 — int8 caches (ops/kv_quant)
    v_scale=None,
    k_new_q=None,      # [b, T, kh, d] int8 — quantized new K rows
    k_new_scale=None,  # [b, T, kh] f32
    v_new_q=None,
    v_new_scale=None,
):
    """T-query generalization of the append-free decode attention —
    the speculative-decoding VERIFICATION step's core math.

    One batched call scores all T = K+1 tokens (the fed token plus K
    drafted continuations) against a READ-ONLY ragged cache, exactly
    what K+1 sequential ``_append_free_attention`` steps would compute
    if each drafted token's K/V had been appended before the next
    step. Three key groups, merged in one online softmax:

    - **Cache part** ([b, S]): rows visible iff ``< cache_len``, per
      row — the same visibility invariant as single-token decode.
    - **Intra-draft part** ([b, T]): query t sees drafted key u iff
      ``u < t`` (strict — the standard causal chain among the new
      tokens). Sequential decode would read these keys FROM THE CACHE,
      i.e. after the storage round trip; so for int8 caches the
      off-diagonal keys here are the QUANTIZED rows (``k_new_q`` with
      per-(row, head) ``k_new_scale`` folded post-reduction, the exact
      read-site math of the cache part) — bit-exact int8 parity with
      the non-speculative path.
    - **Self part**: each query always sees its own K/V at FULL
      precision (the write-once rule: a token's quantized row is what
      LATER tokens read, never itself).

    T=1 degenerates to ``_append_free_attention`` (the intra part is
    empty) — the parity test pins the two. Returns [b, T, h, d].
    """
    b, T, h, d = q.shape
    _, skv, kh, _ = k_cache.shape
    g = h // kh
    scale = d ** -0.5
    # [b, T, kh, g, d] f32 query groups.
    q32 = (q * scale).astype(jnp.float32).reshape(b, T, kh, g, d)
    # Cache part: [b, kh, g, T, S]; per-row visibility masking.
    logits = jnp.einsum(
        "btkgd,bskd->bkgts", q32, k_cache.astype(jnp.float32)
    )
    if k_scale is not None:
        logits = logits * k_scale.transpose(0, 2, 1)[:, :, None, None, :]
    lens = jnp.atleast_1d(jnp.asarray(cache_len, jnp.int32))
    visible = jnp.arange(skv)[None, :] < lens[:, None]       # [1|b, S]
    logits = jnp.where(visible[:, None, None, None, :], logits, NEG_INF)
    # Intra-draft part: [b, kh, g, T, T]; key u visible to query t iff
    # u < t. Off-diagonal keys go through the storage round trip (int8:
    # quantized values with the scale folded post-reduction, exactly
    # like the cache read above; fp: the cache dtype IS the compute
    # dtype, so the round trip is the identity and k_new serves as-is).
    intra_k = (k_new_q if k_new_q is not None else k_new).astype(
        jnp.float32
    )
    l_intra = jnp.einsum("btkgd,bukd->bkgtu", q32, intra_k)
    if k_new_scale is not None:
        l_intra = l_intra * k_new_scale.transpose(0, 2, 1)[
            :, :, None, None, :
        ]
    tq = jnp.arange(T)
    intra_mask = tq[None, :] < tq[:, None]                   # [T, T] u<t
    l_intra = jnp.where(intra_mask[None, None, None], l_intra, NEG_INF)
    # Self part: full-precision own K/V.
    l_self = jnp.einsum(
        "btkgd,btkd->bkgt", q32, k_new.astype(jnp.float32)
    )
    m = jnp.maximum(
        jnp.maximum(jnp.max(logits, axis=-1), jnp.max(l_intra, axis=-1)),
        l_self,
    )                                                        # [b,kh,g,T]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(visible[:, None, None, None, :], p, 0.0)
    p_intra = jnp.exp(l_intra - m[..., None])
    p_intra = jnp.where(intra_mask[None, None, None], p_intra, 0.0)
    p_self = jnp.exp(l_self - m)
    denom = (
        jnp.sum(p, axis=-1) + jnp.sum(p_intra, axis=-1) + p_self
    )                                                        # >= p_self
    pv = p if v_scale is None else (
        p * v_scale.transpose(0, 2, 1)[:, :, None, None, :]
    )
    intra_v = (v_new_q if v_new_q is not None else v_new).astype(
        jnp.float32
    )
    pv_intra = p_intra if v_new_scale is None else (
        p_intra * v_new_scale.transpose(0, 2, 1)[:, :, None, None, :]
    )
    out = (
        jnp.einsum("bkgts,bskd->bkgtd", pv, v_cache.astype(jnp.float32))
        + jnp.einsum("bkgtu,bukd->bkgtd", pv_intra, intra_v)
        + p_self[..., None] * v_new.astype(jnp.float32).transpose(
            0, 2, 1, 3
        )[:, :, None]
    ) / denom[..., None]
    # [b, kh, g, T, d] -> [b, T, h, d]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, T, h, d).astype(
        q.dtype
    )


def _decode_body(
    len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
    o_ref, m_ref, l_ref, acc_ref, *, block_k: int, scale: float,
):
    """Online-softmax sweep shared by the fp and int8 kernels. With
    scale refs present the KV blocks are int8 and dequantization is
    folded into the math IN-KERNEL: the per-(row, head) K scales
    multiply the raw q·k logits and the V scales fold into the
    probability rows before the p·v matmul — the dequantized cache is
    never materialized, and HBM moves half the bytes. Scale blocks
    carry ALL kv heads ([1, bk, kh] — a full minor dim, which Mosaic
    pads, unlike a 1-wide lane slice it could reject) and the kernel
    selects its own head's column by the grid index."""
    ib = pl.program_id(0)
    ih = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[ib]
    base = j * block_k

    @pl.when(base < length)
    def _():
        q = q_ref[0, 0]                                 # [gp, d]
        k = k_ref[0]                                    # [bk, d]
        v = v_ref[0]
        if ks_ref is not None:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                       # [gp, bk]
        if ks_ref is not None:
            # Dequantized logits: s_true = (q · k_q) * scale * k_scale
            ks = jax.lax.dynamic_slice_in_dim(
                ks_ref[0], ih, 1, axis=1
            )[:, 0]
            s = s * ks[None, :]
        cols = base + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(cols < length, s, NEG_INF)
        m_prev = m_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(p, -1, keepdims=True),
            l_ref.shape,
        )
        # V dequant folds into the probability rows (l above keeps the
        # UNSCALED p — it is the softmax denominator).
        if vs_ref is None:
            pv = p
        else:
            vs = jax.lax.dynamic_slice_in_dim(
                vs_ref[0], ih, 1, axis=1
            )[:, 0]
            pv = p * vs[None, :]
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            pv.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nj - 1)
    def _():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def _kernel(
    len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_k: int, scale: float,
):
    _decode_body(
        len_ref, q_ref, k_ref, v_ref, None, None,
        o_ref, m_ref, l_ref, acc_ref, block_k=block_k, scale=scale,
    )


def _kernel_q8(
    len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
    o_ref, m_ref, l_ref, acc_ref, *, block_k: int, scale: float,
):
    _decode_body(
        len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
        o_ref, m_ref, l_ref, acc_ref, block_k=block_k, scale=scale,
    )


def _paged_kernel(
    len_ref, bt_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_k: int, scale: float,
):
    # The block table is consumed entirely by the kv index maps; the
    # compute body is the flat kernel's online-softmax sweep unchanged.
    del bt_ref
    _decode_body(
        len_ref, q_ref, k_ref, v_ref, None, None,
        o_ref, m_ref, l_ref, acc_ref, block_k=block_k, scale=scale,
    )


def _paged_kernel_q8(
    len_ref, bt_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
    o_ref, m_ref, l_ref, acc_ref, *, block_k: int, scale: float,
):
    del bt_ref
    _decode_body(
        len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
        o_ref, m_ref, l_ref, acc_ref, block_k=block_k, scale=scale,
    )


def paged_decode_attention(
    q,             # [b, n_heads, d] — ONE query token per sequence
    k_pool,        # [num_blocks, block_size, kv_heads, d]
    v_pool,
    block_tables,  # [b, max_blocks] int32 — pool rows per sequence
    length,        # [b] int32 — filled LOGICAL rows per sequence
    interpret=None,
    k_scale=None,  # [num_blocks, block_size, kv_heads] f32 — int8 pools
    v_scale=None,
):
    """Single-query attention straight through a block table.

    The paged generalization of :func:`decode_attention`: the KV pool
    is block-granular (``[num_blocks, block_size, kh, d]``) and each
    sequence's logical cache is the concatenation of the pool rows its
    ``block_tables`` row names. Both the fill vector AND the tables
    ride as scalar-prefetch operands, so the kv index map dereferences
    the table on the host side of the DMA: grid step ``j`` of row
    ``ib`` copies pool block ``block_tables[ib, j]``, clamped past the
    fill to the row's last valid table entry (repeat index = skipped
    copy, the same Mosaic trick as the flat kernel). Visibility is the
    engine invariant — a logical row is read iff ``< length[ib]`` —
    so stale ids beyond the fill in a table row are never dereferenced
    into the softmax. With ``k_scale``/``v_scale`` the pools are int8
    (ops/kv_quant per-(row, head) scheme) and dequantization happens
    in-kernel — half the KV bytes per step. Returns
    ``[b, n_heads, d]``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    nb_pool, block_size, kh, _ = k_pool.shape
    _, max_blocks = block_tables.shape
    if h % kh:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kh}")
    g = h // kh
    gp = max(g, 8)  # sublane minimum
    scale = d ** -0.5
    qg = q.reshape(b, kh, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    length = jnp.broadcast_to(
        jnp.asarray(length, jnp.int32).reshape(-1), (b,)
    )
    tables = jnp.asarray(block_tables, jnp.int32)

    def kv_index(ib, ih, j, len_ref, bt_ref):
        # Clamp to the row's last FILLED logical block, then map the
        # logical block through the row's table to a pool row.
        last = jnp.maximum((len_ref[ib] - 1) // block_size, 0)
        return (bt_ref[ib, jnp.minimum(j, last)], 0, ih)

    kf = k_pool.reshape(nb_pool, block_size, kh * d)
    vf = v_pool.reshape(nb_pool, block_size, kh * d)

    quantized = k_scale is not None
    kernel = _paged_kernel_q8 if quantized else _paged_kernel
    in_specs = [
        pl.BlockSpec(
            (1, 1, gp, d),
            lambda ib, ih, j, ln, bt: (ib, ih, 0, 0),
        ),
        pl.BlockSpec((1, block_size, d), kv_index),
        pl.BlockSpec((1, block_size, d), kv_index),
    ]
    operands = [length, tables, qg, kf, vf]
    if quantized:
        # Per-(row, head) scale blocks ride the SAME table-deref row
        # clamp as their KV blocks but carry ALL kh heads (full minor
        # dim — Mosaic pads it; the kernel picks its head's column).
        def scale_index(ib, ih, j, len_ref, bt_ref):
            last = jnp.maximum((len_ref[ib] - 1) // block_size, 0)
            return (bt_ref[ib, jnp.minimum(j, last)], 0, 0)

        in_specs += [
            pl.BlockSpec((1, block_size, kh), scale_index),
            pl.BlockSpec((1, block_size, kh), scale_index),
        ]
        operands += [
            jnp.asarray(k_scale, jnp.float32),
            jnp.asarray(v_scale, jnp.float32),
        ]

    out = pl.pallas_call(
        functools.partial(kernel, block_k=block_size, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, kh, max_blocks),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, gp, d),
                lambda ib, ih, j, ln, bt: (ib, ih, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, gp, d), q.dtype),
        interpret=interpret,
    )(*operands)
    return out[:, :, :g, :].reshape(b, h, d)


def decode_attention(
    q,            # [b, n_heads, d] — ONE query token per sequence
    k_cache,      # [b, max_len, kv_heads, d]
    v_cache,
    length,       # [] or [b] int32 — filled cache rows per sequence
    block_k: int = 128,
    interpret=None,
    k_scale=None,  # [b, max_len, kv_heads] f32 — int8 caches only
    v_scale=None,
):
    """Length-masked single-query attention; returns [b, n_heads, d].

    ``length`` may be a scalar (uniform fill — every row clamps to the
    same block range, the original generate() contract) or a [b] vector
    of per-row fills (ragged slots — the serving engine's case, where
    each (batch, kv-head) grid cell reads only its own row's filled
    blocks). Rows with length 0 produce zero output. With
    ``k_scale``/``v_scale`` the caches are int8 (ops/kv_quant) and the
    kernel dequantizes in-kernel — the HBM stream the decode roofline
    is judged against halves."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    _, max_len, kh, _ = k_cache.shape
    if h % kh:
        raise ValueError(f"n_heads {h} not divisible by kv_heads {kh}")
    g = h // kh
    gp = max(g, 8)  # sublane minimum
    scale = d ** -0.5
    # [b, kh, gp, d] query groups, zero-padded rows.
    qg = q.reshape(b, kh, g, d)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    length = jnp.broadcast_to(
        jnp.asarray(length, jnp.int32).reshape(-1), (b,)
    )
    nj = max_len // block_k
    if max_len % block_k:
        raise ValueError(
            f"max_len {max_len} not a multiple of block_k {block_k}"
        )

    def kv_index(ib, ih, j, len_ref):
        # Clamp past-the-fill blocks to THIS ROW's last valid one:
        # Mosaic skips the HBM copy when the index repeats, so unfilled
        # cache rows are never read — per sequence, not per batch.
        last = jnp.maximum((len_ref[ib] - 1) // block_k, 0)
        return (ib, jnp.minimum(j, last), ih)

    # Mosaic wants the trailing two block dims (8, 128)-divisible: view
    # the cache [b, L, kh, d] as [b, L, kh*d] (free — contiguous) and
    # block the lane dim per kv head.
    kf = k_cache.reshape(b, max_len, kh * d)
    vf = v_cache.reshape(b, max_len, kh * d)

    quantized = k_scale is not None
    kernel = _kernel_q8 if quantized else _kernel
    in_specs = [
        pl.BlockSpec(
            (1, 1, gp, d), lambda ib, ih, j, s: (ib, ih, 0, 0)
        ),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    operands = [length, qg, kf, vf]
    if quantized:
        # Full-kh scale blocks (see paged variant for the Mosaic
        # minor-dim rationale); same per-row fill clamp as K/V.
        def scale_index(ib, ih, j, len_ref):
            last = jnp.maximum((len_ref[ib] - 1) // block_k, 0)
            return (ib, jnp.minimum(j, last), 0)

        in_specs += [
            pl.BlockSpec((1, block_k, kh), scale_index),
            pl.BlockSpec((1, block_k, kh), scale_index),
        ]
        operands += [
            jnp.asarray(k_scale, jnp.float32),
            jnp.asarray(v_scale, jnp.float32),
        ]

    out = pl.pallas_call(
        functools.partial(kernel, block_k=block_k, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, kh, nj),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, gp, d), lambda ib, ih, j, s: (ib, ih, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, gp, d), q.dtype),
        interpret=interpret,
    )(*operands)
    return out[:, :, :g, :].reshape(b, h, d)
