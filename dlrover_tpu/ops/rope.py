"""Rotary position embeddings (RoPE).

Takes explicit global position indices so sequence-parallel shards (each
holding ``seq/sp`` tokens) rotate with their true positions — required by
ring attention where the local sequence index is not the global one.
"""

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0):
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotate x: [..., seq, heads, head_dim] by positions: [..., seq].

    Uses the half-split convention (first half paired with second half),
    which keeps the op a pair of multiplies + one concat — friendlier to
    XLA fusion than interleaved lanes.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)
    # [..., seq, head_dim//2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    # broadcast over the heads axis: [..., seq, 1, head_dim//2]
    angles = angles[..., None, :]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        (x1 * cos - x2 * sin, x2 * cos + x1 * sin), axis=-1
    )
    return rotated.astype(x.dtype)
