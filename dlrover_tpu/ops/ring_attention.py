"""Ring attention: exact causal attention over a sequence-parallel mesh
axis (long-context path).

A ``shard_map`` island inside the jitted program: Q/K/V are sharded on
the ``sp`` mesh axis along sequence; each device computes blockwise
attention of its local queries against the K/V block it currently holds,
accumulating with an online (flash-style) softmax, then rotates K/V one
hop around the ``sp`` ring via ``ppermute`` — compute and ICI transfer
overlap, HBM never holds the full sequence. Position-based causal
masking makes the result exact for any block arrival order.

This is the long-context capability the reference lacks entirely
(SURVEY.md §2.9: EP/CP/ring attention "absent"); the reference's
DeepSpeed-SP awareness (docs/design/elastic.md:23-29) stops at
checkpoint/rendezvous metadata.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.ops.attention import NEG_INF
from dlrover_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec


def _block_attn(q, k, v, q_pos, kv_pos, causal, scale):
    """Partial attention of q against one K/V block.

    q: [b, sq, h, d]; k/v: [b, skv, hkv, d]. Returns (o, m, l) where
    o = sum(exp(logits - m) @ v), m = rowwise max logits, l = rowwise
    sum exp — the flash-attention partial triple, f32.

    Matmuls keep the input dtype (bf16 = full-rate MXU) and accumulate
    in f32; softmax math runs on the f32 logits with the scale applied
    there, so bf16 inputs lose nothing to a pre-scaled q.
    """
    b, sq, h, d = q.shape
    _, skv, hkv, _ = k.shape
    groups = h // hkv
    qg = q.reshape(b, sq, hkv, groups, d)
    logits = (
        jnp.einsum(
            "bqkgd,bskd->bkgqs",
            qg,
            k,
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    if causal:
        mask = q_pos[:, :, None] >= kv_pos[:, None, :]  # [b, sq, skv]
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                        # [b, hkv, g, sq]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bkgqs,bskd->bkgqd",
        p.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(jnp.float32)
    return o, m, l


def _ring_overlap() -> bool:
    """Collective/compute overlap schedule (default on): each hop
    ISSUES the next chunk's ppermute before running the current
    chunk's attention block, so the collective-permute-start flows
    into the scheduler ahead of the matmuls it must hide behind, and
    the final hop elides the wasted wrap-around K/V permute entirely
    (n-1 rotations instead of n). DLROVER_TPU_RING_OVERLAP=0 restores
    the legacy compute-then-permute order for the bench A/B."""
    from dlrover_tpu.common.env_utils import get_env_bool

    return get_env_bool("DLROVER_TPU_RING_OVERLAP", True)


def ring_attention_local(
    q,
    k,
    v,
    q_positions,
    kv_positions,
    axis_name: str = "sp",
    causal: bool = True,
    softmax_scale: Optional[float] = None,
):
    """Per-shard body (call under shard_map). Shapes are LOCAL:
    q [b, sq_loc, h, d]; k/v [b, skv_loc, hkv, d]; positions are the
    GLOBAL token indices of the local rows ([b, sq_loc]/[b, skv_loc]).
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    n = _axis_size(axis_name)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    o0 = jnp.zeros((b, hkv, groups, sq, d), jnp.float32)
    m0 = jnp.full((b, hkv, groups, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, groups, sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_merge(o, m, l, k_cur, v_cur, kv_pos):
        bo, bm, bl = _block_attn(
            q, k_cur, v_cur, q_positions, kv_pos, causal, scale
        )
        m_new = jnp.maximum(m, bm)
        corr = jnp.exp(m - m_new)
        bcorr = jnp.exp(bm - m_new)
        o = o * corr[..., None] + bo * bcorr[..., None]
        l = l * corr + bl * bcorr
        return o, m_new, l

    if _ring_overlap():
        def step(i, carry):
            o, m, l, k_cur, v_cur, kv_pos = carry
            # Next chunk's rotation is issued BEFORE this chunk's
            # attention block: the permute depends only on the carry,
            # so its transfer hides behind the block's matmuls.
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            p_nxt = jax.lax.ppermute(kv_pos, axis_name, perm)
            o, m, l = block_merge(o, m, l, k_cur, v_cur, kv_pos)
            return (o, m, l, k_nxt, v_nxt, p_nxt)

        o, m, l, k_l, v_l, p_l = jax.lax.fori_loop(
            0, n - 1, step, (o0, m0, l0, k, v, kv_positions)
        )
        # Final chunk: compute only — the wrap-around permute that the
        # legacy schedule paid (result discarded) is gone.
        o, m, l = block_merge(o, m, l, k_l, v_l, p_l)
    else:
        def step(i, carry):
            o, m, l, k_cur, v_cur, kv_pos = carry
            o, m, l = block_merge(o, m, l, k_cur, v_cur, kv_pos)
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
            return (o, m, l, k_cur, v_cur, kv_pos)

        o, m, l, _, _, _ = jax.lax.fori_loop(
            0, n, step, (o0, m0, l0, k, v, kv_positions)
        )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((m > NEG_INF / 2)[..., None], out, 0.0)
    # [b, hkv, g, sq, d] -> [b, sq, h, d]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, sq, h, d)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas ring attention: the flash kernel as the per-hop inner block
# ---------------------------------------------------------------------------
#
# The XLA path above materializes the full local [sq_loc, skv_loc] logits
# tensor on every ring hop — exactly the memory/bandwidth cost flash
# attention kills. This path instead calls the fused Pallas kernels
# (ops/pallas_attention.py) per hop and merges the (out, lse) partials:
#
# - forward: out_global = sum_b exp(lse_b - lse_global) * out_b, with
#   lse_global accumulated stably across hops;
# - backward (ring-level custom VJP): p_ij = exp(s_ij - lse_global)
#   globally, so each hop's (dq, dk, dv) is one flash-backward call fed
#   the FINAL lse and the global delta = rowsum(do * out); dk/dv
#   accumulators rotate around the ring alongside k/v and are home after
#   n hops.
#
# Requires each sp shard to hold a CONTIGUOUS chunk of the sequence (the
# layout make_ring_attention's shard_map produces): the per-hop causal
# relation then collapses to three static cases — fully-past block (no
# mask), diagonal block (relative causal mask), fully-future block
# (skipped) — so the kernels never need absolute positions.


def _flash_block(q, k, v, causal, scale):
    """One ring hop through the Pallas forward. Returns (out [b,sq,h,d]
    in q.dtype, lse [b, h, sq] f32)."""
    from dlrover_tpu.ops.pallas_attention import _flash_forward

    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, scale, interpret)
    b, sq, h, d = q.shape
    return out, lse[:, :, 0].reshape(b, h, sq)


def _merge(o, lse, out_b, lse_b):
    """Merge a block partial into the running (o f32 [b,sq,h,d],
    lse f32 [b,h,sq]) accumulator."""
    m = jnp.maximum(lse, lse_b)
    lse_new = m + jnp.log(jnp.exp(lse - m) + jnp.exp(lse_b - m))
    w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
    w_new = jnp.exp(lse_b - lse_new).transpose(0, 2, 1)[..., None]
    o = o * w_old + out_b.astype(jnp.float32) * w_new
    return o, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def ring_flash_attention_local(
    q, k, v, q_positions, kv_positions,
    axis_name: str = "sp",
    causal: bool = True,
    softmax_scale: Optional[float] = None,
):
    out, _ = _ring_flash_fwd(
        q, k, v, q_positions, kv_positions, axis_name, causal,
        softmax_scale,
    )
    return out


def _contiguity_poison(q_pos, kv_pos):
    """NaN unless positions are what the pallas path assumes: every batch
    row identical and contiguous within the shard (the layout
    make_ring_attention's shard_map produces from global iota positions).
    Packed/per-batch positions then fail LOUDLY (NaN loss on step one)
    instead of training on silently wrong causal masks — such callers
    must use impl="xla"."""
    sq = q_pos.shape[1]
    skv = kv_pos.shape[1]
    ok_q = jnp.all(
        q_pos == q_pos[0, 0] + jnp.arange(sq, dtype=q_pos.dtype)[None, :]
    )
    ok_kv = jnp.all(
        kv_pos
        == kv_pos[0, 0] + jnp.arange(skv, dtype=kv_pos.dtype)[None, :]
    )
    return jnp.where(ok_q & ok_kv, 0.0, jnp.nan).astype(jnp.float32)


def _ring_flash_fwd(q, k, v, q_pos, kv_pos, axis_name, causal, scale):
    b, sq, h, d = q.shape
    n = _axis_size(axis_name)
    scale = scale if scale is not None else d ** -0.5
    q_off = q_pos[0, 0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def skip():
        return (
            jnp.zeros((b, sq, h, d), q.dtype),
            jnp.full((b, h, sq), NEG_INF, jnp.float32),
        )

    def block_merge(o, lse, k_cur, v_cur, kvp):
        kv_off = kvp[0, 0]
        if causal:
            out_b, lse_b = jax.lax.cond(
                kv_off > q_off,
                skip,
                lambda: jax.lax.cond(
                    kv_off == q_off,
                    lambda: _flash_block(q, k_cur, v_cur, True, scale),
                    lambda: _flash_block(q, k_cur, v_cur, False, scale),
                ),
            )
        else:
            out_b, lse_b = _flash_block(q, k_cur, v_cur, False, scale)
        return _merge(o, lse, out_b, lse_b)

    o0 = jnp.zeros((b, sq, h, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    if _ring_overlap():
        def hop(i, carry):
            o, lse, k_cur, v_cur, kvp = carry
            # Rotation first: the ppermute-start is in flight while the
            # flash kernel chews the chunk it already holds (§33).
            k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            kvp_nxt = jax.lax.ppermute(kvp, axis_name, perm)
            o, lse = block_merge(o, lse, k_cur, v_cur, kvp)
            return (o, lse, k_nxt, v_nxt, kvp_nxt)

        o, lse, k_l, v_l, kvp_l = jax.lax.fori_loop(
            0, n - 1, hop, (o0, lse0, k, v, kv_pos)
        )
        o, lse = block_merge(o, lse, k_l, v_l, kvp_l)
    else:
        def hop(i, carry):
            o, lse, k_cur, v_cur, kvp = carry
            o, lse = block_merge(o, lse, k_cur, v_cur, kvp)
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            kvp = jax.lax.ppermute(kvp, axis_name, perm)
            return (o, lse, k_cur, v_cur, kvp)

        o, lse, _, _, _ = jax.lax.fori_loop(
            0, n, hop, (o0, lse0, k, v, kv_pos)
        )
    if causal:
        # Only causal masking consults positions; bidirectional ring
        # attention is position-free and needs no guard.
        o = o + _contiguity_poison(q_pos, kv_pos)
    return o.astype(q.dtype), lse


def _ring_fwd_rule(q, k, v, q_pos, kv_pos, axis_name, causal, scale):
    out, lse = _ring_flash_fwd(
        q, k, v, q_pos, kv_pos, axis_name, causal, scale
    )
    return out, (q, k, v, q_pos, kv_pos, out, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, g):
    from dlrover_tpu.ops.pallas_attention import (
        LANES,
        flash_backward_T,
        flash_backward_delta,
    )

    q, k, v, q_pos, kv_pos, out, lse = res
    b, sq, h, d = q.shape
    n = _axis_size(axis_name)
    scale_v = scale if scale is not None else d ** -0.5
    interpret = jax.default_backend() != "tpu"
    q_off = q_pos[0, 0]
    perm = [(i, (i + 1) % n) for i in range(n)]
    # Loop invariants, hoisted: final lse + global delta (from the FINAL
    # out/do — with p_ij = exp(s_ij - lse_final), each hop's grads are
    # exact partials of the global softmax), and the [b, h, s, d]
    # transposes the backward kernels want. k/v rotate around the ring
    # already transposed so no per-hop transpose remains.
    lse_lane = jnp.broadcast_to(
        lse.reshape(b * h, sq)[:, :, None], (b * h, sq, LANES)
    )
    di = flash_backward_delta(g, out)
    qT = q.transpose(0, 2, 1, 3)
    doT = g.transpose(0, 2, 1, 3)
    kT0 = k.transpose(0, 2, 1, 3)
    vT0 = v.transpose(0, 2, 1, 3)

    def skip(kT_cur, vT_cur):
        return (
            jnp.zeros_like(qT),
            jnp.zeros_like(kT_cur),
            jnp.zeros_like(vT_cur),
        )

    def block_grads(dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp):
        kv_off = kvp[0, 0]

        def run(causal_blk):
            return lambda: flash_backward_T(
                qT, kT_cur, vT_cur, doT, lse_lane, di, causal_blk,
                scale_v, interpret,
            )

        if causal:
            dqb, dkb, dvb = jax.lax.cond(
                kv_off > q_off,
                lambda: skip(kT_cur, vT_cur),
                lambda: jax.lax.cond(
                    kv_off == q_off, run(True), run(False)
                ),
            )
        else:
            dqb, dkb, dvb = run(False)()
        return (
            dqT + dqb.astype(jnp.float32),
            dkT_acc + dkb.astype(jnp.float32),
            dvT_acc + dvb.astype(jnp.float32),
        )

    dq0 = jnp.zeros(qT.shape, jnp.float32)
    dk0 = jnp.zeros(kT0.shape, jnp.float32)
    dv0 = jnp.zeros(vT0.shape, jnp.float32)
    if _ring_overlap():
        def hop(i, carry):
            dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp = carry
            # K/V rotation issued BEFORE the backward kernels (depends
            # only on the carry — hides behind the block compute). The
            # dk/dv accumulators can only move AFTER this hop's adds:
            # they ride the ring with the chunk, n permutes total, so
            # each shard's accumulated gradient lands back home.
            kT_nxt = jax.lax.ppermute(kT_cur, axis_name, perm)
            vT_nxt = jax.lax.ppermute(vT_cur, axis_name, perm)
            kvp_nxt = jax.lax.ppermute(kvp, axis_name, perm)
            dqT, dkT_acc, dvT_acc = block_grads(
                dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp
            )
            dkT_acc = jax.lax.ppermute(dkT_acc, axis_name, perm)
            dvT_acc = jax.lax.ppermute(dvT_acc, axis_name, perm)
            return (dqT, dkT_acc, dvT_acc, kT_nxt, vT_nxt, kvp_nxt)

        dqT, dkT, dvT, kT_l, vT_l, kvp_l = jax.lax.fori_loop(
            0, n - 1, hop, (dq0, dk0, dv0, kT0, vT0, kv_pos)
        )
        # Final chunk: grads computed without the wasted K/V rotation;
        # the accumulators take their n-th hop home.
        dqT, dkT, dvT = block_grads(dqT, dkT, dvT, kT_l, vT_l, kvp_l)
        dkT = jax.lax.ppermute(dkT, axis_name, perm)
        dvT = jax.lax.ppermute(dvT, axis_name, perm)
    else:
        def hop(i, carry):
            dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp = carry
            dqT, dkT_acc, dvT_acc = block_grads(
                dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp
            )
            # dk/dv accumulators ride the ring WITH k/v: after n hops
            # each shard's accumulated gradient is back on the shard
            # that owns it.
            kT_cur = jax.lax.ppermute(kT_cur, axis_name, perm)
            vT_cur = jax.lax.ppermute(vT_cur, axis_name, perm)
            kvp = jax.lax.ppermute(kvp, axis_name, perm)
            dkT_acc = jax.lax.ppermute(dkT_acc, axis_name, perm)
            dvT_acc = jax.lax.ppermute(dvT_acc, axis_name, perm)
            return (dqT, dkT_acc, dvT_acc, kT_cur, vT_cur, kvp)

        dqT, dkT, dvT, _, _, _ = jax.lax.fori_loop(
            0, n, hop, (dq0, dk0, dv0, kT0, vT0, kv_pos)
        )
    return (
        dqT.transpose(0, 2, 1, 3).astype(q.dtype),
        dkT.transpose(0, 2, 1, 3).astype(k.dtype),
        dvT.transpose(0, 2, 1, 3).astype(v.dtype),
        np.zeros(q_pos.shape, jax.dtypes.float0),
        np.zeros(kv_pos.shape, jax.dtypes.float0),
    )


ring_flash_attention_local.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def _ring_impl(impl: Optional[str]) -> str:
    """pallas (flash inner block) on TPU, xla elsewhere; DLROVER_TPU_RING
    overrides. The pallas path assumes each sp shard holds a contiguous
    chunk of the sequence — callers with packed/arbitrary positions must
    pass impl="xla"."""
    if impl is None:
        impl = os.environ.get("DLROVER_TPU_RING", "auto")
    impl = impl.lower()
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(
            f"ring attention impl {impl!r} not in ('auto', 'pallas', "
            f"'xla') — refusing to silently fall back"
        )
    return impl


def _axis_size(axis_name) -> int:
    """Static mesh-axis size inside shard_map: jax.lax.axis_size where
    it exists, the psum-of-unit idiom (resolved to a Python int at
    trace time) on older releases."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _shard_map_compat(body, mesh, in_specs, out_specs):
    """jax.shard_map(check_vma=False) where the public API exists,
    jax.experimental.shard_map.shard_map(check_rep=False) on older
    releases (the replication/VMA check was renamed across versions —
    both forms disable it, which the ring's manual collectives need)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_ring_attention(
    mesh: Mesh,
    rules=DEFAULT_RULES,
    axis_name="sp",
    impl: Optional[str] = None,
):
    """Returns an ``attention_fn`` drop-in for ``dot_product_attention``
    that runs ring attention along ``axis_name`` via a shard_map island.
    Plug into ``llama.forward(..., attention_fn=...)``.
    """
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), rules)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), rules)
    pos_spec = logical_to_spec(("batch", "seq"), rules)
    impl = _ring_impl(impl)
    local_fn = (
        ring_flash_attention_local
        if impl == "pallas"
        else ring_attention_local
    )

    def attention_fn(
        q, k, v, causal=True, q_positions=None, kv_positions=None,
        softmax_scale=None,
    ):
        b, sq = q.shape[0], q.shape[1]
        skv = k.shape[1]
        if q_positions is None:
            q_positions = jnp.broadcast_to(jnp.arange(sq), (b, sq))
        if kv_positions is None:
            kv_positions = jnp.broadcast_to(jnp.arange(skv), (b, skv))
        q_positions = jnp.broadcast_to(q_positions, (b, sq))
        kv_positions = jnp.broadcast_to(kv_positions, (b, skv))

        # Positional call: custom_vjp functions reject keyword args for
        # nondiff parameters.
        def body(q, k, v, qp, kp):
            return local_fn(
                q, k, v, qp, kp, axis_name, causal, softmax_scale
            )

        return _shard_map_compat(
            body,
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, pos_spec, pos_spec),
            out_specs=q_spec,
        )(q, k, v, q_positions, kv_positions)

    # The pallas path's ring-level custom VJP keeps O(s*d) residuals
    # (q/k/v/out + lse), so mlp_only remat may exempt it (llama.py).
    attention_fn.saveable_residuals = impl == "pallas"
    return attention_fn
