"""Ring attention: exact causal attention over a sequence-parallel mesh
axis (long-context path).

A ``shard_map`` island inside the jitted program: Q/K/V are sharded on
the ``sp`` mesh axis along sequence; each device computes blockwise
attention of its local queries against the K/V block it currently holds,
accumulating with an online (flash-style) softmax, then rotates K/V one
hop around the ``sp`` ring via ``ppermute`` — compute and ICI transfer
overlap, HBM never holds the full sequence. Position-based causal
masking makes the result exact for any block arrival order.

This is the long-context capability the reference lacks entirely
(SURVEY.md §2.9: EP/CP/ring attention "absent"); the reference's
DeepSpeed-SP awareness (docs/design/elastic.md:23-29) stops at
checkpoint/rendezvous metadata.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.ops.attention import NEG_INF
from dlrover_tpu.parallel.sharding import DEFAULT_RULES, logical_to_spec


def _block_attn(q, k, v, q_pos, kv_pos, causal, scale):
    """Partial attention of q against one K/V block.

    q: [b, sq, h, d]; k/v: [b, skv, hkv, d]. Returns (o, m, l) where
    o = sum(exp(logits - m) @ v), m = rowwise max logits, l = rowwise
    sum exp — the flash-attention partial triple, f32.

    Matmuls keep the input dtype (bf16 = full-rate MXU) and accumulate
    in f32; softmax math runs on the f32 logits with the scale applied
    there, so bf16 inputs lose nothing to a pre-scaled q.
    """
    b, sq, h, d = q.shape
    _, skv, hkv, _ = k.shape
    groups = h // hkv
    qg = q.reshape(b, sq, hkv, groups, d)
    logits = (
        jnp.einsum(
            "bqkgd,bskd->bkgqs",
            qg,
            k,
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    if causal:
        mask = q_pos[:, :, None] >= kv_pos[:, None, :]  # [b, sq, skv]
        logits = jnp.where(mask[:, None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                        # [b, hkv, g, sq]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bkgqs,bskd->bkgqd",
        p.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    ).astype(jnp.float32)
    return o, m, l


def ring_attention_local(
    q,
    k,
    v,
    q_positions,
    kv_positions,
    axis_name: str = "sp",
    causal: bool = True,
    softmax_scale: Optional[float] = None,
):
    """Per-shard body (call under shard_map). Shapes are LOCAL:
    q [b, sq_loc, h, d]; k/v [b, skv_loc, hkv, d]; positions are the
    GLOBAL token indices of the local rows ([b, sq_loc]/[b, skv_loc]).
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    n = jax.lax.axis_size(axis_name)
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    o0 = jnp.zeros((b, hkv, groups, sq, d), jnp.float32)
    m0 = jnp.full((b, hkv, groups, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, groups, sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        o, m, l, k_cur, v_cur, kv_pos = carry
        bo, bm, bl = _block_attn(
            q, k_cur, v_cur, q_positions, kv_pos, causal, scale
        )
        m_new = jnp.maximum(m, bm)
        corr = jnp.exp(m - m_new)
        bcorr = jnp.exp(bm - m_new)
        o = o * corr[..., None] + bo * bcorr[..., None]
        l = l * corr + bl * bcorr
        m = m_new
        # Rotate K/V one hop around the ring (overlaps with next block's
        # compute under XLA latency hiding).
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
        return (o, m, l, k_cur, v_cur, kv_pos)

    o, m, l, _, _, _ = jax.lax.fori_loop(
        0, n, step, (o0, m0, l0, k, v, kv_positions)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    out = jnp.where((m > NEG_INF / 2)[..., None], out, 0.0)
    # [b, hkv, g, sq, d] -> [b, sq, h, d]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, sq, h, d)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, rules=DEFAULT_RULES, axis_name="sp"):
    """Returns an ``attention_fn`` drop-in for ``dot_product_attention``
    that runs ring attention along ``axis_name`` via a shard_map island.
    Plug into ``llama.forward(..., attention_fn=...)``.
    """
    q_spec = logical_to_spec(("batch", "seq", "heads", "head_dim"), rules)
    kv_spec = logical_to_spec(("batch", "seq", "kv_heads", "head_dim"), rules)
    pos_spec = logical_to_spec(("batch", "seq"), rules)

    def attention_fn(
        q, k, v, causal=True, q_positions=None, kv_positions=None,
        softmax_scale=None,
    ):
        b, sq = q.shape[0], q.shape[1]
        skv = k.shape[1]
        if q_positions is None:
            q_positions = jnp.broadcast_to(jnp.arange(sq), (b, sq))
        if kv_positions is None:
            kv_positions = jnp.broadcast_to(jnp.arange(skv), (b, skv))
        q_positions = jnp.broadcast_to(q_positions, (b, sq))
        kv_positions = jnp.broadcast_to(kv_positions, (b, skv))

        body = functools.partial(
            ring_attention_local,
            axis_name=axis_name,
            causal=causal,
            softmax_scale=softmax_scale,
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(q_spec, kv_spec, kv_spec, pos_spec, pos_spec),
            out_specs=q_spec,
            check_vma=False,
        )(q, k, v, q_positions, kv_positions)

    return attention_fn
