"""Fused blockwise cross-entropy: the LM loss without the [N, V] logits.

The baseline loss (models/llama.py ``cross_entropy``) materializes full
f32 logits — at the flagship bench shape that is a 2 GB HBM round-trip
per pass (forward write, logsumexp read, softmax write/read in the
backward, plus the 2 GB value_and_grad residual). This op computes the
identical token-mean ``nll + z_weight * logz^2`` loss by streaming the
vocab in blocks with an online logsumexp, so only [block_n, block_v]
tiles ever exist:

- **Pallas path** (TPU): forward kernel with grid (n_tiles, v_tiles),
  v innermost; running (m, l, target_logit) live in VMEM scratch across
  v iterations (same sequential-grid trick as ops/pallas_attention.py).
  Backward recomputes the logits tile from (x, w, logz) flash-style and
  runs two kernels — one accumulating dx over v blocks, one accumulating
  dw over n blocks — so no O(N*V) tensor hits HBM in either direction.
- **XLA path** (CPU tests, sharded meshes): the same math as a
  ``lax.scan`` over vocab blocks. Saves the O(N*V) peak memory and the
  residual; XLA still stages each block through HBM.

Per-row integers/stats ride lane-broadcast [N, LANES] like the attention
kernel's lse. Custom VJP keeps residuals to (x, w, targets, weights,
logz) — logz is [N], everything else is an input.

Parity note: the reference has no loss kernels at all (torch frameworks
own the compute path, SURVEY.md §2.9); this is the TPU-native analogue
of the fused-CE kernels its workloads would get from apex/liger.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pick_bn(n: int, target: int) -> int:
    for cand in (target, 512, 256, 128, 64, 32, 16, 8):
        if cand <= n and n % cand == 0:
            return cand
    return n


# ---------------------------------------------------------------------------
# XLA (lax.scan) implementation — CPU fallback and sharded-mesh path
# ---------------------------------------------------------------------------


def _xla_forward(x, w, tgt, z_weight, block_v):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    nb = vp // block_v
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)

    def body(carry, j):
        m, l, tl = carry
        wj = jax.lax.dynamic_slice_in_dim(wp, j * block_v, block_v, axis=1)
        logits = jax.lax.dot_general(
            x, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [n, block_v]
        cols = j * block_v + jax.lax.iota(jnp.int32, block_v)
        logits = jnp.where(cols[None, :] < v, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        tl = tl + jnp.sum(
            jnp.where(cols[None, :] == tgt[:, None], logits, 0.0), axis=-1
        )
        return (m_new, l, tl), None

    init = (
        jnp.full((n,), NEG_INF, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, l, tl), _ = jax.lax.scan(body, init, jnp.arange(nb))
    logz = m + jnp.log(jnp.maximum(l, 1e-30))
    per_tok = logz - tl + z_weight * jnp.square(logz)
    return per_tok, logz


def _xla_backward(x, w, tgt, logz, coef_a, coef_b, block_v):
    """coef_a/b: [n] f32 — a*softmax - b*onehot is d(loss)/d(logits)."""
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    nb = vp // block_v
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)

    def body(dx, j):
        wj = jax.lax.dynamic_slice_in_dim(wp, j * block_v, block_v, axis=1)
        logits = jax.lax.dot_general(
            x, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        cols = j * block_v + jax.lax.iota(jnp.int32, block_v)
        logits = jnp.where(cols[None, :] < v, logits, NEG_INF)
        p = jnp.exp(logits - logz[:, None])
        g = coef_a[:, None] * p - jnp.where(
            cols[None, :] == tgt[:, None], coef_b[:, None], 0.0
        )
        g = g.astype(x.dtype)
        dx = dx + jax.lax.dot_general(
            g, wj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwj = jax.lax.dot_general(
            x, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [d, block_v]
        return dx, dwj

    dx, dws = jax.lax.scan(body, jnp.zeros((n, d), jnp.float32),
                           jnp.arange(nb))
    dw = dws.transpose(1, 0, 2).reshape(d, vp)[:, :v]
    return dx, dw


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(
    x_ref, w_ref, tgt_ref, ptok_ref, logz_ref, m_ref, l_ref, tl_ref,
    *, v: int, block_v: int, z_weight: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        tl_ref[:] = jnp.zeros_like(tl_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]                       # [bn, 1] int32
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, block_v]
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_blk = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    p_sum = jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True)
    l_new = l_prev * jnp.exp(m_prev - m_new) + p_sum
    tl_new = tl_ref[:, :1] + jnp.sum(
        jnp.where(cols == tgt, logits, 0.0), axis=-1, keepdims=True
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    tl_ref[:] = jnp.broadcast_to(tl_new, tl_ref.shape)

    @pl.when(j == nj - 1)
    def _():
        logz = m_new + jnp.log(jnp.maximum(l_new, 1e-30))
        per_tok = logz - tl_new + z_weight * jnp.square(logz)
        logz_ref[...] = jnp.broadcast_to(logz, logz_ref.shape)
        ptok_ref[...] = jnp.broadcast_to(per_tok, ptok_ref.shape)


def _bwd_dx_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, a_ref, b_ref, dx_ref, acc_ref,
    *, v: int, block_v: int,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]
    logz = logz_ref[...][:, :1]
    a = a_ref[...][:, :1]
    b = b_ref[...][:, :1]
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)
    p = jnp.exp(logits - logz)
    g = (a * p - jnp.where(cols == tgt, b, 0.0)).astype(x.dtype)
    acc_ref[:] += jax.lax.dot_general(
        g, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nj - 1)
    def _():
        dx_ref[...] = acc_ref[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, a_ref, b_ref, dw_ref, acc_ref,
    *, v: int, block_v: int,
):
    i = pl.program_id(1)
    ni = pl.num_programs(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]
    logz = logz_ref[...][:, :1]
    a = a_ref[...][:, :1]
    b = b_ref[...][:, :1]
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)
    p = jnp.exp(logits - logz)
    g = (a * p - jnp.where(cols == tgt, b, 0.0)).astype(x.dtype)
    acc_ref[:] += jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == ni - 1)
    def _():
        dw_ref[...] = acc_ref[:].astype(dw_ref.dtype)


def _lane(arr, dtype):
    """[n] -> lane-broadcast [n, LANES] (the stats layout)."""
    return jnp.broadcast_to(arr.astype(dtype)[:, None],
                            (arr.shape[0], LANES))


def _pallas_forward(x, w, tgt, z_weight, block_n, block_v, interpret):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    bn = _pick_bn(n, block_n)
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)
    grid = (n // bn, vp // block_v)

    ptok, logz = pl.pallas_call(
        functools.partial(
            _fwd_kernel, v=v, block_v=block_v, z_weight=z_weight
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(x, wp, _lane(tgt, jnp.int32))
    return ptok[:, 0], logz[:, 0]


def _pallas_backward(
    x, w, tgt, logz, coef_a, coef_b, block_n, block_v, interpret
):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    bn = _pick_bn(n, block_n)
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)
    tgt_l = _lane(tgt, jnp.int32)
    logz_l = _lane(logz, jnp.float32)
    a_l = _lane(coef_a, jnp.float32)
    b_l = _lane(coef_b, jnp.float32)

    # Mosaic's scoped-VMEM budget tightens slightly at very large row
    # counts (measured: the 1024-wide vocab block fits at n<=32k and
    # overflows by ~170KB at n=64k) — halve the block there.
    bv_dx = block_v if n <= 32768 else min(block_v, 512)
    vp_dx = _ceil_to(v, bv_dx)
    wp_dx = wp[:, :vp_dx] if vp_dx <= wp.shape[1] else jnp.pad(
        w, ((0, 0), (0, vp_dx - v))
    ).astype(x.dtype)
    stat = pl.BlockSpec((bn, LANES), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, v=v, block_v=bv_dx),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        grid=(n // bn, vp_dx // bv_dx),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv_dx), lambda i, j: (0, j)),
            stat, stat, stat, stat,
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(x, wp_dx, tgt_l, logz_l, a_l, b_l)

    # The dw kernel holds a [d, block_v] f32 accumulator on top of the
    # streamed tiles — at d=1024, block_v=1024 that exceeds the 16 MB
    # scoped-VMEM budget (measured on v5e), so it runs at half the vocab
    # block. Re-pad for its own block size.
    bv_dw = min(block_v, 512)
    vp_dw = _ceil_to(v, bv_dw)
    wp_dw = wp[:, :vp_dw] if vp_dw <= vp else jnp.pad(
        w, ((0, 0), (0, vp_dw - v))
    ).astype(x.dtype)
    stat2 = pl.BlockSpec((bn, LANES), lambda j, i: (i, 0))
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, v=v, block_v=bv_dw),
        out_shape=jax.ShapeDtypeStruct((d, vp_dw), jnp.float32),
        grid=(vp_dw // bv_dw, n // bn),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, bv_dw), lambda j, i: (0, j)),
            stat2, stat2, stat2, stat2,
        ],
        out_specs=pl.BlockSpec((d, bv_dw), lambda j, i: (0, j)),
        scratch_shapes=[pltpu.VMEM((d, bv_dw), jnp.float32)],
        interpret=interpret,
    )(x, wp_dw, tgt_l, logz_l, a_l, b_l)
    return dx, dw[:, :v]


# ---------------------------------------------------------------------------
# Custom-VJP core and public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused_ce_core(x, w, tgt, wgt, z_weight, block_n, block_v, use_pallas):
    per_tok, _ = (
        _pallas_forward(x, w, tgt, z_weight, block_n, block_v,
                        interpret=jax.default_backend() != "tpu")
        if use_pallas
        else _xla_forward(x, w, tgt, z_weight, block_v)
    )
    return jnp.sum(per_tok * wgt)


def _core_fwd(x, w, tgt, wgt, z_weight, block_n, block_v, use_pallas):
    if use_pallas:
        per_tok, logz = _pallas_forward(
            x, w, tgt, z_weight, block_n, block_v,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        per_tok, logz = _xla_forward(x, w, tgt, z_weight, block_v)
    return jnp.sum(per_tok * wgt), (x, w, tgt, wgt, logz)


def _core_bwd(z_weight, block_n, block_v, use_pallas, res, gbar):
    x, w, tgt, wgt, logz = res
    scaled = gbar * wgt                                   # [n] f32
    coef_a = scaled * (1.0 + 2.0 * z_weight * logz)
    coef_b = scaled
    if use_pallas:
        dx, dw = _pallas_backward(
            x, w, tgt, logz, coef_a, coef_b, block_n, block_v,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        dx, dw = _xla_backward(
            x, w, tgt, logz, coef_a, coef_b, block_v
        )
    return (
        dx.astype(x.dtype),
        dw.astype(w.dtype),
        np.zeros(tgt.shape, jax.dtypes.float0),
        jnp.zeros_like(wgt),
    )


_fused_ce_core.defvjp(_core_fwd, _core_bwd)


def fused_cross_entropy(
    x,
    w,
    targets,
    mask=None,
    z_weight: float = 1e-4,
    block_n: int = 512,
    block_v: int = 1024,
    impl: Optional[str] = None,
):
    """Token-mean CE + z-loss from hidden states, no [N, V] logits.

    Identical semantics to ``llama.cross_entropy(x @ w, targets, mask)``
    (f32 logits, token-mean weighting, ``z_weight * logz^2``). x: [..., d]
    hidden states (post final-norm, compute dtype); w: [d, V] unembedding;
    targets int [...]; mask optional [...] — tokens with mask 0 contribute
    nothing.

    impl: "pallas" | "xla" | None (auto: pallas on TPU).
    """
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    d = x.shape[-1]
    n = int(np.prod(x.shape[:-1]))
    x2 = x.reshape(n, d)
    tgt = targets.reshape(n)
    if mask is None:
        wgt = jnp.full((n,), 1.0 / n, jnp.float32)
    else:
        m = mask.reshape(n).astype(jnp.float32)
        wgt = m / jnp.maximum(jnp.sum(m), 1.0)
    wgt = jax.lax.stop_gradient(wgt)
    # Pad the token dim so any (b, s) works; padded rows carry zero weight
    # and target 0, so they affect neither loss nor grads.
    n_pad = _ceil_to(max(n, 8), 8)
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
        tgt = jnp.pad(tgt, (0, n_pad - n))
        wgt = jnp.pad(wgt, (0, n_pad - n))
    return _fused_ce_core(
        x2, w, tgt, wgt, z_weight, block_n, block_v, impl == "pallas"
    )
