"""Fused blockwise cross-entropy: the LM loss without the [N, V] logits.

The baseline loss (models/llama.py ``cross_entropy``) materializes full
f32 logits — at the flagship bench shape that is a 2 GB HBM round-trip
per pass (forward write, logsumexp read, softmax write/read in the
backward, plus the 2 GB value_and_grad residual). This op computes the
identical token-mean ``nll + z_weight * logz^2`` loss by streaming the
vocab in blocks with an online logsumexp, so only [block_n, block_v]
tiles ever exist:

- **Chunked path** (default): ``lax.scan`` over ROW chunks with exact
  per-chunk softmax, computing loss AND unit-cotangent gradients in the
  forward (the loss is a scalar, so grads scale linearly by the incoming
  cotangent — the backward is two multiplies). Total matmul FLOPs equal
  the dense path's three (logits, dx, dw): no flash-style recompute.
  Peak memory is one [block_rows, V] f32 logits tile plus the [d, V]
  f32 dw accumulator — residuals are (dx_unit, dw_unit), both small.
- **Pallas path** (opt-in): forward kernel with grid (n_tiles, v_tiles),
  v innermost; running (m, l, target_logit) live in VMEM scratch across
  v iterations (same sequential-grid trick as ops/pallas_attention.py).
  Backward recomputes the logits tile from (x, w, logz) flash-style and
  runs two kernels — one accumulating dx over v blocks, one accumulating
  dw over n blocks. Strictly lowest memory (no [block, V] tile in HBM),
  but pays 5 logits-sized matmuls vs the chunked path's 3 — measured
  slower on v5e; kept for the truly HBM-starved corner.
- **XLA path** (sharded meshes): the same math as a ``lax.scan`` over
  vocab blocks, keeping the [N, d] activations un-rechunked so GSPMD
  sharding over batch/seq axes passes through untouched.

Per-row integers/stats ride lane-broadcast [N, LANES] like the attention
kernel's lse. Custom VJP keeps residuals to (x, w, targets, weights,
logz) — logz is [N], everything else is an input.

Parity note: the reference has no loss kernels at all (torch frameworks
own the compute path, SURVEY.md §2.9); this is the TPU-native analogue
of the fused-CE kernels its workloads would get from apex/liger.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128

# Measured dense/fused crossover in N*V elements (f32-logits bytes / 4).
# Evidence trail (the §33 kernel campaign re-measured after the MoE /
# decode changes shifted step composition — CE itself is untouched by
# them, and the ratio held): v5e bench r05 AND the BENCH_SELF
# re-measure both put the flagship head shape n=16384, v=32000 —
# N*V = 5.24e8, just below this line — at chunked = 1.042x DENSE (the
# [d, V] f32 dw-carry HBM round-trip per row chunk is pure overhead
# while the logits still fit), so dense keeps its edge below the line;
# above it the ~2 GiB+ logits are what stop long-context steps from
# fitting (the attn_save remat budget), and the fused path's time cost
# is a wash. llama.resolve_ce_path delegates here; the CE A/B bench
# reports the choice (ce_auto_path) plus ce_auto_pin_consistent — a
# live check that the measured ratio still agrees with this pin, so a
# drifted crossover is loud in the artifact rather than silently
# mis-routing the auto path.
CE_CROSSOVER_EVIDENCE = {
    "nv": 16384 * 32000,
    "chunked_vs_dense": 1.042,
    "rounds": ("r05", "BENCH_SELF"),
}
AUTO_FUSED_MIN_NV = 2 * 1024**3 // 4


def auto_prefers_dense(n_tokens: int, vocab: int) -> bool:
    """True when CE "auto" should run the DENSE logits path for a batch
    of ``n_tokens`` rows over ``vocab`` classes (below the measured
    crossover, see AUTO_FUSED_MIN_NV)."""
    return n_tokens * vocab < AUTO_FUSED_MIN_NV


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pick_bn(n: int, target: int) -> int:
    for cand in (target, 512, 256, 128, 64, 32, 16, 8):
        if cand <= n and n % cand == 0:
            return cand
    return n


# ---------------------------------------------------------------------------
# XLA (lax.scan) implementation — CPU fallback and sharded-mesh path
# ---------------------------------------------------------------------------


def _xla_forward(x, w, tgt, z_weight, block_v):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    nb = vp // block_v
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)

    def body(carry, j):
        m, l, tl = carry
        wj = jax.lax.dynamic_slice_in_dim(wp, j * block_v, block_v, axis=1)
        logits = jax.lax.dot_general(
            x, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [n, block_v]
        cols = j * block_v + jax.lax.iota(jnp.int32, block_v)
        logits = jnp.where(cols[None, :] < v, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        tl = tl + jnp.sum(
            jnp.where(cols[None, :] == tgt[:, None], logits, 0.0), axis=-1
        )
        return (m_new, l, tl), None

    init = (
        jnp.full((n,), NEG_INF, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, l, tl), _ = jax.lax.scan(body, init, jnp.arange(nb))
    logz = m + jnp.log(jnp.maximum(l, 1e-30))
    per_tok = logz - tl + z_weight * jnp.square(logz)
    return per_tok, logz


def _xla_backward(x, w, tgt, logz, coef_a, coef_b, block_v):
    """coef_a/b: [n] f32 — a*softmax - b*onehot is d(loss)/d(logits)."""
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    nb = vp // block_v
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)

    def body(dx, j):
        wj = jax.lax.dynamic_slice_in_dim(wp, j * block_v, block_v, axis=1)
        logits = jax.lax.dot_general(
            x, wj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        cols = j * block_v + jax.lax.iota(jnp.int32, block_v)
        logits = jnp.where(cols[None, :] < v, logits, NEG_INF)
        p = jnp.exp(logits - logz[:, None])
        g = coef_a[:, None] * p - jnp.where(
            cols[None, :] == tgt[:, None], coef_b[:, None], 0.0
        )
        g = g.astype(x.dtype)
        dx = dx + jax.lax.dot_general(
            g, wj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwj = jax.lax.dot_general(
            x, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [d, block_v]
        return dx, dwj

    dx, dws = jax.lax.scan(body, jnp.zeros((n, d), jnp.float32),
                           jnp.arange(nb))
    dw = dws.transpose(1, 0, 2).reshape(d, vp)[:, :v]
    return dx, dw


# ---------------------------------------------------------------------------
# Chunked implementation — gradients computed in the forward
# ---------------------------------------------------------------------------


def _pick_chunk(n: int, v: int, block_rows: Optional[int]) -> int:
    """Rows per chunk: the largest power of two whose f32 logits tile
    stays under ~1.1 GB (measured on v5e at n=16k/v=32k: 8192 rows runs
    at 1.014x dense vs 1.07x for 4096 — the [d, V] dw-carry HBM
    round-trip amortizes with fewer chunks — while one ~1 GB transient
    tile still leaves HBM for a long-context step)."""
    if block_rows is not None:
        return max(8, min(block_rows, n))
    budget = 1152 * 1024**2
    c = 8
    while c * 2 <= n and (c * 2) * v * 4 <= budget:
        c *= 2
    # Padding to a chunk multiple costs real matmul FLOPs on zero-weight
    # rows (n=8200 with chunk 8192 would nearly double the CE) — halve
    # the chunk while the pad waste exceeds ~12.5% of n.
    while c > 8 and ((n + c - 1) // c * c - n) * 8 > n:
        c //= 2
    return c


def _chunk_grad_tile(x, w, tgt, wgt, z_weight):
    """One row chunk, exact softmax: (loss_contrib, dx_unit, dw_unit)."""
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [c, v] f32
    m = jnp.max(logits, axis=-1, keepdims=True)
    p_un = jnp.exp(logits - m)
    logz = (m + jnp.log(jnp.sum(p_un, axis=-1, keepdims=True)))[:, 0]
    tl = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    per_tok = logz - tl + z_weight * jnp.square(logz)
    loss = jnp.sum(per_tok * wgt)
    # d(loss)/d(logits) at unit cotangent: a*softmax - wgt*onehot.
    a = wgt * (1.0 + 2.0 * z_weight * logz)
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    g = a[:, None] * jnp.exp(logits - logz[:, None]) - jnp.where(
        cols == tgt[:, None], wgt[:, None], 0.0
    )
    g = g.astype(x.dtype)
    dx = jax.lax.dot_general(
        g, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d, v] f32
    return loss, dx, dw


def _chunked_loss_only(x, w, tgt, wgt, z_weight, chunk):
    n, d = x.shape
    nb = n // chunk
    wc = w.astype(x.dtype)

    def body(loss, inp):
        xs, ts, ws = inp
        logits = jax.lax.dot_general(
            xs, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, ts[:, None], axis=-1)[:, 0]
        per_tok = logz - tl + z_weight * jnp.square(logz)
        return loss + jnp.sum(per_tok * ws), None

    loss, _ = jax.lax.scan(
        body,
        jnp.zeros((), jnp.float32),
        (
            x.reshape(nb, chunk, d),
            tgt.reshape(nb, chunk),
            wgt.reshape(nb, chunk),
        ),
    )
    return loss


def _chunked_fwd_pass(x, w, tgt, wgt, z_weight, chunk):
    """Full fwd+grad sweep: (loss, dx_unit [n,d], dw_unit [d,v] f32)."""
    n, d = x.shape
    v = w.shape[1]
    nb = n // chunk
    wc = w.astype(x.dtype)

    def body(carry, inp):
        dw_acc, loss_acc = carry
        xs, ts, ws = inp
        loss, dx, dw = _chunk_grad_tile(xs, wc, ts, ws, z_weight)
        return (dw_acc + dw, loss_acc + loss), dx

    (dw, loss), dxs = jax.lax.scan(
        body,
        (jnp.zeros((d, v), jnp.float32), jnp.zeros((), jnp.float32)),
        (
            x.reshape(nb, chunk, d),
            tgt.reshape(nb, chunk),
            wgt.reshape(nb, chunk),
        ),
    )
    return loss, dxs.reshape(n, d), dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _chunked_ce_core(x, w, tgt, wgt, z_weight, chunk):
    return _chunked_loss_only(x, w, tgt, wgt, z_weight, chunk)


def _chunked_fwd(x, w, tgt, wgt, z_weight, chunk):
    loss, dx_unit, dw_unit = _chunked_fwd_pass(
        x, w, tgt, wgt, z_weight, chunk
    )
    return loss, (dx_unit, dw_unit.astype(w.dtype))


def _chunked_bwd(z_weight, chunk, res, gbar):
    dx_unit, dw_unit = res
    n = dx_unit.shape[0]
    return (
        (gbar * dx_unit.astype(jnp.float32)).astype(dx_unit.dtype),
        (gbar * dw_unit.astype(jnp.float32)).astype(dw_unit.dtype),
        np.zeros((n,), jax.dtypes.float0),
        jnp.zeros((n,), jnp.float32),
    )


_chunked_ce_core.defvjp(_chunked_fwd, _chunked_bwd)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(
    x_ref, w_ref, tgt_ref, ptok_ref, logz_ref, m_ref, l_ref, tl_ref,
    *, v: int, block_v: int, z_weight: float,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        tl_ref[:] = jnp.zeros_like(tl_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]                       # [bn, 1] int32
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, block_v]
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_blk = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    p_sum = jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True)
    l_new = l_prev * jnp.exp(m_prev - m_new) + p_sum
    tl_new = tl_ref[:, :1] + jnp.sum(
        jnp.where(cols == tgt, logits, 0.0), axis=-1, keepdims=True
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    tl_ref[:] = jnp.broadcast_to(tl_new, tl_ref.shape)

    @pl.when(j == nj - 1)
    def _():
        logz = m_new + jnp.log(jnp.maximum(l_new, 1e-30))
        per_tok = logz - tl_new + z_weight * jnp.square(logz)
        logz_ref[...] = jnp.broadcast_to(logz, logz_ref.shape)
        ptok_ref[...] = jnp.broadcast_to(per_tok, ptok_ref.shape)


def _bwd_dx_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, a_ref, b_ref, dx_ref, acc_ref,
    *, v: int, block_v: int,
):
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]
    logz = logz_ref[...][:, :1]
    a = a_ref[...][:, :1]
    b = b_ref[...][:, :1]
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)
    p = jnp.exp(logits - logz)
    g = (a * p - jnp.where(cols == tgt, b, 0.0)).astype(x.dtype)
    acc_ref[:] += jax.lax.dot_general(
        g, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nj - 1)
    def _():
        dx_ref[...] = acc_ref[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, a_ref, b_ref, dw_ref, acc_ref,
    *, v: int, block_v: int,
):
    i = pl.program_id(1)
    ni = pl.num_programs(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    w = w_ref[...]
    tgt = tgt_ref[...][:, :1]
    logz = logz_ref[...][:, :1]
    a = a_ref[...][:, :1]
    b = b_ref[...][:, :1]
    bn = x.shape[0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (bn, block_v), 1
    )
    logits = jnp.where(cols < v, logits, NEG_INF)
    p = jnp.exp(logits - logz)
    g = (a * p - jnp.where(cols == tgt, b, 0.0)).astype(x.dtype)
    acc_ref[:] += jax.lax.dot_general(
        x, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == ni - 1)
    def _():
        dw_ref[...] = acc_ref[:].astype(dw_ref.dtype)


def _lane(arr, dtype):
    """[n] -> lane-broadcast [n, LANES] (the stats layout)."""
    return jnp.broadcast_to(arr.astype(dtype)[:, None],
                            (arr.shape[0], LANES))


def _pallas_forward(x, w, tgt, z_weight, block_n, block_v, interpret):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    bn = _pick_bn(n, block_n)
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)
    grid = (n // bn, vp // block_v)

    ptok, logz = pl.pallas_call(
        functools.partial(
            _fwd_kernel, v=v, block_v=block_v, z_weight=z_weight
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
            pltpu.VMEM((bn, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(x, wp, _lane(tgt, jnp.int32))
    return ptok[:, 0], logz[:, 0]


def _pallas_backward(
    x, w, tgt, logz, coef_a, coef_b, block_n, block_v, interpret
):
    n, d = x.shape
    v = w.shape[1]
    vp = _ceil_to(v, block_v)
    bn = _pick_bn(n, block_n)
    wp = jnp.pad(w, ((0, 0), (0, vp - v))).astype(x.dtype)
    tgt_l = _lane(tgt, jnp.int32)
    logz_l = _lane(logz, jnp.float32)
    a_l = _lane(coef_a, jnp.float32)
    b_l = _lane(coef_b, jnp.float32)

    # Mosaic's scoped-VMEM budget tightens slightly at very large row
    # counts (measured: the 1024-wide vocab block fits at n<=32k and
    # overflows by ~170KB at n=64k) — halve the block there.
    bv_dx = block_v if n <= 32768 else min(block_v, 512)
    vp_dx = _ceil_to(v, bv_dx)
    wp_dx = wp[:, :vp_dx] if vp_dx <= wp.shape[1] else jnp.pad(
        w, ((0, 0), (0, vp_dx - v))
    ).astype(x.dtype)
    stat = pl.BlockSpec((bn, LANES), lambda i, j: (i, 0))
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, v=v, block_v=bv_dx),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        grid=(n // bn, vp_dx // bv_dx),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv_dx), lambda i, j: (0, j)),
            stat, stat, stat, stat,
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(x, wp_dx, tgt_l, logz_l, a_l, b_l)

    # The dw kernel holds a [d, block_v] f32 accumulator on top of the
    # streamed tiles — at d=1024, block_v=1024 that exceeds the 16 MB
    # scoped-VMEM budget (measured on v5e), so it runs at half the vocab
    # block. Re-pad for its own block size.
    bv_dw = min(block_v, 512)
    vp_dw = _ceil_to(v, bv_dw)
    wp_dw = wp[:, :vp_dw] if vp_dw <= vp else jnp.pad(
        w, ((0, 0), (0, vp_dw - v))
    ).astype(x.dtype)
    stat2 = pl.BlockSpec((bn, LANES), lambda j, i: (i, 0))
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, v=v, block_v=bv_dw),
        out_shape=jax.ShapeDtypeStruct((d, vp_dw), jnp.float32),
        grid=(vp_dw // bv_dw, n // bn),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, bv_dw), lambda j, i: (0, j)),
            stat2, stat2, stat2, stat2,
        ],
        out_specs=pl.BlockSpec((d, bv_dw), lambda j, i: (0, j)),
        scratch_shapes=[pltpu.VMEM((d, bv_dw), jnp.float32)],
        interpret=interpret,
    )(x, wp_dw, tgt_l, logz_l, a_l, b_l)
    return dx, dw[:, :v]


# ---------------------------------------------------------------------------
# Custom-VJP core and public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused_ce_core(x, w, tgt, wgt, z_weight, block_n, block_v, use_pallas):
    per_tok, _ = (
        _pallas_forward(x, w, tgt, z_weight, block_n, block_v,
                        interpret=jax.default_backend() != "tpu")
        if use_pallas
        else _xla_forward(x, w, tgt, z_weight, block_v)
    )
    return jnp.sum(per_tok * wgt)


def _core_fwd(x, w, tgt, wgt, z_weight, block_n, block_v, use_pallas):
    if use_pallas:
        per_tok, logz = _pallas_forward(
            x, w, tgt, z_weight, block_n, block_v,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        per_tok, logz = _xla_forward(x, w, tgt, z_weight, block_v)
    return jnp.sum(per_tok * wgt), (x, w, tgt, wgt, logz)


def _core_bwd(z_weight, block_n, block_v, use_pallas, res, gbar):
    x, w, tgt, wgt, logz = res
    scaled = gbar * wgt                                   # [n] f32
    coef_a = scaled * (1.0 + 2.0 * z_weight * logz)
    coef_b = scaled
    if use_pallas:
        dx, dw = _pallas_backward(
            x, w, tgt, logz, coef_a, coef_b, block_n, block_v,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        dx, dw = _xla_backward(
            x, w, tgt, logz, coef_a, coef_b, block_v
        )
    return (
        dx.astype(x.dtype),
        dw.astype(w.dtype),
        np.zeros(tgt.shape, jax.dtypes.float0),
        jnp.zeros_like(wgt),
    )


_fused_ce_core.defvjp(_core_fwd, _core_bwd)


def _multi_device_mesh_active() -> bool:
    """True when tracing under a ``with mesh:`` context spanning >1
    device — the case where the chunked path's row re-chunking could
    fight GSPMD's batch/seq sharding and the plain vocab-scan XLA path
    (which leaves [N, d] intact) is the safe choice."""
    try:
        from dlrover_tpu.parallel.sharding import current_mesh

        mesh = current_mesh()
        return mesh is not None and mesh.size > 1
    except Exception:
        return False


def resolve_impl(impl: Optional[str] = None) -> str:
    """The fused-CE sub-impl auto-selection: "chunked" single-device,
    the GSPMD-safe vocab-scan "xla" path under a multi-device mesh.
    Mesh-dependent — call under the active ``with mesh:``."""
    if impl is not None:
        return impl
    return "xla" if _multi_device_mesh_active() else "chunked"


def fused_cross_entropy(
    x,
    w,
    targets,
    mask=None,
    z_weight: float = 1e-4,
    block_n: int = 512,
    block_v: int = 1024,
    block_rows: Optional[int] = None,
    impl: Optional[str] = None,
):
    """Token-mean CE + z-loss from hidden states, no [N, V] logits.

    Identical semantics to ``llama.cross_entropy(x @ w, targets, mask)``
    (f32 logits, token-mean weighting, ``z_weight * logz^2``). x: [..., d]
    hidden states (post final-norm, compute dtype); w: [d, V] unembedding;
    targets int [...]; mask optional [...] — tokens with mask 0 contribute
    nothing.

    impl: "chunked" | "pallas" | "xla" | None. Auto picks "chunked"
    (dense-speed, O(block_rows*V) memory) except under a multi-device
    mesh, where the vocab-scan "xla" path keeps GSPMD shardings intact
    (``resolve_impl`` is the selection, shared with the driver dryrun's
    per-mesh CE logging).
    """
    if impl is None:
        impl = resolve_impl()
    d = x.shape[-1]
    n = int(np.prod(x.shape[:-1]))
    x2 = x.reshape(n, d)
    tgt = targets.reshape(n)
    if mask is None:
        wgt = jnp.full((n,), 1.0 / n, jnp.float32)
    else:
        m = mask.reshape(n).astype(jnp.float32)
        wgt = m / jnp.maximum(jnp.sum(m), 1.0)
    wgt = jax.lax.stop_gradient(wgt)
    if impl == "chunked":
        chunk = _pick_chunk(max(n, 8), w.shape[1], block_rows)
        n_pad = _ceil_to(max(n, 8), chunk)
    else:
        # Pad the token dim so any (b, s) works; padded rows carry zero
        # weight and target 0 — they affect neither loss nor grads.
        n_pad = _ceil_to(max(n, 8), 8)
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
        tgt = jnp.pad(tgt, (0, n_pad - n))
        wgt = jnp.pad(wgt, (0, n_pad - n))
    if impl == "chunked":
        return _chunked_ce_core(x2, w, tgt, wgt, z_weight, chunk)
    return _fused_ce_core(
        x2, w, tgt, wgt, z_weight, block_n, block_v, impl == "pallas"
    )
