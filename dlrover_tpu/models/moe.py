"""Mixture-of-Experts MLP: GShard einsum dispatch and a dropless
grouped-matmul path.

Two implementations behind one surface:

- **gshard** (:func:`moe_mlp`): one-hot einsum dispatch with per-expert
  capacity; over-capacity tokens are dropped (residual carries them).
  Expert-parallel by construction — the dispatch/combine einsums
  contract token axes (sharded over dp/ep) against expert axes (ep), so
  GSPMD lowers the resharding to all-to-all over ICI. Static shapes,
  works under any mesh.
- **dropless** (:func:`moe_mlp_dropless`): megablox-style — sort token
  copies by expert and run grouped (ragged) matmuls
  (``jax.experimental.pallas.ops.tpu.megablox.gmm``), so NO token is
  ever dropped and no capacity/one-hot FLOPs are wasted. Group sizes
  are data-dependent, which GSPMD cannot shard over ``ep`` — this path
  is for meshes with ep == 1 (each device holds all experts; dp/tp as
  usual). ``models/llama.mlp_block`` picks it automatically on
  single-device meshes only (auto-selection under multi-device meshes
  stays with the GSPMD-proven gshard path).
- **dropless under ep** (:func:`moe_mlp_dropless_ep`): the dropless
  property survives expert scaling via ``shard_map`` — each ep shard
  routes its local tokens, ships them to their experts' shards with
  ``jax.lax.ragged_all_to_all`` (sized by the actual routing, no
  capacity bound), runs the per-shard grouped matmuls, and ships
  results back through the reverse ragged exchange.

The reference has no MoE/EP support (SURVEY.md section 2.9: "absent") —
this is parity-plus for the TPU build.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.parallel.sharding import with_logical_constraint


class MoEMetrics(NamedTuple):
    aux_loss: jnp.ndarray     # load-balance loss (scalar)
    router_z_loss: jnp.ndarray
    dropped_fraction: jnp.ndarray


def expert_capacity(
    seq: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    cap = int(seq * top_k * capacity_factor / n_experts)
    return max(cap, 1)


def moe_mlp(
    x,
    router_w,     # [embed, experts]
    w_gate,       # [experts, embed, mlp]
    w_up,         # [experts, embed, mlp]
    w_down,       # [experts, mlp, embed]
    top_k: int = 2,
    capacity_factor: float = 1.25,
):
    """x: [batch, seq, embed] -> (out, MoEMetrics).

    Groups = batch rows (tokens within one sequence compete for expert
    capacity). Over-capacity tokens are dropped (residual carries them).
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    cap = expert_capacity(s, e, top_k, capacity_factor)

    router_logits = jnp.einsum(
        "gsd,de->gse", x.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)

    # --- iterative top-k one-hot assignment with capacity ---------------
    combine = jnp.zeros((b, s, e, cap), dtype=jnp.float32)
    remaining = probs
    # position counters per expert, advanced between the k rounds
    used = jnp.zeros((b, e), dtype=jnp.int32)
    dropped = 0.0
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                   # [g, s]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [g, s, e]
        gate = jnp.sum(remaining * onehot, axis=-1)            # [g, s]
        remaining = remaining * (1.0 - onehot)
        # capacity slot for each token in its chosen expert
        pos_in_expert = (
            jnp.cumsum(onehot, axis=1) - onehot
        ) + used[:, None, :]                                   # [g, s, e]
        pos = jnp.einsum("gse,gse->gs", pos_in_expert, onehot).astype(
            jnp.int32
        )
        fits = pos < cap
        dropped = dropped + jnp.mean(1.0 - fits)
        gate = gate * fits
        pos_onehot = jax.nn.one_hot(
            jnp.where(fits, pos, cap), cap, dtype=jnp.float32
        )  # out-of-range -> all-zero row
        combine = combine + (
            gate[..., None, None] * onehot[..., None] * pos_onehot[:, :, None, :]
        )
        used = used + jnp.sum(onehot * fits[..., None], axis=1).astype(jnp.int32)

    # renormalize the kept gates so they sum to 1 per token (when any kept)
    denom = jnp.sum(combine, axis=(-2, -1), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0.0).astype(x.dtype)
    combine = combine.astype(x.dtype)

    # --- dispatch -> expert compute -> combine --------------------------
    # [e, g, cap, d]: token shards (dp/ep) contract into expert shards (ep)
    expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, x)
    expert_in = with_logical_constraint(
        expert_in, ("expert", "batch", "capacity", "embed")
    )
    h = jnp.einsum("egcd,edf->egcf", expert_in, w_gate.astype(x.dtype))
    u = jnp.einsum("egcd,edf->egcf", expert_in, w_up.astype(x.dtype))
    h = jax.nn.silu(h) * u
    h = with_logical_constraint(h, ("expert", "batch", "capacity", "mlp"))
    expert_out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(x.dtype))
    # Without this constraint GSPMD infers an (e, d)-sharded layout from
    # w_down and then can't reshard the backward cotangent (which
    # arrives batch-sharded from dout) efficiently — involuntary full
    # rematerialization on the ep mesh.
    expert_out = with_logical_constraint(
        expert_out, ("expert", "batch", "capacity", "embed")
    )
    out = jnp.einsum("egcd,gsec->gsd", expert_out, combine)
    out = with_logical_constraint(out, ("batch", "seq", "embed"))

    # --- router losses (shared with the dropless path) -------------------
    aux, z = _router_losses(router_logits, probs)
    metrics = MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=dropped / top_k,
    )
    return out, metrics


# ---------------------------------------------------------------------------
# Dropless path: sort-by-expert + grouped matmul (megablox gmm)
# ---------------------------------------------------------------------------


def _router_losses(router_logits, probs):
    e = probs.shape[-1]
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=tuple(range(top1.ndim - 1)))
    mean_probs = jnp.mean(probs, axis=tuple(range(probs.ndim - 1)))
    aux = e * jnp.sum(frac_tokens * mean_probs)
    z = jnp.mean(
        jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2
    )
    return aux, z


def _tile(dim: int, cap: int = 512) -> int:
    """Largest power-of-two divisor of ``dim``, capped — gmm requires
    every dimension to be tile-divisible."""
    t = 1
    while t * 2 <= min(dim, cap) and dim % (t * 2) == 0:
        t *= 2
    return t


# The dispatch/combine gathers are permutation-shaped, and XLA's
# transpose of a gather is a SCATTER(-add) — slow on TPU and the bulk
# of the dropless path's overhead in the backward. Both inverses are
# already in hand (argsort byproducts), so custom VJPs express every
# backward as another gather: zero scatters in fwd+bwd.


@jax.custom_vjp
def _permute_rows(x, perm, inv_perm):
    """x[perm] where ``inv_perm`` is perm's inverse permutation."""
    return jnp.take(x, perm, axis=0)


def _permute_fwd(x, perm, inv_perm):
    return jnp.take(x, perm, axis=0), (perm, inv_perm)


def _permute_bwd(res, g):
    perm, inv_perm = res
    return (
        jnp.take(g, inv_perm, axis=0),
        np.zeros(perm.shape, jax.dtypes.float0),
        np.zeros(inv_perm.shape, jax.dtypes.float0),
    )


_permute_rows.defvjp(_permute_fwd, _permute_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gather_dispatch(xf, order, inv_order, top_k):
    """xs[i] = xf[order[i] // top_k] (each token duplicated top_k
    times, sorted by expert). Backward: unsort to token-major and
    reduce the k copies densely — no scatter."""
    return jnp.take(xf, order // top_k, axis=0)


def _dispatch_fwd(xf, order, inv_order, top_k):
    return (
        jnp.take(xf, order // top_k, axis=0),
        (order, inv_order, xf.shape[0]),
    )


def _dispatch_bwd(top_k, res, g):
    order, inv_order, n = res
    d = g.shape[-1]
    gt = jnp.take(g, inv_order, axis=0).reshape(n, top_k, d)
    return (
        jnp.sum(gt.astype(jnp.float32), axis=1).astype(g.dtype),
        np.zeros(order.shape, jax.dtypes.float0),
        np.zeros(inv_order.shape, jax.dtypes.float0),
    )


_gather_dispatch.defvjp(_dispatch_fwd, _dispatch_bwd)


def _dispatch_impl() -> str:
    """"fused" (ops/moe_dispatch grouped kernel, the default) | "gmm"
    (megablox grouped matmuls around XLA gathers — the A/B baseline)
    for the dropless expert compute. DLROVER_TPU_MOE_DISPATCH picks;
    typos warn once and fall back to "fused"."""
    from dlrover_tpu.common.env_utils import resolve_env_choice

    return resolve_env_choice(
        "DLROVER_TPU_MOE_DISPATCH", ("fused", "gmm"), "fused"
    )


def _dropless_core(
    xf, router_w, w_gate, w_up, w_down, top_k, interpret, dispatch=None
):
    """Sorted grouped-matmul expert compute over flat tokens [n, d] ->
    out [n, d] f32. Local to one device (all experts resident).
    ``dispatch``: "fused" routes through the ops/moe_dispatch Pallas
    kernel (gather→GEMM→scatter in one pass, custom VJP on the same
    permutation); "gmm" keeps the megablox path with XLA gathers."""
    from jax.experimental.pallas.ops.tpu.megablox import gmm

    n, d = xf.shape
    e = router_w.shape[-1]
    m = n * top_k
    dispatch = dispatch or _dispatch_impl()

    router_logits = jnp.einsum(
        "nd,de->ne", xf.astype(jnp.float32),
        router_w.astype(jnp.float32),
    )
    probs = jax.nn.softmax(router_logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, top_k)        # [n, k]
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9
    )

    flat_expert = experts.reshape(m)

    if dispatch == "fused":
        from dlrover_tpu.ops import moe_dispatch as md

        cdt = xf.dtype
        tm = md.default_tile_m(m)
        row_ids, dest_ids, tile_expert = md.build_dispatch_layout(
            flat_expert, e, tm, top_k
        )
        w_gu = jnp.concatenate(
            [w_gate.astype(cdt), w_up.astype(cdt)], axis=-1
        )
        out_tok = md.grouped_ffn(
            xf, w_gu, w_down.astype(cdt), row_ids, dest_ids,
            tile_expert, m, top_k, tm, interpret,
        )
        return jnp.sum(
            out_tok.reshape(n, top_k, d).astype(jnp.float32)
            * gates[:, :, None],
            axis=1,
        )

    order = jnp.argsort(flat_expert, stable=True)       # [m]
    inv_order = jnp.argsort(order)
    xs = _gather_dispatch(xf, order, inv_order, top_k)  # [m, d] sorted
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    # gmm needs tile-divisible dims; pad the row dim with zero rows
    # assigned to the LAST group (sorted order keeps them contiguous at
    # the end) and slice them off before the combine.
    f = w_gate.shape[-1]
    m_pad = ((m + 127) // 128 * 128) if m >= 128 else m
    if m_pad != m:
        xs = jnp.pad(xs, ((0, m_pad - m), (0, 0)))
        group_sizes = group_sizes.at[e - 1].add(m_pad - m)
    cdt = xf.dtype
    # gate and up share lhs rows and group structure: ONE fused gmm over
    # the concatenated [e, d, 2f] weights reads the sorted tokens once
    # (half the lhs HBM traffic and kernel launches of separate calls).
    w_gu = jnp.concatenate(
        [w_gate.astype(cdt), w_up.astype(cdt)], axis=-1
    )
    hu = gmm(
        xs, w_gu, group_sizes, interpret=interpret,
        tiling=(_tile(m_pad), _tile(d), _tile(2 * f)),
    )
    a = (jax.nn.silu(hu[:, :f]) * hu[:, f:]).astype(cdt)
    out_sorted = gmm(
        a, w_down.astype(cdt), group_sizes, interpret=interpret,
        tiling=(_tile(m_pad), _tile(f), _tile(d)),
    )[:m]                                               # [m, d] f32

    # Combine WITHOUT a [n, d] scatter-add (slow on TPU): invert the
    # sort permutation (int sort + [m, d] gather), then the k copies of
    # each token sit contiguously — a dense reshape-sum finishes it.
    out_tok_major = _permute_rows(out_sorted, inv_order, order)
    return jnp.sum(
        out_tok_major.reshape(n, top_k, d)
        * gates.astype(out_sorted.dtype)[:, :, None],
        axis=1,
    )


def _global_router_metrics(x, router_w):
    logits = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32),
        router_w.astype(jnp.float32),
    )
    aux, z = _router_losses(logits, jax.nn.softmax(logits, axis=-1))
    return MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=jnp.zeros((), jnp.float32),
    )


def moe_mlp_dropless(
    x,
    router_w,     # [embed, experts]
    w_gate,       # [experts, embed, mlp]
    w_up,         # [experts, embed, mlp]
    w_down,       # [experts, mlp, embed]
    top_k: int = 2,
    interpret=None,
    dispatch=None,
):
    """x: [batch, seq, embed] -> (out, MoEMetrics). Zero dropped tokens.

    Token copies are stably sorted by their routed expert; the expert
    matmuls then run as grouped matmuls over the sorted rows (megablox
    gmm: contiguous per-expert row groups hit the MXU with no one-hot
    dispatch algebra and no capacity padding). Single-device math —
    multi-device meshes go through :func:`moe_mlp_dropless_sharded`
    (ep == 1) or :func:`moe_mlp_dropless_ep` (ep > 1)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, d = x.shape
    out = _dropless_core(
        x.reshape(b * s, d), router_w, w_gate, w_up, w_down,
        top_k, interpret, dispatch=dispatch,
    )
    out = with_logical_constraint(
        out.astype(x.dtype).reshape(b, s, d), ("batch", "seq", "embed")
    )
    return out, _global_router_metrics(x, router_w)


def moe_mlp_dropless_sharded(
    x,
    router_w,
    w_gate,
    w_up,
    w_down,
    mesh,
    top_k: int = 2,
    interpret=None,
    dispatch=None,
):
    """Dropless MoE on a multi-device mesh WITHOUT expert parallelism:
    every device holds all experts, so each shard routes and computes
    its local tokens independently — a ``shard_map`` island over the
    batch axes with replicated weights. (The global-argsort single-
    device path has data-dependent group sizes GSPMD cannot lower
    soundly; this per-shard form sidesteps that entirely.)"""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.parallel.sharding import logical_to_spec

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d = x.shape[-1]

    def body(xl, rw, wg, wu, wd):
        bl, sl, _ = xl.shape
        out = _dropless_core(
            xl.reshape(bl * sl, d), rw, wg, wu, wd, top_k, interpret,
            dispatch=dispatch,
        )
        return out.astype(xl.dtype).reshape(bl, sl, d)

    xspec = logical_to_spec(("batch", None, None))
    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(xspec, P(), P(), P(), P()),
        out_specs=xspec,
        check_rep=False,
    )(x, router_w, w_gate, w_up, w_down)
    out = with_logical_constraint(out, ("batch", "seq", "embed"))
    return out, _global_router_metrics(x, router_w)


# ---------------------------------------------------------------------------
# Dropless under expert parallelism: shard_map + ragged all-to-all
# ---------------------------------------------------------------------------


def _exchange(rows, sizes_mat, me, n_shards, axis_name, reverse=False):
    """One ragged all-to-all hop of ``rows`` ([cap, d], per-shard).

    ``sizes_mat[src, dst]`` — rows src ships to dst — is known on every
    shard, so each shard derives all four offset/size vectors locally:
    chunks live densely in SOURCE-major order on the sender and land in
    SOURCE-major order on the receiver. ``reverse=True`` runs the
    mirrored exchange (processed rows travel home).

    On TPU this is ``jax.lax.ragged_all_to_all`` (wire bytes sized by
    the actual routing). XLA:CPU does not implement that opcode, so the
    virtual-mesh test/dryrun path takes a semantically identical dense
    ``all_to_all`` of capacity-padded chunks instead."""
    if reverse:
        sizes_mat = sizes_mat.T
    send = sizes_mat[me]                                   # [n_shards]
    recv = sizes_mat[:, me]
    input_offsets = jnp.cumsum(send) - send
    # Where MY chunk starts on each receiver: after every earlier
    # source's chunk for that receiver.
    col_excl = jnp.cumsum(sizes_mat, axis=0) - sizes_mat   # [src, dst]
    output_offsets = col_excl[me]
    if jax.default_backend() == "tpu":
        return jax.lax.ragged_all_to_all(
            rows,
            jnp.zeros_like(rows),
            input_offsets.astype(jnp.int32),
            send.astype(jnp.int32),
            output_offsets.astype(jnp.int32),
            recv.astype(jnp.int32),
            axis_name=axis_name,
        )
    cap, d = rows.shape
    lane = jnp.arange(cap)
    # Pack: slot j carries my chunk for peer j (zero-padded).
    src_idx = jnp.clip(input_offsets[:, None] + lane[None, :], 0, cap - 1)
    valid = lane[None, :] < send[:, None]
    packed = jnp.where(
        valid[..., None], jnp.take(rows, src_idx, axis=0), 0
    )                                                      # [ep, cap, d]
    arrived = jax.lax.all_to_all(packed, axis_name, 0, 0)  # slot i: from i
    # Unpack into the contiguous source-major receive layout.
    pos = col_excl[:, me][:, None] + lane[None, :]         # [src, cap]
    pos = jnp.where(lane[None, :] < recv[:, None], pos, cap)
    return (
        jnp.zeros_like(rows)
        .at[pos.reshape(-1)]
        .set(arrived.reshape(-1, d), mode="drop")
    )


def moe_mlp_dropless_ep(
    x,
    router_w,
    w_gate,       # [experts, embed, mlp] — expert dim sharded over ep
    w_up,
    w_down,
    mesh,
    top_k: int = 2,
    axis_name: str = "ep",
    interpret=None,
    dispatch=None,
):
    """Dropless MoE that SURVIVES expert parallelism (the ep==1-only
    restriction of :func:`moe_mlp_dropless` lifted).

    Per ep shard, under ``shard_map``: route local tokens, sort the
    token copies by expert, ship each shard's copies to the shards
    owning their experts via ``jax.lax.ragged_all_to_all`` (buffers
    sized by the ACTUAL routing — no capacity bound, nothing dropped),
    run the fused grouped matmuls over the received rows, and ship the
    results back through the mirrored exchange. The all-to-all size
    matrix is replicated via an all_gather of per-shard counts, so all
    offset bookkeeping is local arithmetic.

    Worst-case receive buffer is ``top_k * n_global`` rows (all tokens
    routed to one shard) — the price of true droplessness; the gshard
    path bounds memory with capacity instead (and drops).
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, d = x.shape
    e = router_w.shape[-1]
    f = w_gate.shape[-1]
    ep = dict(mesh.shape).get(axis_name, 1)
    if e % ep:
        raise ValueError(f"{e} experts not divisible by ep={ep}")
    e_loc = e // ep
    cdt = x.dtype

    # Router losses from the (GSPMD-sharded) global logits — the tiny
    # [n, e] matmul is recomputed inside the shards for routing.
    logits_global = jnp.einsum(
        "bsd,de->bse", x.astype(jnp.float32),
        router_w.astype(jnp.float32),
    )
    aux, z = _router_losses(
        logits_global, jax.nn.softmax(logits_global, axis=-1)
    )

    from dlrover_tpu.parallel.sharding import logical_to_spec

    xspec = logical_to_spec(("batch", None, None))
    # Worst case for one ep shard: every copy in its ep row lands on it
    # (batch is sharded over e.g. dcn x dp x ep; the exchange stays
    # within one row of the non-ep batch shards, so other rows' tokens
    # can never arrive).
    batch_axes = xspec[0]
    axes = (
        (batch_axes,) if isinstance(batch_axes, str)
        else tuple(batch_axes or ())
    )
    other = 1
    for a in axes:
        if a != axis_name:
            other *= dict(mesh.shape).get(a, 1)
    cap_rows = (b // max(other, 1)) * s * top_k
    cap_rows = (cap_rows + 127) // 128 * 128

    def body(xl, rw, wg, wu, wd):
        from jax.experimental.pallas.ops.tpu.megablox import gmm

        me = jax.lax.axis_index(axis_name)
        bl, sl, _ = xl.shape
        n_loc = bl * sl
        m_loc = n_loc * top_k
        xf = xl.reshape(n_loc, d)

        logits = jnp.einsum(
            "nd,de->ne", xf.astype(jnp.float32), rw.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)
        gates, experts = jax.lax.top_k(probs, top_k)       # [n_loc, k]
        gates = gates / jnp.maximum(
            jnp.sum(gates, axis=-1, keepdims=True), 1e-9
        )

        flat_expert = experts.reshape(m_loc)
        order = jnp.argsort(flat_expert, stable=True)
        inv_order = jnp.argsort(order)
        xs = _gather_dispatch(xf, order, inv_order, top_k)  # [m_loc, d]
        counts = jnp.bincount(flat_expert, length=e)       # [e]

        # Replicate the full src x dst size matrix and per-(src, local
        # expert) counts: every shard then derives offsets locally.
        counts_all = jax.lax.all_gather(counts, axis_name)  # [ep, e]
        sizes_mat = counts_all.reshape(ep, ep, e_loc).sum(-1)

        xs_pad = jnp.zeros((cap_rows, d), cdt).at[:m_loc].set(
            xs.astype(cdt)
        )
        recv = _exchange(xs_pad, sizes_mat, me, ep, axis_name)

        # Received rows are (src, expert)-major; regroup expert-major
        # for gmm. Row expert ids reconstruct from the counts matrix
        # (data-dependent lengths -> repeat with a static total).
        my_counts = jax.lax.dynamic_slice_in_dim(
            counts_all, me * e_loc, e_loc, axis=1
        )                                                   # [src, e_loc]
        seg_experts = jnp.tile(jnp.arange(e_loc), ep)       # [src*e_loc]
        row_expert = jnp.repeat(
            seg_experts, my_counts.reshape(-1),
            total_repeat_length=cap_rows,
        )
        n_recv = my_counts.sum()
        # Padding rows past n_recv got arbitrary repeat values; force
        # them to the sentinel group (>= e_loc) so the fused layout
        # drops them / the gmm sort sends them to the end.
        row_expert = jnp.where(
            jnp.arange(cap_rows) < n_recv, row_expert, e_loc
        )
        w_gu = jnp.concatenate([wg.astype(cdt), wu.astype(cdt)], -1)

        if (dispatch or _dispatch_impl()) == "fused":
            # The SAME grouped kernel as the local core, driven by the
            # exchange layout: row_ids gather the (src, expert)-major
            # received rows per expert segment and dest_ids scatter
            # results straight back to that layout — the xs2/ys2
            # [cap_rows, d] permute round-trips disappear.
            from dlrover_tpu.ops import moe_dispatch as md

            tm = md.default_tile_m(cap_rows)
            row_ids, dest_ids, tile_expert = md.build_dispatch_layout(
                row_expert, e_loc, tm, 1
            )
            ys = md.grouped_ffn(
                recv, w_gu, wd.astype(cdt), row_ids, dest_ids,
                tile_expert, cap_rows, 1, tm, interpret,
            ).astype(cdt)
        else:
            order2 = jnp.argsort(row_expert, stable=True)
            inv2 = jnp.argsort(order2)
            xs2 = _permute_rows(recv, order2, inv2)
            group_sizes = jnp.bincount(
                row_expert, length=e_loc + 1
            ).astype(jnp.int32)
            # gmm groups must cover all rows: fold the pad tail (zero
            # rows, zero outputs regardless of expert) into the last
            # real group.
            group_sizes = (
                group_sizes[:e_loc]
                .at[e_loc - 1].add(group_sizes[e_loc])
            )
            hu = gmm(
                xs2, w_gu, group_sizes, interpret=interpret,
                tiling=(_tile(cap_rows), _tile(d), _tile(2 * f)),
            )
            a = (jax.nn.silu(hu[:, :f]) * hu[:, f:]).astype(cdt)
            ys2 = gmm(
                a, wd.astype(cdt), group_sizes, interpret=interpret,
                tiling=(_tile(cap_rows), _tile(f), _tile(d)),
            ).astype(cdt)
            # Unsort to (src, expert)-major and ship results home.
            ys = _permute_rows(ys2, inv2, order2)
        back = _exchange(ys, sizes_mat, me, ep, axis_name, reverse=True)

        # Home layout equals the original sorted xs rows; unsort and
        # combine the k copies per token with a dense reshape-sum.
        out_tok = _permute_rows(back[:m_loc], inv_order, order)
        out = jnp.sum(
            out_tok.reshape(n_loc, top_k, d).astype(jnp.float32)
            * gates[:, :, None],
            axis=1,
        )
        return out.astype(x.dtype).reshape(bl, sl, d)

    wspec = P(axis_name)
    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(xspec, P(), wspec, wspec, wspec),
        out_specs=xspec,
        check_rep=False,
    )(x, router_w, w_gate, w_up, w_down)
    out = with_logical_constraint(out, ("batch", "seq", "embed"))

    metrics = MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=jnp.zeros((), jnp.float32),
    )
    return out, metrics
