"""Mixture-of-Experts MLP: GShard einsum dispatch and a dropless
grouped-matmul path.

Two implementations behind one surface:

- **gshard** (:func:`moe_mlp`): one-hot einsum dispatch with per-expert
  capacity; over-capacity tokens are dropped (residual carries them).
  Expert-parallel by construction — the dispatch/combine einsums
  contract token axes (sharded over dp/ep) against expert axes (ep), so
  GSPMD lowers the resharding to all-to-all over ICI. Static shapes,
  works under any mesh.
- **dropless** (:func:`moe_mlp_dropless`): megablox-style — sort token
  copies by expert and run grouped (ragged) matmuls
  (``jax.experimental.pallas.ops.tpu.megablox.gmm``), so NO token is
  ever dropped and no capacity/one-hot FLOPs are wasted. Group sizes
  are data-dependent, which GSPMD cannot shard over ``ep`` — this path
  is for meshes with ep == 1 (each device holds all experts; dp/tp as
  usual). ``models/llama.mlp_block`` picks it automatically there.

The reference has no MoE/EP support (SURVEY.md section 2.9: "absent") —
this is parity-plus for the TPU build.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from dlrover_tpu.parallel.sharding import with_logical_constraint


class MoEMetrics(NamedTuple):
    aux_loss: jnp.ndarray     # load-balance loss (scalar)
    router_z_loss: jnp.ndarray
    dropped_fraction: jnp.ndarray


def expert_capacity(
    seq: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    cap = int(seq * top_k * capacity_factor / n_experts)
    return max(cap, 1)


def moe_mlp(
    x,
    router_w,     # [embed, experts]
    w_gate,       # [experts, embed, mlp]
    w_up,         # [experts, embed, mlp]
    w_down,       # [experts, mlp, embed]
    top_k: int = 2,
    capacity_factor: float = 1.25,
):
    """x: [batch, seq, embed] -> (out, MoEMetrics).

    Groups = batch rows (tokens within one sequence compete for expert
    capacity). Over-capacity tokens are dropped (residual carries them).
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    cap = expert_capacity(s, e, top_k, capacity_factor)

    router_logits = jnp.einsum(
        "gsd,de->gse", x.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)

    # --- iterative top-k one-hot assignment with capacity ---------------
    combine = jnp.zeros((b, s, e, cap), dtype=jnp.float32)
    remaining = probs
    # position counters per expert, advanced between the k rounds
    used = jnp.zeros((b, e), dtype=jnp.int32)
    dropped = 0.0
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                   # [g, s]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [g, s, e]
        gate = jnp.sum(remaining * onehot, axis=-1)            # [g, s]
        remaining = remaining * (1.0 - onehot)
        # capacity slot for each token in its chosen expert
        pos_in_expert = (
            jnp.cumsum(onehot, axis=1) - onehot
        ) + used[:, None, :]                                   # [g, s, e]
        pos = jnp.einsum("gse,gse->gs", pos_in_expert, onehot).astype(
            jnp.int32
        )
        fits = pos < cap
        dropped = dropped + jnp.mean(1.0 - fits)
        gate = gate * fits
        pos_onehot = jax.nn.one_hot(
            jnp.where(fits, pos, cap), cap, dtype=jnp.float32
        )  # out-of-range -> all-zero row
        combine = combine + (
            gate[..., None, None] * onehot[..., None] * pos_onehot[:, :, None, :]
        )
        used = used + jnp.sum(onehot * fits[..., None], axis=1).astype(jnp.int32)

    # renormalize the kept gates so they sum to 1 per token (when any kept)
    denom = jnp.sum(combine, axis=(-2, -1), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0.0).astype(x.dtype)
    combine = combine.astype(x.dtype)

    # --- dispatch -> expert compute -> combine --------------------------
    # [e, g, cap, d]: token shards (dp/ep) contract into expert shards (ep)
    expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, x)
    expert_in = with_logical_constraint(
        expert_in, ("expert", "batch", "capacity", "embed")
    )
    h = jnp.einsum("egcd,edf->egcf", expert_in, w_gate.astype(x.dtype))
    u = jnp.einsum("egcd,edf->egcf", expert_in, w_up.astype(x.dtype))
    h = jax.nn.silu(h) * u
    h = with_logical_constraint(h, ("expert", "batch", "capacity", "mlp"))
    expert_out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(x.dtype))
    # Without this constraint GSPMD infers an (e, d)-sharded layout from
    # w_down and then can't reshard the backward cotangent (which
    # arrives batch-sharded from dout) efficiently — involuntary full
    # rematerialization on the ep mesh.
    expert_out = with_logical_constraint(
        expert_out, ("expert", "batch", "capacity", "embed")
    )
    out = jnp.einsum("egcd,gsec->gsd", expert_out, combine)
    out = with_logical_constraint(out, ("batch", "seq", "embed"))

    # --- router losses (shared with the dropless path) -------------------
    aux, z = _router_losses(router_logits, probs)
    metrics = MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=dropped / top_k,
    )
    return out, metrics


# ---------------------------------------------------------------------------
# Dropless path: sort-by-expert + grouped matmul (megablox gmm)
# ---------------------------------------------------------------------------


def _router_losses(router_logits, probs):
    e = probs.shape[-1]
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=tuple(range(top1.ndim - 1)))
    mean_probs = jnp.mean(probs, axis=tuple(range(probs.ndim - 1)))
    aux = e * jnp.sum(frac_tokens * mean_probs)
    z = jnp.mean(
        jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2
    )
    return aux, z


def _tile(dim: int, cap: int = 512) -> int:
    """Largest power-of-two divisor of ``dim``, capped — gmm requires
    every dimension to be tile-divisible."""
    t = 1
    while t * 2 <= min(dim, cap) and dim % (t * 2) == 0:
        t *= 2
    return t


def moe_mlp_dropless(
    x,
    router_w,     # [embed, experts]
    w_gate,       # [experts, embed, mlp]
    w_up,         # [experts, embed, mlp]
    w_down,       # [experts, mlp, embed]
    top_k: int = 2,
    interpret=None,
):
    """x: [batch, seq, embed] -> (out, MoEMetrics). Zero dropped tokens.

    Token copies are stably sorted by their routed expert; the three
    expert matmuls then run as ONE grouped matmul each over the sorted
    rows (megablox gmm: contiguous per-expert row groups hit the MXU
    with no one-hot dispatch algebra and no capacity padding). The
    scatter back is a segment-sum over the k copies of each token.
    """
    from jax.experimental.pallas.ops.tpu.megablox import gmm

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, d = x.shape
    e = router_w.shape[-1]
    n = b * s
    m = n * top_k
    xf = x.reshape(n, d)

    router_logits = jnp.einsum(
        "nd,de->ne", xf.astype(jnp.float32),
        router_w.astype(jnp.float32),
    )
    probs = jax.nn.softmax(router_logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, top_k)        # [n, k]
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9
    )

    flat_expert = experts.reshape(m)
    order = jnp.argsort(flat_expert, stable=True)       # [m]
    token_of = order // top_k
    xs = jnp.take(xf, token_of, axis=0)                 # [m, d] sorted
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    # gmm needs tile-divisible dims; pad the row dim with zero rows
    # assigned to the LAST group (sorted order keeps them contiguous at
    # the end) and slice them off before the scatter.
    f = w_gate.shape[-1]
    m_pad = ((m + 127) // 128 * 128) if m >= 128 else m
    if m_pad != m:
        xs = jnp.pad(xs, ((0, m_pad - m), (0, 0)))
        group_sizes = group_sizes.at[e - 1].add(m_pad - m)
    tiling = (_tile(m_pad), _tile(d), _tile(f))
    run = functools.partial(gmm, interpret=interpret, tiling=tiling)
    cdt = x.dtype
    h = run(xs, w_gate.astype(cdt), group_sizes)
    u = run(xs, w_up.astype(cdt), group_sizes)
    a = (jax.nn.silu(h) * u).astype(cdt)
    out_sorted = run(
        a, w_down.astype(cdt), group_sizes,
        tiling=(_tile(m_pad), _tile(f), _tile(d)),
    )[:m]                                               # [m, d] f32

    gate_sorted = gates.reshape(m)[order].astype(out_sorted.dtype)
    out = jnp.zeros((n, d), out_sorted.dtype).at[token_of].add(
        out_sorted * gate_sorted[:, None]
    )
    out = with_logical_constraint(
        out.astype(x.dtype).reshape(b, s, d), ("batch", "seq", "embed")
    )

    aux, z = _router_losses(router_logits, probs)
    metrics = MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=jnp.zeros((), jnp.float32),
    )
    return out, metrics
