"""Mixture-of-Experts MLP with GShard-style einsum dispatch.

Expert-parallel by construction: expert-stacked weights carry a leading
("expert",) logical axis mapped to the ``ep`` mesh axis, and the dispatch/
combine einsums contract token axes (sharded over dp/ep) against expert
axes (sharded over ep) — XLA lowers the resharding to all-to-all over ICI.
No per-token Python control flow: top-k and capacity assignment are
one-hot einsum algebra, so everything stays on the MXU with static shapes.

The reference has no MoE/EP support (SURVEY.md section 2.9: "absent") —
this is parity-plus for the TPU build.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dlrover_tpu.parallel.sharding import with_logical_constraint


class MoEMetrics(NamedTuple):
    aux_loss: jnp.ndarray     # load-balance loss (scalar)
    router_z_loss: jnp.ndarray
    dropped_fraction: jnp.ndarray


def expert_capacity(
    seq: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    cap = int(seq * top_k * capacity_factor / n_experts)
    return max(cap, 1)


def moe_mlp(
    x,
    router_w,     # [embed, experts]
    w_gate,       # [experts, embed, mlp]
    w_up,         # [experts, embed, mlp]
    w_down,       # [experts, mlp, embed]
    top_k: int = 2,
    capacity_factor: float = 1.25,
):
    """x: [batch, seq, embed] -> (out, MoEMetrics).

    Groups = batch rows (tokens within one sequence compete for expert
    capacity). Over-capacity tokens are dropped (residual carries them).
    """
    b, s, d = x.shape
    e = router_w.shape[-1]
    cap = expert_capacity(s, e, top_k, capacity_factor)

    router_logits = jnp.einsum(
        "gsd,de->gse", x.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)

    # --- iterative top-k one-hot assignment with capacity ---------------
    combine = jnp.zeros((b, s, e, cap), dtype=jnp.float32)
    remaining = probs
    # position counters per expert, advanced between the k rounds
    used = jnp.zeros((b, e), dtype=jnp.int32)
    dropped = 0.0
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                   # [g, s]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [g, s, e]
        gate = jnp.sum(remaining * onehot, axis=-1)            # [g, s]
        remaining = remaining * (1.0 - onehot)
        # capacity slot for each token in its chosen expert
        pos_in_expert = (
            jnp.cumsum(onehot, axis=1) - onehot
        ) + used[:, None, :]                                   # [g, s, e]
        pos = jnp.einsum("gse,gse->gs", pos_in_expert, onehot).astype(
            jnp.int32
        )
        fits = pos < cap
        dropped = dropped + jnp.mean(1.0 - fits)
        gate = gate * fits
        pos_onehot = jax.nn.one_hot(
            jnp.where(fits, pos, cap), cap, dtype=jnp.float32
        )  # out-of-range -> all-zero row
        combine = combine + (
            gate[..., None, None] * onehot[..., None] * pos_onehot[:, :, None, :]
        )
        used = used + jnp.sum(onehot * fits[..., None], axis=1).astype(jnp.int32)

    # renormalize the kept gates so they sum to 1 per token (when any kept)
    denom = jnp.sum(combine, axis=(-2, -1), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    dispatch = (combine > 0.0).astype(x.dtype)
    combine = combine.astype(x.dtype)

    # --- dispatch -> expert compute -> combine --------------------------
    # [e, g, cap, d]: token shards (dp/ep) contract into expert shards (ep)
    expert_in = jnp.einsum("gsec,gsd->egcd", dispatch, x)
    expert_in = with_logical_constraint(
        expert_in, ("expert", "batch", "capacity", "embed")
    )
    h = jnp.einsum("egcd,edf->egcf", expert_in, w_gate.astype(x.dtype))
    u = jnp.einsum("egcd,edf->egcf", expert_in, w_up.astype(x.dtype))
    h = jax.nn.silu(h) * u
    h = with_logical_constraint(h, ("expert", "batch", "capacity", "mlp"))
    expert_out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(x.dtype))
    # Without this constraint GSPMD infers an (e, d)-sharded layout from
    # w_down and then can't reshard the backward cotangent (which
    # arrives batch-sharded from dout) efficiently — involuntary full
    # rematerialization on the ep mesh.
    expert_out = with_logical_constraint(
        expert_out, ("expert", "batch", "capacity", "embed")
    )
    out = jnp.einsum("egcd,gsec->gsd", expert_out, combine)
    out = with_logical_constraint(out, ("batch", "seq", "embed"))

    # --- router losses ---------------------------------------------------
    # load-balance (Switch): E * sum_e fraction_tokens_e * mean_prob_e
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=(0, 1))
    mean_probs = jnp.mean(probs, axis=(0, 1))
    aux = e * jnp.sum(frac_tokens * mean_probs)
    z = jnp.mean(jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2)
    metrics = MoEMetrics(
        aux_loss=aux,
        router_z_loss=z,
        dropped_fraction=dropped / top_k,
    )
    return out, metrics
