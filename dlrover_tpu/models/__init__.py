"""Model zoo: functional JAX models with logical-axis sharding metadata.

Every model exposes ``init_params(config, rng) -> (params, logical_axes)``
and ``forward(config, params, tokens, ...) -> (logits, aux)`` as pure
functions — no framework Module state, so checkpointing, resharding, and
pipelining operate on plain pytrees.
"""

from dlrover_tpu.models.llama import (  # noqa: F401
    TpuLMConfig,
    init_params,
    forward,
    loss_fn,
)
