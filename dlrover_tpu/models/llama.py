"""TpuLM — the flagship decoder-only transformer (Llama-family shape:
RMSNorm + RoPE + GQA + SwiGLU; optionally MoE every layer).

Pure-functional: ``init_params`` returns (params, logical_axes) twin
pytrees; ``forward`` is jit/pjit-safe with static shapes and scan-over-
layers. Parallelism is declared, not coded: logical axes map to the
(dp, ep, pp, sp, tp) mesh via parallel/sharding.py rules, giving FSDP
(embed over dp), tensor parallel (heads/mlp/vocab over tp), pipeline
(stage over pp via trainer/pipeline.py), sequence parallel (ring
attention over sp), and expert parallel (expert over ep) from one model
definition.

The reference delegates all of this to torch frameworks (SURVEY.md
section 2.9); here the model layer is first-class so the elastic/ckpt
machinery has a real workload to supervise.
"""

import dataclasses
import functools
import math
import os
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from dlrover_tpu.models import moe as moe_lib
from dlrover_tpu.ops.attention import dot_product_attention
from dlrover_tpu.ops.norms import rms_norm
from dlrover_tpu.ops.rope import apply_rope
from dlrover_tpu.parallel.sharding import with_logical_constraint


@dataclasses.dataclass(frozen=True)
class TpuLMConfig:
    vocab_size: int = 32000
    embed_dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 11008
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"          # compute dtype (params stay f32)
    # MoE (n_experts > 0 makes every layer's MLP an expert layer)
    n_experts: int = 0
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # "gshard": one-hot dispatch with capacity drop (works under ep
    # meshes); "dropless": megablox grouped matmul, zero drops (ep == 1
    # only); "auto": dropless when the mesh has no ep axis.
    moe_impl: str = "auto"
    # pipeline: layer stack is stored [stages, layers_per_stage, ...]
    pp_stages: int = 1
    num_microbatches: int = 1
    remat: bool = True
    # "mlp_only": the attention half of each layer is NOT rematerialized
    #   (its Pallas flash kernel would otherwise re-run in the backward —
    #   a measured ~1ms/layer/step on v5e) while the MLP half keeps the
    #   "dots" policy. Costs ~+130MB/layer of saved attention residuals.
    # "attn_save": the long-context middle ground — the attention call
    #   still escapes remat (at 32k tokens re-running flash attention is
    #   the dominant remat cost) but BOTH flanks recompute fully, so the
    #   saved state stays O(s*d)/layer where "mlp_only"'s dots flanks
    #   would pin the [s, mlp_dim] hiddens (the 32k OOM).
    # "dots": selective rematerialization — matmul outputs are saved,
    #   only elementwise work recomputes in the backward (measured +2 MFU
    #   points over full remat on v5e at the bench config).
    # "full": recompute everything (lowest memory; the hyperparam
    #   strategy escalates to this on OOM evidence).
    remat_policy: str = "mlp_only"

    def __post_init__(self):
        if self.remat_policy not in (
            "mlp_only", "attn_save", "dots", "full"
        ):
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in ('mlp_only', "
                f"'attn_save', 'dots', 'full') — a typo here silently "
                f"costs MFU"
            )
        if self.moe_impl not in ("auto", "gshard", "dropless"):
            raise ValueError(
                f"moe_impl {self.moe_impl!r} not in ('auto', 'gshard', "
                f"'dropless')"
            )

    @property
    def layers_per_stage(self) -> int:
        if self.n_layers % self.pp_stages:
            raise ValueError(
                f"n_layers {self.n_layers} % pp_stages {self.pp_stages} != 0"
            )
        return self.n_layers // self.pp_stages

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    def flops_per_token(self) -> float:
        """Approximate training FLOPs per token (fwd+bwd ~= 6 * params)."""
        return 6.0 * self.count_params()

    def attention_flops_per_token(self, seq: int, causal: bool = True):
        """Training attention-matmul FLOPs per token at sequence ``seq``:
        3 (fwd + bwd) x 2 matmuls (QK^T, AV) x 2 FLOPs/MAC x seq x
        n_heads x head_dim per layer, halved for causal masking. Excluded
        from the 6N model-FLOPs basis; at long context they dominate, so
        honest MFU there is (6N + attention) — the basis the longctx
        bench reports."""
        f = 12.0 * self.n_layers * self.n_heads * self.head_dim * seq
        return f / 2 if causal else f

    def count_params(self) -> int:
        d, hd = self.embed_dim, self.head_dim
        attn = d * (self.n_heads + 2 * self.n_kv_heads) * hd
        attn += self.n_heads * hd * d
        if self.n_experts > 0:
            mlp = 3 * d * self.mlp_dim * self.n_experts + d * self.n_experts
        else:
            mlp = 3 * d * self.mlp_dim
        per_layer = attn + mlp + 2 * d
        return (
            self.n_layers * per_layer
            + 2 * self.vocab_size * d
            + d
        )

    def count_active_params(self) -> int:
        """Params a single token actually touches — for MoE, top_k
        experts instead of all of them (the honest 6N basis for MoE
        MFU; equals count_params() for dense configs)."""
        if self.n_experts == 0:
            return self.count_params()
        d = self.embed_dim
        dense_mlp = 3 * d * self.mlp_dim
        all_mlp = dense_mlp * self.n_experts
        active_mlp = dense_mlp * self.moe_top_k
        return self.count_params() - self.n_layers * (
            all_mlp - active_mlp
        )


def tiny_config(**overrides) -> TpuLMConfig:
    """A config small enough for CPU tests yet exercising every axis."""
    defaults = dict(
        vocab_size=256,
        embed_dim=64,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        mlp_dim=128,
        dtype="float32",
    )
    defaults.update(overrides)
    return TpuLMConfig(**defaults)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _layer_leading(config) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    """Leading dims/axes of stacked layer params."""
    if config.pp_stages > 1:
        return (
            (config.pp_stages, config.layers_per_stage),
            ("stage", "layer"),
        )
    return ((config.n_layers,), ("layer",))


def param_axes(config: TpuLMConfig) -> Dict[str, Any]:
    """Logical-axis names per param leaf (static; no tracing needed)."""
    lead_ax = _layer_leading(config)[1]
    layer_axes = {
        "attn_norm": lead_ax + ("norm",),
        "wq": lead_ax + ("embed", "heads", "head_dim"),
        "wk": lead_ax + ("embed", "kv_heads", "head_dim"),
        "wv": lead_ax + ("embed", "kv_heads", "head_dim"),
        "wo": lead_ax + ("heads", "head_dim", "embed"),
        "mlp_norm": lead_ax + ("norm",),
    }
    if config.n_experts > 0:
        layer_axes.update(
            router=lead_ax + ("embed", "expert"),
            w_gate=lead_ax + ("expert", "embed", "mlp"),
            w_up=lead_ax + ("expert", "embed", "mlp"),
            w_down=lead_ax + ("expert", "mlp", "embed"),
        )
    else:
        layer_axes.update(
            w_gate=lead_ax + ("embed", "mlp"),
            w_up=lead_ax + ("embed", "mlp"),
            w_down=lead_ax + ("mlp", "embed"),
        )
    return {
        "embed": ("vocab", "embed"),
        "layers": layer_axes,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(
    config: TpuLMConfig, rng: jax.Array
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Returns (params, logical_axes): twin pytrees.

    Simple init: normal(0, 1/sqrt(fan_in)); norm scales zero (the
    (1+scale) parameterization makes zero the identity).
    """
    d, hd = config.embed_dim, config.head_dim
    h, kv = config.n_heads, config.n_kv_heads
    f, v = config.mlp_dim, config.vocab_size
    lead, _ = _layer_leading(config)

    keys = jax.random.split(rng, 16)

    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, dtype=jnp.float32)
            / math.sqrt(fan_in)
        )

    layers = {
        "attn_norm": jnp.zeros(lead + (d,), jnp.float32),
        "wq": dense(keys[0], lead + (d, h, hd), d),
        "wk": dense(keys[1], lead + (d, kv, hd), d),
        "wv": dense(keys[2], lead + (d, kv, hd), d),
        "wo": dense(keys[3], lead + (h, hd, d), h * hd),
        "mlp_norm": jnp.zeros(lead + (d,), jnp.float32),
    }
    if config.n_experts > 0:
        e = config.n_experts
        layers.update(
            router=dense(keys[4], lead + (d, e), d),
            w_gate=dense(keys[5], lead + (e, d, f), d),
            w_up=dense(keys[6], lead + (e, d, f), d),
            w_down=dense(keys[7], lead + (e, f, d), f),
        )
    else:
        layers.update(
            w_gate=dense(keys[5], lead + (d, f), d),
            w_up=dense(keys[6], lead + (d, f), d),
            w_down=dense(keys[7], lead + (f, d), f),
        )

    params = {
        "embed": dense(keys[8], (v, d), 1.0),  # ~N(0,1) embedding
        "layers": layers,
        "final_norm": jnp.zeros((d,), jnp.float32),
        "lm_head": dense(keys[9], (d, v), d),
    }
    return params, param_axes(config)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


_ATTN_CACHE: Dict[str, Any] = {}


def default_attention_fn():
    """Best attention impl for contiguous-position causal attention on the
    current backend: the Pallas flash kernel (ops/pallas_attention.py) on
    TPU, the XLA reference op elsewhere (``None`` → transformer_layer's
    ``dot_product_attention`` fallback).

    Override with ``DLROVER_TPU_ATTN=xla|pallas`` (``pallas`` off-TPU runs
    the kernel in interpret mode — for tests/debugging only).
    """
    choice = os.environ.get("DLROVER_TPU_ATTN", "auto").lower()
    if choice not in _ATTN_CACHE:
        use_pallas = choice == "pallas" or (
            choice == "auto" and jax.default_backend() == "tpu"
        )
        if use_pallas:
            from dlrover_tpu.ops.pallas_attention import make_flash_attention

            _ATTN_CACHE[choice] = make_flash_attention()
        else:
            _ATTN_CACHE[choice] = None
    return _ATTN_CACHE[choice]


def attention_qkv(config: TpuLMConfig, p, x, positions):
    """Pre-attention block: norm + QKV projections + RoPE.

    Shared by the training layer and the KV-cache decode path
    (models/generate.py) so the two can never drift."""
    cdt = config.compute_dtype
    hx = rms_norm(x, p["attn_norm"]).astype(cdt)
    q = jnp.einsum("bsd,dhk->bshk", hx, p["wq"].astype(cdt))
    k = jnp.einsum("bsd,dhk->bshk", hx, p["wk"].astype(cdt))
    v = jnp.einsum("bsd,dhk->bshk", hx, p["wv"].astype(cdt))
    q = with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"))
    k = with_logical_constraint(k, ("batch", "seq", "kv_heads", "head_dim"))
    q = apply_rope(q, positions, config.rope_theta)
    k = apply_rope(k, positions, config.rope_theta)
    return q, k, v


def attention_out(config: TpuLMConfig, p, attn, residual):
    """Post-attention projection + residual add (shared with decode)."""
    cdt = config.compute_dtype
    out = jnp.einsum("bshk,hkd->bsd", attn, p["wo"].astype(cdt))
    x = residual + out.astype(residual.dtype)
    return with_logical_constraint(x, ("batch", "seq", "embed"))


def _moe_resolve_impl(config) -> str:
    """Which MoE path runs: "gshard" | "dropless" | "dropless_sharded"
    | "dropless_ep".

    Explicit ``moe_impl="dropless"`` maps to the mesh-appropriate
    dropless variant: the single-device core, the shard_map-per-shard
    form on multi-device meshes without ep (the global-argsort core has
    data-dependent group sizes GSPMD cannot lower soundly — it must
    never see a sharded batch directly), or the ragged-all-to-all ep
    form. "auto" follows the measured crossover (bench.py
    moe_crossover_sweep, v5e): gshard wins at the default capacity
    factor (1.25: e.g. 9.3 vs 12.9 ms/layer at 8 experts), dropless
    wins once the capacity budget reaches ~2.0 — and at that point it
    is also drop-free, so auto picks it there. Multi-device auto stays
    on the GSPMD-proven gshard path."""
    from dlrover_tpu.parallel.sharding import current_mesh

    mesh = current_mesh()
    multi = mesh is not None and mesh.size > 1
    has_ep = mesh is not None and dict(mesh.shape).get("ep", 1) > 1
    if config.moe_impl == "gshard":
        return "gshard"
    if config.moe_impl == "dropless":
        if has_ep:
            return "dropless_ep"
        return "dropless_sharded" if multi else "dropless"
    if not multi and config.capacity_factor >= 2.0:
        return "dropless"
    return "gshard"


def mlp_block(config: TpuLMConfig, p, x):
    """Residual MLP (dense or MoE). Returns (x, aux). Shared with the
    decode path."""
    with jax.named_scope("mlp"):
        return _mlp_block_inner(config, p, x)


def _mlp_block_inner(config: TpuLMConfig, p, x):
    cdt = config.compute_dtype
    residual = x
    hx = rms_norm(x, p["mlp_norm"]).astype(cdt)
    if config.n_experts > 0:
        impl = _moe_resolve_impl(config)
        experts = (p["router"], p["w_gate"], p["w_up"], p["w_down"])
        if impl == "dropless":
            out, metrics = moe_lib.moe_mlp_dropless(
                hx, *experts, top_k=config.moe_top_k
            )
        elif impl in ("dropless_sharded", "dropless_ep"):
            from dlrover_tpu.parallel.sharding import current_mesh

            fn = (
                moe_lib.moe_mlp_dropless_ep
                if impl == "dropless_ep"
                else moe_lib.moe_mlp_dropless_sharded
            )
            out, metrics = fn(
                hx, *experts, mesh=current_mesh(),
                top_k=config.moe_top_k,
            )
        else:
            out, metrics = moe_lib.moe_mlp(
                hx,
                *experts,
                top_k=config.moe_top_k,
                capacity_factor=config.capacity_factor,
            )
        aux = metrics.aux_loss + 0.001 * metrics.router_z_loss
    else:
        g = jnp.einsum("bsd,df->bsf", hx, p["w_gate"].astype(cdt))
        u = jnp.einsum("bsd,df->bsf", hx, p["w_up"].astype(cdt))
        g = with_logical_constraint(g, ("batch", "seq", "mlp"))
        out = jnp.einsum(
            "bsf,fd->bsd", jax.nn.silu(g) * u, p["w_down"].astype(cdt)
        )
        aux = jnp.zeros((), jnp.float32)
    x = residual + out.astype(x.dtype)
    x = with_logical_constraint(x, ("batch", "seq", "embed"))
    return x, aux


def transformer_layer(
    config: TpuLMConfig,
    layer_params: Dict[str, jnp.ndarray],
    x,
    positions,
    attention_fn=None,
):
    """One decoder block. x: [b, s, d]; positions: [b, s] global indices.

    Returns (x, moe_aux_losses or None).
    """
    p = layer_params
    attn_fn = attention_fn or dot_product_attention

    residual = x
    # named_scope: the scope lands in every op's trace metadata (tf_op),
    # forward AND backward — the basis of the bench's mfu_breakdown
    # (tpu_timer/xla_capture.bucket_by_scope).
    with jax.named_scope("attn"):
        q, k, v = attention_qkv(config, p, x, positions)
        attn = attn_fn(q, k, v, causal=True,
                       q_positions=positions, kv_positions=positions)
        x = attention_out(config, p, attn, residual)
    return mlp_block(config, p, x)


def embed_tokens(config, params, tokens):
    # Release the table's FSDP (embed-over-dp) sharding BEFORE the
    # gather: the [vocab, d] all-gather is cheap, while letting GSPMD
    # reshard the [b, s, d] gather output (which inherits the table's
    # embed sharding) triggers involuntary full rematerialization on
    # meshes where batch/seq/embed axes all move (observed on sp).
    table = with_logical_constraint(params["embed"], ("vocab", None))
    x = jnp.take(table, tokens, axis=0).astype(config.compute_dtype)
    return with_logical_constraint(x, ("batch", "seq", "embed"))


def final_hidden(config, params, x):
    """Final-norm + compute-dtype cast — the single head path shared by
    ``unembed`` and the fused-CE loss so they can never diverge."""
    return rms_norm(x, params["final_norm"]).astype(config.compute_dtype)


def unembed(config, params, x):
    with jax.named_scope("vocab"):
        x = final_hidden(config, params, x)
        # bf16 einsum + separate f32 cast measures ~2ms/step better
        # than a preferred_element_type=f32 matmul here: XLA fuses the
        # convert into the loss consumers, so the bf16 intermediate
        # halves the HBM write.
        logits = jnp.einsum(
            "bsd,dv->bsv",
            x, params["lm_head"].astype(config.compute_dtype),
        )
        return with_logical_constraint(
            logits.astype(jnp.float32), ("batch", "seq", "vocab")
        )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _attn_block_lite(config, p, x, positions):
    """Norm + qkv projection + rope + flash attention as ONE
    differentiable unit whose backward residuals are (p, x, out,
    compact lse) — NOT (q, k, v, out, lse).

    This is what lets the ``attn_save`` remat policy fit at 64k
    tokens: the plain escape pins q/k/v/out per layer (512MB/layer at
    64k, 8GB across 16 layers — a compile-time HBM OOM on 16GB v5e),
    while this block re-derives q/k/v from the saved layer input in
    the backward (cheap projections, the same recompute the flanks
    already pay) and still never re-runs the flash FORWARD (out/lse
    are saved — re-running it is what makes plain "full" remat slow
    at long context). ~258MB/layer saved at 64k."""
    from dlrover_tpu.ops.pallas_attention import flash_attention

    q, k, v = attention_qkv(config, p, x, positions)
    return flash_attention(q, k, v, True)


def _attn_block_lite_fwd(config, p, x, positions):
    from dlrover_tpu.ops.pallas_attention import _flash_forward

    q, k, v = attention_qkv(config, p, x, positions)
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, True, None, interpret)
    # lse compact [b*h, sq]: the lane-broadcast layout would pin 128x
    # the bytes (same trade as pallas_attention._fwd).
    return out, (p, x, positions, out, lse[:, :, 0])


def _attn_block_lite_bwd(config, res, g):
    import numpy as np

    from dlrover_tpu.ops.pallas_attention import LANES, _flash_backward

    p, x, positions, out, lse2d = res
    (q, k, v), qkv_vjp = jax.vjp(
        lambda p_, x_: attention_qkv(config, p_, x_, positions), p, x
    )
    if os.environ.get(
        "DLROVER_TPU_FLASH_BWD", "pallas"
    ).lower() == "xla":
        # Same debug fallback as pallas_attention._bwd: rebuild the
        # attention grads through the XLA reference op so the knob
        # keeps working under the lite path too.
        _, attn_vjp = jax.vjp(
            lambda q_, k_, v_: dot_product_attention(
                q_, k_, v_, causal=True
            ),
            q, k, v,
        )
        dq, dk, dv = attn_vjp(g)
    else:
        lse = jnp.broadcast_to(
            lse2d[:, :, None], lse2d.shape + (LANES,)
        )
        interpret = jax.default_backend() != "tpu"
        dq, dk, dv = _flash_backward(
            q, k, v, out, lse, g, True, None, interpret
        )
    dp, dx = qkv_vjp((dq, dk, dv))
    dpos = np.zeros(positions.shape, jax.dtypes.float0)
    return dp, dx, dpos


_attn_block_lite.defvjp(_attn_block_lite_fwd, _attn_block_lite_bwd)


def run_layer_stack(
    config: TpuLMConfig,
    layer_params,
    x,
    positions,
    attention_fn=None,
):
    """scan over a [L, ...] stacked layer pytree (single pipeline stage)."""

    # Cast the stacked MATMUL params to the compute dtype ONCE, outside
    # the scan: the scan's per-layer dynamic-slice then moves half the
    # bytes (f32 master params slice+convert measured ~0.8ms/layer/step
    # on v5e, in both the forward and the backward's recompute).
    # Gradients still reach the optimizer in f32 — the convert's
    # transpose upcasts the bf16 layer cotangents automatically. Norm
    # scales and the MoE router stay f32: rms_norm and moe_mlp
    # deliberately compute those in f32, and rounding the master values
    # here would silently flip near-boundary top-k routing decisions.
    cdt = config.compute_dtype
    if cdt != jnp.float32:
        keep_f32 = {"attn_norm", "mlp_norm", "router"}
        layer_params = {
            k: (v if k in keep_f32 else v.astype(cdt))
            for k, v in layer_params.items()
        }

    dots_policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable

    # "mlp_only" exempts the attention call from remat on the premise
    # that its saved residuals are O(s*d) — true for the flash kernel
    # (custom VJP: q/k/v/out + compact lse) but NOT for plain XLA
    # attention or other impls, whose backward would pin O(s^2) softmax
    # intermediates per layer. Impls that keep O(s*d) residuals declare
    # it via a ``saveable_residuals`` attribute; everything else demotes
    # to the "dots" policy.
    attn_escapes = (
        config.remat
        and config.remat_policy in ("mlp_only", "attn_save")
        and getattr(attention_fn, "saveable_residuals", False)
    )
    if attn_escapes:
        # Only the flash-attention call itself escapes rematerialization
        # (re-running its Pallas forward in the backward costs a measured
        # ~1ms/layer/step at 2k and dominates the remat bill at 32k).
        # "mlp_only": flanks keep the dots policy — the extra saved
        # state is just (q_roped, k_roped, v, attn_out) plus the compact
        # lse; the pre-rope projections DCE away because rope's backward
        # only needs the (recomputed) sin/cos. "attn_save": flanks
        # recompute fully — the long-context memory budget.
        flank_policy = (
            dots_policy if config.remat_policy == "mlp_only" else None
        )

        def out_mlp(p, attn, residual):
            with jax.named_scope("attn"):
                y = attention_out(config, p, attn, residual)
            return mlp_block(config, p, y)

        ckpt_out_mlp = jax.checkpoint(out_mlp, policy=flank_policy)

        if config.remat_policy == "attn_save" and getattr(
            attention_fn, "is_plain_flash", False
        ):
            # The memory-tight policy uses the lite block: residuals
            # are (x, out, lse) instead of (q, k, v, out, lse) — what
            # makes 64k-token training compile on one 16GB chip (see
            # _attn_block_lite). Independent of the passed
            # attention_fn by construction: is_plain_flash asserts the
            # fn IS the default flash kernel.
            def body(carry, pl):
                with jax.named_scope("attn"):
                    attn = _attn_block_lite(config, pl, carry, positions)
                return ckpt_out_mlp(pl, attn, carry)

        else:
            attn_fn = attention_fn or dot_product_attention
            ckpt_qkv = jax.checkpoint(
                functools.partial(attention_qkv, config),
                policy=flank_policy,
            )

            def body(carry, pl):
                with jax.named_scope("attn"):
                    q, k, v = ckpt_qkv(pl, carry, positions)
                    attn = attn_fn(
                        q, k, v, causal=True,
                        q_positions=positions, kv_positions=positions,
                    )
                return ckpt_out_mlp(pl, attn, carry)

    else:
        def body(carry, pl):
            y, aux = transformer_layer(
                config, pl, carry, positions, attention_fn
            )
            return y, aux

        if config.remat:
            policy = (
                dots_policy
                if config.remat_policy in ("dots", "mlp_only")
                else None
            )
            body = jax.checkpoint(body, policy=policy)
    x, auxes = jax.lax.scan(body, x, layer_params)
    return x, jnp.sum(auxes)


def forward_hidden(
    config: TpuLMConfig,
    params,
    tokens,                      # [b, s] int32
    positions=None,              # [b, s] global positions
    attention_fn=None,
):
    """Forward up to (but excluding) the final norm + unembedding.

    Returns (hidden [b, s, d], aux_loss scalar). pp_stages must be 1 —
    the pipelined path owns its own unembed placement.
    """
    if attention_fn is None and positions is None:
        attention_fn = default_attention_fn()
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    x = embed_tokens(config, params, tokens)
    return run_layer_stack(
        config, params["layers"], x, positions, attention_fn
    )


def forward(
    config: TpuLMConfig,
    params,
    tokens,                      # [b, s] int32
    positions=None,              # [b, s] global positions
    attention_fn=None,
):
    """Full forward. Dispatches to trainer/pipeline.py when
    pp_stages > 1. Returns (logits [b, s, vocab] f32, aux_loss scalar).

    When the caller passes no explicit ``attention_fn`` and no explicit
    ``positions`` (i.e. positions are the contiguous [0..s) default), the
    attention impl is resolved by ``default_attention_fn`` — the Pallas
    flash kernel on TPU. Callers with sharded/packed positions (ring
    attention, SP meshes) pass their own ``attention_fn``.
    """
    if config.pp_stages > 1:
        if attention_fn is None and positions is None:
            attention_fn = default_attention_fn()
        from dlrover_tpu.trainer.pipeline import pipelined_forward

        return pipelined_forward(
            config, params, tokens, positions, attention_fn
        )
    x, aux = forward_hidden(config, params, tokens, positions, attention_fn)
    return unembed(config, params, x), aux


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------


def cross_entropy(logits, targets, mask=None, z_weight: float = 1e-4):
    """Token-mean CE + z-loss. logits f32 [b,s,v]; targets int [b,s]."""
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    )[..., 0]
    nll = logz - target_logit
    zloss = z_weight * jnp.square(logz)
    per_tok = nll + zloss
    if mask is None:
        return jnp.mean(per_tok)
    mask = mask.astype(jnp.float32)
    return jnp.sum(per_tok * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _fused_ce_mode() -> str:
    """Parse DLROVER_TPU_FUSED_CE once: "on" | "off" | "auto".

    Unrecognized values warn and fall back to auto instead of silently
    flipping the CE path."""
    raw = os.environ.get("DLROVER_TPU_FUSED_CE", "auto").lower()
    if raw in ("on", "1", "fused", "true"):
        return "on"
    if raw in ("off", "0", "unfused", "false"):
        return "off"
    if raw != "auto":
        import logging

        logging.getLogger(__name__).warning(
            "DLROVER_TPU_FUSED_CE=%r not in (on, off, auto); using auto",
            raw,
        )
    return "auto"


def _fused_ce_applicable(config) -> bool:
    """Fused CE handles pp == 1 and vocab-unsharded meshes. Under tensor
    parallelism the vocab dim of lm_head is sharded — there the unfused
    path is the right one anyway (GSPMD shards the logits matmul and
    inserts the logsumexp psum); a blockwise dynamic-slice over a sharded
    vocab would force per-block collectives instead."""
    if config.pp_stages > 1:
        return False
    from dlrover_tpu.parallel.sharding import current_mesh, logical_to_spec

    mesh = current_mesh()
    if mesh is None:
        return True
    vocab_spec = logical_to_spec(("embed", "vocab"))[1]
    if vocab_spec is None:
        return True
    axes = (vocab_spec,) if isinstance(vocab_spec, str) else vocab_spec
    return all(dict(mesh.shape).get(a, 1) == 1 for a in axes)


def resolve_ce_path(config, n_tokens: int) -> str:
    """"fused" | "dense" — the CE decision ``loss_fn`` makes for a
    batch of ``n_tokens`` tokens, exposed so the driver dryrun
    (__graft_entry__.py) can LOG which CE path each certified mesh
    executed (VERDICT r4 #8). Mesh-dependent: call under the same
    ``with mesh:`` the step runs in.

    The chunked fused CE runs at ~0.99-1.07x dense on v5e (same three
    matmuls; gradients computed in the forward, see ops/fused_ce.py)
    while never materializing the [N, V] logits. "auto" engages it
    only ABOVE the measured N*V crossover
    (ops/fused_ce.AUTO_FUSED_MIN_NV ≈ 2 GiB of f32 logits): bench r05
    measured the chunked path at 1.042x dense at the flagship shape
    just below the line, while above it the memory freed is what lets
    the attn_save remat policy fit at 32k tokens and the time cost is
    a wash. Below the line dense keeps its measured edge on the
    flagship MFU path."""
    from dlrover_tpu.ops.fused_ce import auto_prefers_dense

    mode = _fused_ce_mode()
    use_fused = mode == "on" or (
        mode == "auto"
        and not auto_prefers_dense(n_tokens, config.vocab_size)
    )
    if use_fused and _fused_ce_applicable(config):
        return "fused"
    return "dense"


def loss_fn(config, params, batch, attention_fn=None):
    """batch: {"tokens": [b,s+1]} — next-token LM loss.

    Uses the fused blockwise CE (ops/fused_ce.py) whenever applicable so
    the [b, s, vocab] f32 logits never materialize; falls back to
    ``forward`` + ``cross_entropy`` for pipelined or vocab-sharded runs
    (see ``resolve_ce_path``). Set DLROVER_TPU_FUSED_CE=off to force
    the unfused path.
    """
    tokens = batch["tokens"][:, :-1]
    targets = batch["tokens"][:, 1:]
    if resolve_ce_path(config, tokens.size) == "fused":
        from dlrover_tpu.ops.fused_ce import fused_cross_entropy

        x, aux = forward_hidden(
            config, params, tokens, attention_fn=attention_fn
        )
        with jax.named_scope("vocab"):
            h = final_hidden(config, params, x)
            # Long sequences cap the CE row chunk at 4096: the 8192-row
            # tile pushed the whole-program TPU compile over the edge
            # when combined with the attn_save remat policy (measured
            # v5e: compile-helper failure at 32k tokens; 4096 compiles
            # and times identically there, and at long context the CE is
            # ~2% of the step). Short-sequence large-batch runs keep the
            # measured-fastest auto chunk.
            ce = fused_cross_entropy(
                h,
                params["lm_head"].astype(config.compute_dtype),
                targets,
                batch.get("mask"),
                block_rows=4096 if tokens.shape[1] >= 32768 else None,
            )
    else:
        logits, aux = forward(
            config, params, tokens, attention_fn=attention_fn
        )
        with jax.named_scope("vocab"):
            ce = cross_entropy(logits, targets, batch.get("mask"))
    loss = ce + config.moe_aux_weight * aux
    return loss, {"ce": ce, "aux": aux}
