"""Autoregressive decoding for TpuLM: KV-cache prefill + generate.

TPU-shaped: the whole decode loop is ONE jitted ``lax.scan`` — static
shapes (cache pre-allocated at ``max_len``), no per-token dispatch, and
position-masked attention over the cache so padding never leaks into
the softmax. The cache layout [layers, batch, max_len, kv_heads,
head_dim] keeps the per-step update a ``dynamic_update_slice`` on the
time axis and shards like activations (kv_heads on tp, batch on dp).

The decode layer is BUILT FROM the training layer's own blocks
(llama.attention_qkv / attention_out / mlp_block) plus the shared
``dot_product_attention`` — only the cache append is decode-specific,
so dense-model training and generation cannot drift. Compiled programs
are cached per (config, shapes); temperature is a TRACED scalar, so
per-request temperatures retrace nothing.

The cache's fill cursor is a PER-ROW [b] int32 vector: generate() keeps
every row at the same fill (its append is still one dynamic-update-
slice at the shared cursor), while the continuous-batching serving
engine (serving/engine.py) drives the same layer blocks with genuinely
ragged per-slot fills — the masking (_append_free_attention,
dot_product_attention positions) is per-row either way.

MoE caveat: expert capacity is derived from the LOCAL sequence length
of each call (models/moe.py expert_capacity), so token-drop behavior
differs between a full teacher-forced forward and prefill+decode —
single-token decode steps clamp capacity to 1 and never drop. This is
the standard train/infer capacity asymmetry of capacity-factor MoE,
not a bug; exact logit parity holds for dense configs only.

    state = ... (restored params)
    out = generate(cfg, params, prompt_tokens, max_new_tokens=64)
"""

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.models import llama
from dlrover_tpu.ops.attention import dot_product_attention


class DecodeCache(NamedTuple):
    k: jnp.ndarray  # [layers, b, max_len, kv_heads, head_dim]
    v: jnp.ndarray
    length: jnp.ndarray  # [b] int32 — tokens filled so far, per row
    # int8 caches only (ops/kv_quant): per-(row, head) f32 scales
    # [layers, b, max_len, kv_heads]; None for fp caches.
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


def _kv_cache_dtype() -> str:
    """"fp" (cache in compute_dtype, the default) | "int8"
    (ops/kv_quant per-(row, head) scales — half the decode KV bytes).
    DLROVER_TPU_KV_DTYPE picks; typos warn once and fall back to
    "fp"."""
    from dlrover_tpu.common.env_utils import resolve_env_choice

    return resolve_env_choice(
        "DLROVER_TPU_KV_DTYPE", ("fp", "int8"), "fp"
    )


def init_cache(
    config: llama.TpuLMConfig, batch: int, max_len: int,
    kv_dtype: Optional[str] = None,
) -> DecodeCache:
    if config.pp_stages > 1:
        raise NotImplementedError(
            "decode runs on the flat layer stack; merge pipeline stages "
            "for inference"
        )
    kv_dtype = kv_dtype or _kv_cache_dtype()
    if kv_dtype not in ("fp", "int8"):
        # An explicit argument bypasses the env resolver's vocabulary
        # check; silently building an fp cache would make an intended
        # int8 A/B measure the wrong path.
        raise ValueError(
            f"kv_dtype {kv_dtype!r} not in ('fp', 'int8')"
        )
    shape = (
        config.n_layers,
        batch,
        max_len,
        config.n_kv_heads,
        config.head_dim,
    )
    if kv_dtype == "int8":
        return DecodeCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            length=jnp.zeros((batch,), jnp.int32),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32),
        )
    dtype = config.compute_dtype
    return DecodeCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        length=jnp.zeros((batch,), jnp.int32),
    )


def _uniform_cursor(cache_len):
    """Scalar write cursor from a scalar-or-[b] fill. The uniform
    prefill/append paths (generate keeps every row at the same fill)
    write with ONE dynamic-update-slice at the shared cursor; ragged
    callers (the serving engine) never reach these paths — they append
    with a per-row scatter instead."""
    cl = jnp.asarray(cache_len)
    return cl if cl.ndim == 0 else cl[0]


def _decode_attn_impl() -> str:
    """"pallas" | "xla" for the single-token decode step's attention.

    Auto is XLA: the length-aware Pallas kernel
    (ops/decode_attention.py) reads only the filled cache blocks, but
    its (batch, kv_head, block) grid runs SEQUENTIALLY on TPU — at the
    flagship decode shape the serialization costs more than the padded
    reads it saves (measured v5e b=8: 3.58 vs 1.26 ms/token against
    the append-free XLA step; the bench A/B keeps both on record). DLROVER_TPU_DECODE_ATTN=pallas opts in
    (wins would need batch*kv_heads small or caches much longer than
    the fill). Typos warn once and fall back to auto → xla
    (env_utils.resolve_env_choice: a silent "palas"→xla would make an
    intended kernel A/B measure the wrong path)."""
    from dlrover_tpu.common.env_utils import resolve_env_choice

    raw = resolve_env_choice(
        "DLROVER_TPU_DECODE_ATTN", ("pallas", "xla", "auto"), "auto"
    )
    return "xla" if raw == "auto" else raw


def _fuse_decode_params(config, layers):
    """Concatenate the per-layer projection weights the decode loop
    multiplies back to back: wq|wk|wv -> one [d, h+2kh, hd] matmul and
    w_gate|w_up -> one [d, 2f] matmul (dense configs). Decode is
    op-count-bound (each step is ~160 small dispatches), so halving the
    projection matmuls is a direct ms/token win; the math is identical.
    Leaves are stacked [L, ...]."""
    if config.n_experts > 0:
        return layers
    fused = dict(layers)
    fused["wqkv"] = jnp.concatenate(
        [layers["wq"], layers["wk"], layers["wv"]], axis=2
    )  # [L, d, h + 2*kh, hd]
    fused["w_gu"] = jnp.concatenate(
        [layers["w_gate"], layers["w_up"]], axis=2
    )  # [L, d, 2f]
    for k in ("wq", "wk", "wv", "w_gate", "w_up"):
        del fused[k]
    return fused


def _fused_qkv(config, p, x, positions):
    """attention_qkv over the concatenated projection (decode path)."""
    cdt = config.compute_dtype
    hx = llama.rms_norm(x, p["attn_norm"]).astype(cdt)
    qkv = jnp.einsum("bsd,dhk->bshk", hx, p["wqkv"].astype(cdt))
    h, kh = config.n_heads, config.n_kv_heads
    q, k, v = (
        qkv[:, :, :h],
        qkv[:, :, h:h + kh],
        qkv[:, :, h + kh:],
    )
    q = llama.apply_rope(q, positions, config.rope_theta)
    k = llama.apply_rope(k, positions, config.rope_theta)
    return q, k, v


def _fused_mlp(config, p, x):
    cdt = config.compute_dtype
    residual = x
    hx = llama.rms_norm(x, p["mlp_norm"]).astype(cdt)
    f = config.mlp_dim
    gu = jnp.einsum("bsd,df->bsf", hx, p["w_gu"].astype(cdt))
    a = jax.nn.silu(gu[..., :f]) * gu[..., f:]
    out = jnp.einsum("bsf,fd->bsd", a, p["w_down"].astype(cdt))
    return residual + out.astype(residual.dtype)


def _layer_decode(
    config, p, x, positions, k_cache, v_cache, cache_len,
    attn_impl=None, k_scale=None, v_scale=None,
):
    """One decoder block over [b, sq] new tokens with cache append.
    Returns (x, new_k_cache, new_v_cache) — plus (new_k_scale,
    new_v_scale) when the cache is int8 (``k_scale`` given: the append
    quantizes per ops/kv_quant; the single-token Pallas path
    dequantizes in-kernel, the full-cache XLA path materializes the
    dequantized view — it only serves compute-bound prefill).
    ``attn_impl`` ("pallas" | "xla") is resolved by the caller; None
    falls back to the env knob (direct callers / tests). ``cache_len``
    may be scalar or a UNIFORM [b] vector — the append writes at the
    shared cursor."""
    residual = x
    quantized = k_scale is not None
    if "wqkv" in p:
        q, k, v = _fused_qkv(config, p, x, positions)
    else:
        q, k, v = llama.attention_qkv(config, p, x, positions)
    # Append the new tokens' K/V at the (uniform) cache cursor.
    cursor = _uniform_cursor(cache_len)
    if quantized:
        from dlrover_tpu.ops.kv_quant import quantize_kv

        kq, ks_new = quantize_kv(k)
        vq, vs_new = quantize_kv(v)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, kq, (0, cursor, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, vq, (0, cursor, 0, 0)
        )
        k_scale = jax.lax.dynamic_update_slice(
            k_scale, ks_new, (0, cursor, 0)
        )
        v_scale = jax.lax.dynamic_update_slice(
            v_scale, vs_new, (0, cursor, 0)
        )
    else:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, cursor, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, cursor, 0, 0)
        )
    max_len = k_cache.shape[1]
    block_k = next(
        (c for c in (128, 64, 32, 16) if max_len % c == 0), None
    )
    if (
        q.shape[1] == 1
        and block_k is not None
        and (attn_impl or _decode_attn_impl()) == "pallas"
    ):
        # Single-token step: the length-aware kernel reads only the
        # filled cache blocks (ops/decode_attention.py); int8 caches
        # dequantize in-kernel.
        from dlrover_tpu.ops.decode_attention import decode_attention

        attn = decode_attention(
            q[:, 0], k_cache, v_cache, cache_len + 1, block_k=block_k,
            k_scale=k_scale, v_scale=v_scale,
        )[:, None]
    else:
        # Plain attention over the full pre-allocated cache; with
        # contiguous query positions the causal mask already excludes
        # every unfilled slot. This path now serves PREFILL (sq > 1)
        # and the opt-in Pallas A/B only — the single-token hot loop
        # uses the append-free step (_layer_decode_read_only), which
        # removed the per-token cache rebuild that dominated this
        # path's profile. Other rejected alternatives (v5e, b=8,
        # 334M): the sequential-grid Pallas kernel (3.6 vs 1.3
        # ms/token) and lax.switch-bucketed static prefixes (no gain
        # at b>=8, b=1 0.92 -> 1.39 ms/token).
        if quantized:
            from dlrover_tpu.ops.kv_quant import dequantize_kv

            cdt = config.compute_dtype
            k_attn = dequantize_kv(k_cache, k_scale, cdt)
            v_attn = dequantize_kv(v_cache, v_scale, cdt)
        else:
            k_attn, v_attn = k_cache, v_cache
        attn = dot_product_attention(
            q,
            k_attn,
            v_attn,
            causal=True,
            q_positions=positions,
            kv_positions=jnp.arange(max_len),
        )
    x = llama.attention_out(config, p, attn, residual)
    if "w_gu" in p:
        x = _fused_mlp(config, p, x)
    else:
        x, _ = llama.mlp_block(config, p, x)
    if quantized:
        return x, k_cache, v_cache, k_scale, v_scale
    return x, k_cache, v_cache


def _append_free_attention(
    q, k_cache, v_cache, k_new, v_new, cache_len,
    k_scale=None, v_scale=None,
):
    """Single-token attention WITHOUT materializing an updated cache.

    The padded-cache decode path spent 21% of device time on two
    100-200MB per-token copies (measured v5e op profile): the layer
    scan rebuilt the full [L, b, max_len, kh, d] cache as stacked scan
    outputs every token, and XLA inserted a layout copy feeding it back
    to the next step. Here the cache is a READ-ONLY input; the new
    token's attention is decomposed into a cache part and a
    new-token part with a merged softmax (exact same math as
    dot_product_attention over the DUS'd cache — the new token is
    always its own last visible key), and the caller appends all
    layers' new K/V with ONE small dynamic-update-slice per token.

    q: [b, 1, h, d]; k_cache/v_cache: [b, S, kh, d] (rows >=
    cache_len unfilled); k_new/v_new: [b, 1, kh, d]; cache_len scalar
    or PER-ROW [b] int32 — ragged fills (the serving engine's slot
    pool) mask each row at its own length. Returns [b, 1, h, d].

    Int8 caches (``k_scale``/``v_scale`` [b, S, kh] — ops/kv_quant):
    dequantization FOLDS into the math — K scales multiply the raw
    logits, V scales the probability rows — so the dequantized cache
    is never materialized and the step's HBM read is the int8 bytes.
    The new token's own K/V stay full-precision here; its quantized
    row is what LATER steps read (write-once scheme).
    """
    from dlrover_tpu.ops.attention import NEG_INF

    b, _, h, d = q.shape
    _, skv, kh, _ = k_cache.shape
    g = h // kh
    scale = d ** -0.5
    q32 = (q[:, 0] * scale).astype(jnp.float32).reshape(b, kh, g, d)
    # Cache part: [b, kh, g, S]; only filled rows are visible — per
    # row, so ragged slot fills mask independently.
    logits = jnp.einsum(
        "bkgd,bskd->bkgs", q32, k_cache.astype(jnp.float32)
    )
    if k_scale is not None:
        logits = logits * k_scale.transpose(0, 2, 1)[:, :, None, :]
    lens = jnp.atleast_1d(jnp.asarray(cache_len, jnp.int32))
    visible = jnp.arange(skv)[None, :] < lens[:, None]  # [1|b, S]
    logits = jnp.where(visible[:, None, None, :], logits, NEG_INF)
    # New-token part: the query always sees itself.
    l_new = jnp.einsum(
        "bkgd,bkd->bkg", q32, k_new[:, 0].astype(jnp.float32)
    )
    m = jnp.maximum(jnp.max(logits, axis=-1), l_new)  # [b, kh, g]
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(visible[:, None, None, :], p, 0.0)
    p_new = jnp.exp(l_new - m)
    denom = jnp.sum(p, axis=-1) + p_new  # >= p_new > 0
    pv = p if v_scale is None else (
        p * v_scale.transpose(0, 2, 1)[:, :, None, :]
    )
    out = (
        jnp.einsum("bkgs,bskd->bkgd", pv, v_cache.astype(jnp.float32))
        + p_new[..., None] * v_new[:, 0].astype(jnp.float32)[:, :, None]
    ) / denom[..., None]
    return out.reshape(b, 1, h, d).astype(q.dtype)


def _layer_decode_read_only(
    config, p, x, positions, k_cache, v_cache, cache_len,
    k_scale=None, v_scale=None,
):
    """One decoder block over [b, 1] tokens; the cache is read-only.
    Returns (x, k_new [b, 1, kh, d], v_new) — the caller batches the
    cache append across all layers (see _append_free_attention).
    ``cache_len`` may be a ragged [b] vector: positions and masking are
    per-row, which is what the serving engine's decode step drives.
    ``k_scale``/``v_scale`` mark an int8 cache (folded dequant)."""
    residual = x
    if "wqkv" in p:
        q, k, v = _fused_qkv(config, p, x, positions)
    else:
        q, k, v = llama.attention_qkv(config, p, x, positions)
    attn = _append_free_attention(
        q, k_cache, v_cache, k, v, cache_len,
        k_scale=k_scale, v_scale=v_scale,
    )
    x = llama.attention_out(config, p, attn, residual)
    if "w_gu" in p:
        x = _fused_mlp(config, p, x)
    else:
        x, _ = llama.mlp_block(config, p, x)
    return x, k, v


def _layer_verify_read_only(
    config, p, x, positions, k_cache, v_cache, cache_len,
    k_scale=None, v_scale=None,
):
    """One decoder block over [b, T] tokens (speculative-decoding
    verification: the fed token plus K drafts); the cache is read-only.
    The T-query sibling of :func:`_layer_decode_read_only`, built on
    ``ops.decode_attention.spec_verify_attention`` — intra-draft
    causality rides inside the merged softmax, so T=1 is exactly the
    single-token step.

    fp caches: returns (x, k_new [b, T, kh, d], v_new). int8 caches
    (``k_scale`` given): the new rows are quantized IN-LAYER (per-row
    round-to-nearest — identical values to a post-scan quantize) so
    later draft queries attend the QUANTIZED earlier-draft keys
    exactly as sequential decode would read them back from the cache;
    returns (x, k_q, k_rows_scale, v_q, v_rows_scale) and the caller
    appends the quantized rows directly."""
    from dlrover_tpu.ops.decode_attention import spec_verify_attention

    residual = x
    if "wqkv" in p:
        q, k, v = _fused_qkv(config, p, x, positions)
    else:
        q, k, v = llama.attention_qkv(config, p, x, positions)
    if k_scale is not None:
        from dlrover_tpu.ops.kv_quant import quantize_kv

        kq, ks_rows = quantize_kv(k)
        vq, vs_rows = quantize_kv(v)
        attn = spec_verify_attention(
            q, k_cache, v_cache, k, v, cache_len,
            k_scale=k_scale, v_scale=v_scale,
            k_new_q=kq, k_new_scale=ks_rows,
            v_new_q=vq, v_new_scale=vs_rows,
        )
    else:
        attn = spec_verify_attention(
            q, k_cache, v_cache, k, v, cache_len
        )
    x = llama.attention_out(config, p, attn, residual)
    if "w_gu" in p:
        x = _fused_mlp(config, p, x)
    else:
        x, _ = llama.mlp_block(config, p, x)
    if k_scale is not None:
        return x, kq, ks_rows, vq, vs_rows
    return x, k, v


def _layer_scan_unroll(n_layers: int) -> int:
    """Unroll factor for the decode-time layer scan. ROLLED is the
    measured winner: with the append-free step the rolled scan lets
    XLA alias the cache append in place (measured v5e, 334M, b=8:
    1.38 ms/token, zero per-token cache copies in the op profile),
    while unrolling reintroduces 100-200MB/token of cache copy
    traffic (1.47-1.74 ms/token) — the unrolled straight-line code
    defeats the buffer aliasing that the loop structure makes
    provable. DLROVER_TPU_DECODE_UNROLL overrides for experiments."""
    import os

    raw = os.environ.get("DLROVER_TPU_DECODE_UNROLL", "")
    if raw:
        try:
            return max(1, min(int(raw), n_layers))
        except ValueError:
            pass
    return 1


def _forward_with_cache(
    config, params, tokens, cache: DecodeCache, attn_impl=None,
    unroll=None,
):
    """Run [b, sq] tokens through all layers, appending to the cache.
    Returns (logits of the LAST position [b, vocab], new cache).
    Uniform-fill contract: every row of ``cache.length`` holds the same
    value (generate() only ever advances all rows together), so the
    appends are single dynamic-update-slices at the shared cursor."""
    b, sq = tokens.shape
    positions = cache.length[:, None] + jnp.arange(sq, dtype=jnp.int32)[
        None, :
    ]
    x = llama.embed_tokens(config, params, tokens)
    unroll = unroll or _layer_scan_unroll(config.n_layers)
    quantized = cache.k_scale is not None
    new_ks = new_vs = None

    if sq == 1 and (attn_impl or _decode_attn_impl()) != "pallas":
        # Append-free single-token step (the decode hot loop): the
        # layer scan READS the cache; each layer returns only its new
        # token's K/V, and one small dynamic-update-slice appends all
        # layers at once. The padded-cache path below rebuilt the full
        # cache as stacked scan outputs — 100-200MB of per-token copy
        # traffic, 21% of decode device time (v5e op profile). Int8
        # caches stream half those bytes (dequant folded into the
        # attention math); the append quantizes each layer's new row.
        if quantized:
            def body1(carry, layer_in):
                pl, k_c, v_c, ks, vs = layer_in
                y, k_new, v_new = _layer_decode_read_only(
                    config, pl, carry, positions, k_c, v_c,
                    cache.length, k_scale=ks, v_scale=vs,
                )
                return y, (k_new, v_new)

            x, (k_news, v_news) = jax.lax.scan(
                body1, x,
                (params["layers"], cache.k, cache.v,
                 cache.k_scale, cache.v_scale),
                unroll=unroll,
            )
        else:
            def body1(carry, layer_in):
                pl, k_c, v_c = layer_in
                y, k_new, v_new = _layer_decode_read_only(
                    config, pl, carry, positions, k_c, v_c,
                    cache.length,
                )
                return y, (k_new, v_new)

            x, (k_news, v_news) = jax.lax.scan(
                body1, x, (params["layers"], cache.k, cache.v),
                unroll=unroll,
            )
        cursor = _uniform_cursor(cache.length)
        if quantized:
            from dlrover_tpu.ops.kv_quant import quantize_kv

            kq, ks_rows = quantize_kv(k_news)
            vq, vs_rows = quantize_kv(v_news)
            new_k = jax.lax.dynamic_update_slice(
                cache.k, kq, (0, 0, cursor, 0, 0)
            )
            new_v = jax.lax.dynamic_update_slice(
                cache.v, vq, (0, 0, cursor, 0, 0)
            )
            new_ks = jax.lax.dynamic_update_slice(
                cache.k_scale, ks_rows, (0, 0, cursor, 0)
            )
            new_vs = jax.lax.dynamic_update_slice(
                cache.v_scale, vs_rows, (0, 0, cursor, 0)
            )
        else:
            new_k = jax.lax.dynamic_update_slice(
                cache.k, k_news.astype(cache.k.dtype),
                (0, 0, cursor, 0, 0),
            )
            new_v = jax.lax.dynamic_update_slice(
                cache.v, v_news.astype(cache.v.dtype),
                (0, 0, cursor, 0, 0),
            )
    elif quantized:
        def body_q(carry, layer_in):
            pl, k_c, v_c, ks, vs = layer_in
            y, k_c, v_c, ks, vs = _layer_decode(
                config, pl, carry, positions, k_c, v_c, cache.length,
                attn_impl=attn_impl, k_scale=ks, v_scale=vs,
            )
            return y, (k_c, v_c, ks, vs)

        x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
            body_q, x,
            (params["layers"], cache.k, cache.v,
             cache.k_scale, cache.v_scale),
            unroll=unroll,
        )
    else:
        def body(carry, layer_in):
            pl, k_c, v_c = layer_in
            y, k_c, v_c = _layer_decode(
                config, pl, carry, positions, k_c, v_c, cache.length,
                attn_impl=attn_impl,
            )
            return y, (k_c, v_c)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache.k, cache.v),
            unroll=unroll,
        )
    logits = llama.unembed(config, params, x[:, -1:, :])[:, 0, :]
    new_cache = DecodeCache(
        k=new_k, v=new_v, length=cache.length + sq,
        k_scale=new_ks, v_scale=new_vs,
    )
    return logits, new_cache


def sample_token(logits, rng, temperature):
    """Greedy-or-sampled next token over the last axis of ``logits``
    ([V], [b, V], ...). ``temperature`` is a TRACED scalar or per-row
    vector; <= 0 means argmax. ONE definition shared by generate()'s
    pick and the serving engine's decode/prefill samplers — the
    sampling rule must never drift between batch generation and
    serving.

    Fused gumbel-max form: categorical sampling IS
    ``argmax(logits/t + gumbel)`` — drawing the SAME gumbel noise
    ``jax.random.categorical`` would (same key, same shape) and
    zeroing it where t <= 0 (a positive 1/t rescale never moves an
    argmax) collapses the old categorical + argmax + select — three
    full passes over the [b, V] logits — into ONE perturbed argmax
    pass. Token-identical to the previous implementation for every
    (key, temperature)."""
    t = jnp.asarray(temperature, jnp.float32)
    t_rows = t[..., None] if t.ndim else t
    z = logits / jnp.maximum(t_rows, 1e-6)
    gumbel = jax.random.gumbel(rng, z.shape, z.dtype)
    # t <= 0 rows select the RAW logits (not the 1/t-rescaled copy):
    # rescaling is argmax-preserving in exact arithmetic but could
    # round two near-ties together in low precision.
    z = jnp.where(t_rows > 0.0, z + gumbel, logits)
    return jnp.argmax(z, axis=-1).astype(jnp.int32)


def sample_token_logprobs(logits, rng, temperature, top_k: int = 0):
    """``sample_token`` variant that ALSO returns the chosen token's
    log-probability under the (temperature-scaled) sampling
    distribution — and, with ``top_k > 0``, the top-k alternatives.

    TOKEN-IDENTICAL to :func:`sample_token` for every (key,
    temperature) by construction: the token comes from the same fused
    perturbed-argmax call, and only the extra ``log_softmax`` pass over
    the [*, V] logits is new — which is exactly why this is a separate
    opt-in variant rather than the default hot-path sampler. The
    speculative-decoding verifier needs it for the rejection-sampling
    correction pick (masked residual logits in, chosen token +
    logprob out); ``temperature <= 0`` rows report the argmax token's
    logprob under the unscaled softmax.

    Returns ``(token, logprob)``, or with ``top_k``:
    ``(token, logprob, topk_tokens, topk_logprobs)``."""
    tok = sample_token(logits, rng, temperature)
    t = jnp.asarray(temperature, jnp.float32)
    t_rows = t[..., None] if t.ndim else t
    base = jnp.where(
        t_rows > 0.0, logits / jnp.maximum(t_rows, 1e-6), logits
    )
    logp = jax.nn.log_softmax(base, axis=-1)
    lp = jnp.take_along_axis(logp, tok[..., None], axis=-1)[..., 0]
    if top_k:
        tk_lp, tk_idx = jax.lax.top_k(logp, top_k)
        return tok, lp, tk_idx.astype(jnp.int32), tk_lp
    return tok, lp


def prepare_decode_params(config, params):
    """Decode-ready params: matmul leaves cast to the compute dtype
    (decode is bandwidth-bound on parameter reads — measured 2.2ms/token
    on v5e with f32 masters = one 1.3GB sweep per step; the cast cost
    amortizes over the whole loop and every per-step read halves) plus
    the fused wqkv/w_gu projections (_fuse_decode_params). Norm scales
    and the MoE router stay f32 (same precision rule as
    llama.run_layer_stack). Pure jnp: generate()'s jitted run calls it
    traced, the serving engine calls it eagerly once per engine."""
    cdt = config.compute_dtype
    if cdt != jnp.float32:
        keep = {"attn_norm", "mlp_norm", "router"}
        params = {
            "embed": params["embed"].astype(cdt),
            "layers": {
                k: (v if k in keep else v.astype(cdt))
                for k, v in params["layers"].items()
            },
            "final_norm": params["final_norm"],
            "lm_head": params["lm_head"].astype(cdt),
        }
    return {
        **params,
        "layers": _fuse_decode_params(config, params["layers"]),
    }


class GenerateResult(NamedTuple):
    tokens: jnp.ndarray       # [b, max_new_tokens]
    cache: DecodeCache


@functools.lru_cache(maxsize=32)
def _compiled_generate(
    config: llama.TpuLMConfig,
    batch: int,
    max_new_tokens: int,
    max_len: int,
    attn_impl: str = "xla",
    unroll: int = 0,
    kv_dtype: str = "fp",
):
    """One compiled program per (config, shapes, attn_impl, unroll) —
    repeat generate() calls reuse it (jit caches key on the function
    object, which must therefore be cached itself). Temperature is a
    TRACED scalar argument, NOT a cache key: per-request temperatures
    (a serving workload's normal case) previously forced a full
    retrace each time the value changed. The decode-attention impl and
    the layer-scan unroll are EXPLICIT cache-key arguments: generate()
    resolves their env knobs per call, so toggling them takes effect
    without cache_clear() (advisor r4)."""

    pick = sample_token

    def run(params, prompt, rng, temperature):
        params = prepare_decode_params(config, params)
        cache = init_cache(config, batch, max_len, kv_dtype=kv_dtype)
        logits, cache = _forward_with_cache(
            config, params, prompt, cache, attn_impl=attn_impl,
            unroll=unroll or None,
        )
        rng, first_key = jax.random.split(rng)
        first = pick(logits, first_key, temperature)

        def step(carry, _):
            cache, tok, rng = carry
            rng, sub = jax.random.split(rng)
            logits, cache = _forward_with_cache(
                config, params, tok[:, None], cache,
                attn_impl=attn_impl, unroll=unroll or None,
            )
            nxt = pick(logits, sub, temperature)
            return (cache, nxt, rng), tok

        (cache, last, _), toks = jax.lax.scan(
            step, (cache, first, rng), None, length=max_new_tokens - 1
        )
        out = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1
        )
        return out, cache

    return jax.jit(run)


def generate(
    config: llama.TpuLMConfig,
    params,
    prompt,                    # [b, prompt_len] int32
    max_new_tokens: int,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    kv_cache_dtype: Optional[str] = None,
) -> GenerateResult:
    """Greedy (temperature=0) or sampled decoding. The prefill and the
    whole decode loop are one jit-compiled program with static shapes.
    ``kv_cache_dtype``: "fp" (default) | "int8" — int8 halves the KV
    bytes every decode step streams (DLROVER_TPU_KV_DTYPE sets the
    default; the dtype is a compile-cache key, not a retrace)."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    b, prompt_len = prompt.shape
    max_len = max_len or (prompt_len + max_new_tokens)
    if max_len < prompt_len + max_new_tokens:
        raise ValueError("max_len too small for prompt + new tokens")
    if temperature > 0.0 and rng is None:
        # A silent fixed default would make every sampled call return
        # identical tokens (best-of-n sampling quietly broken).
        raise ValueError("temperature > 0 requires an explicit rng key")
    rng = rng if rng is not None else jax.random.key(0)
    run = _compiled_generate(
        config, b, max_new_tokens, max_len,
        attn_impl=_decode_attn_impl(),
        unroll=_layer_scan_unroll(config.n_layers),
        kv_dtype=kv_cache_dtype or _kv_cache_dtype(),
    )
    # np.float32, not a Python float: a weakly-typed scalar would give
    # the traced argument a different avals key and retrace once.
    import numpy as np

    tokens, cache = run(params, prompt, rng, np.float32(temperature))
    return GenerateResult(tokens=tokens, cache=cache)
