"""Deterministic fault-injection plane.

A process-wide registry of named injection sites (fault points) compiled
to near-no-ops when disarmed, armed by a seeded :class:`FaultSchedule`
so every recovery path in the stack (RPC transport, master servicer,
sharding client, flash checkpoint, elastic trainer, serving engine) can
be driven through a *reproducible* fault sequence — the substrate of
``tools/chaos_soak.py`` and the chaos regression tests
(docs/DESIGN.md §26).
"""

from dlrover_tpu.fault.registry import (  # noqa: F401
    KNOWN_POINTS,
    FaultAction,
    FaultInjected,
    FaultRule,
    FaultSchedule,
    active_schedule,
    arm,
    arm_from_env,
    disarm,
    fault_point,
)
