"""Fault-point registry + seeded fault schedules.

Design (docs/DESIGN.md §26):

- A **fault point** is a named call site on a production code path:
  ``fault_point("ckpt.persist.torn_write", path=...)``. Disarmed (the
  default, and the only state production jobs ever see) the call is one
  global read and a return — no locks, no allocation.
- A **FaultSchedule** arms the process: a list of :class:`FaultRule`\\ s,
  each binding a point (exact name or ``fnmatch`` glob) to an action.
  Triggers are *deterministic*: a rule fires on the Nth matching hit
  (per-rule counter), optionally once. Randomness lives in schedule
  GENERATION (the soak derives rule parameters from a seeded RNG), not
  in triggering — that is what makes a seed's fault trace reproducible.
- **Actions**: ``raise`` (``FaultInjected``), ``delay`` (sleep
  ``delay_s``), ``crash`` (SIGKILL the process — a worker dying
  mid-step), ``truncate`` (returned to the caller as a directive; the
  site applies it, e.g. tearing a just-written checkpoint shard).
- Every *fired* injection is appended to the schedule's trace — and,
  when ``DLROVER_TPU_FAULT_TRACE`` names a file, appended there with an
  fsync BEFORE the action executes, so even a ``crash`` action's entry
  survives the SIGKILL.

Cross-process arming: ``DLROVER_TPU_FAULT_SCHEDULE`` points at a JSON
file (:meth:`FaultSchedule.to_json` format); a subprocess calls
:func:`arm_from_env` early in main. The chaos soak uses this to rig its
worker subprocesses.
"""

import fnmatch
import json
import os
import signal
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.log import logger

SCHEDULE_ENV = "DLROVER_TPU_FAULT_SCHEDULE"
TRACE_ENV = "DLROVER_TPU_FAULT_TRACE"


class FaultInjected(RuntimeError):
    """Raised by an armed fault point with action ``raise``."""

    def __init__(self, point: str, rule_id: str = ""):
        super().__init__(f"injected fault at {point}" +
                         (f" (rule {rule_id})" if rule_id else ""))
        self.point = point
        self.rule_id = rule_id


class FaultAction:
    RAISE = "raise"
    DELAY = "delay"
    CRASH = "crash"
    TRUNCATE = "truncate"

    ALL = (RAISE, DELAY, CRASH, TRUNCATE)


# The instrumented sites, greppable in one place. Tests assert every
# listed point is actually reachable; new instrumentation registers its
# name here so the taxonomy in docs/DESIGN.md §26 stays honest.
KNOWN_POINTS: Dict[str, str] = {
    "rpc.get.drop_reply": (
        "master servicer: after a get handler ran (state mutated), drop "
        "the reply — the client sees a transport error, leases/values "
        "already moved master-side (ctx: request=<request class name>)"
    ),
    "rpc.report.drop_reply": (
        "master servicer: after a report handler ran, drop the reply — "
        "exercises at-most-once re-apply of done-reports etc."
    ),
    "rpc.client.get": (
        "master client: before a get RPC leaves the worker "
        "(ctx: request) — delay simulates a slow master, raise a "
        "dead one"
    ),
    "rpc.client.report": (
        "master client: before a report RPC leaves the worker"
    ),
    "shard.dispatch": (
        "task manager: entry of the batched lease dispatch — delay "
        "starves the prefetch pipeline"
    ),
    "data.prefetch.fetch": (
        "sharding client: prefetcher about to fetch leases — raise "
        "drives the transport-failure retry/backoff path"
    ),
    "ckpt.persist.torn_write": (
        "checkpoint storage: a proc shard file just landed — truncate "
        "tears its tail (torn write at crash), the reader must reject "
        "it (ctx: path)"
    ),
    "ckpt.persist.proc_file": (
        "checkpoint storage: before a proc shard file is written — "
        "crash kills the persister mid-step-dir (uncommitted dir), "
        "raise fails the persist"
    ),
    "ckpt.restore.memory": (
        "checkpoint engine: about to read the shm image — raise "
        "simulates the host (and its shm) being replaced, forcing the "
        "storage restore path"
    ),
    "agent.worker.crash": (
        "elastic trainer: a training step just completed — crash is a "
        "worker SIGKILL mid-step (ctx: step)"
    ),
    "serving.step.error": (
        "serving engine: an iteration is about to run its compiled "
        "programs — raise simulates a device/XLA error mid-decode"
    ),
    "fleet.router.dispatch": (
        "fleet router: a request is about to be handed to a chosen "
        "replica (ctx: replica, request) — raise drives the bounded "
        "retry / re-dispatch-to-a-different-replica path"
    ),
    "fleet.replica.step": (
        "fleet replica serve loop: one iteration is about to run "
        "(ctx: replica) — raise kills a thread replica's loop (the "
        "router must detect the silent death via heartbeats), crash "
        "SIGKILLs a subprocess replica mid-decode"
    ),
    "fleet.health.heartbeat": (
        "fleet replica: a heartbeat is about to be recorded/emitted "
        "(ctx: replica) — raise drops it (missed-heartbeat strikes), "
        "delay simulates a stalled replica"
    ),
    "sync.wait": (
        "sync service: a bounded barrier wait is starting — delay "
        "pushes it into its timeout path"
    ),
    "rescale.plan.broadcast": (
        "master servicer: a rescale plan is about to be returned to a "
        "polling worker — raise drops the broadcast on the wire; the "
        "pull protocol must re-deliver it on the next poll "
        "(ctx: plan_id, rank)"
    ),
    "rescale.barrier.wait": (
        "rescale client: one poll of a plan's phase barrier — crash is "
        "a worker SIGKILL mid-barrier; the coordinator's bounded wait "
        "must expire and re-plan around it (ctx: plan_id, phase)"
    ),
    "rescale.resume.first_step": (
        "rescale client: state restored, resume acked, first "
        "post-rescale step about to run — crash kills the worker in "
        "the restore-to-first-step window (ctx: plan_id)"
    ),
    "master.journal.write": (
        "master journal: a record group just became durable (fsynced) "
        "but the RPC reply has NOT been sent (ctx: kind) — crash on "
        "kind=dispatch is the canonical master_kill window: the lease "
        "is journaled, the worker never saw it, and the restarted "
        "master must requeue it exactly once"
    ),
    "master.restart": (
        "master journal: restore_master_state is replaying a recovered "
        "journal into a fresh master (ctx: epoch) — delay stretches the "
        "recovery window workers must ride through, raise fails the "
        "rehydration"
    ),
}


@dataclass
class FaultRule:
    """One (point, trigger, action) binding.

    ``nth``: fire on the Nth matching hit (1-based) of this rule's
    counter; ``every``: after the first firing, fire again every
    ``every`` hits (0 = governed by ``once``). ``once``: disarm after
    the first firing. ``match``: equality filter on the fault point's
    ctx kwargs (a hit only counts when every key matches).
    """

    point: str
    action: str = FaultAction.RAISE
    nth: int = 1
    once: bool = True
    every: int = 0
    delay_s: float = 0.0
    truncate_bytes: int = 0
    match: Optional[Dict[str, str]] = None
    rule_id: str = ""
    # runtime state (not part of the wire format)
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.action not in FaultAction.ALL:
            raise ValueError(f"unknown fault action {self.action!r}")
        if self.nth < 1:
            raise ValueError("nth is 1-based; use nth=1 for 'first hit'")
        if self.every > 0:
            # A recurring rule that disarms after one firing would
            # silently contradict its own ``every``.
            self.once = False
        if not self.rule_id:
            self.rule_id = f"{self.point}:{self.action}:n{self.nth}"

    def matches(self, name: str, ctx: Dict) -> bool:
        if not fnmatch.fnmatchcase(name, self.point):
            return False
        if self.match:
            for key, want in self.match.items():
                if str(ctx.get(key)) != str(want):
                    return False
        return True

    def should_fire(self) -> bool:
        """Call with the schedule lock held, after incrementing hits."""
        if self.once and self.fired:
            return False
        if self.hits == self.nth:
            return True
        if self.every > 0 and self.hits > self.nth:
            return (self.hits - self.nth) % self.every == 0
        return False

    def to_dict(self) -> dict:
        d = asdict(self)
        d.pop("hits")
        d.pop("fired")
        return d


class FaultSchedule:
    """A seeded set of rules + the trace of everything that fired.

    The ``seed`` is carried for provenance/repro (the soak derives rule
    parameters from it); triggering itself is deterministic counters.
    """

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 label: str = ""):
        self.rules = list(rules)
        self.seed = seed
        self.label = label
        self.trace: List[Dict] = []
        self._lock = threading.Lock()
        self._seq = 0
        self._trace_path = os.getenv(TRACE_ENV, "")

    # ---- hit path ----------------------------------------------------------

    def hit(self, name: str, ctx: Dict) -> Optional[Dict]:
        """Evaluate one fault-point hit. Returns a directive dict for
        caller-applied actions (truncate), None otherwise. May raise
        FaultInjected, sleep, or SIGKILL the process."""
        fired_rule = None
        with self._lock:
            for rule in self.rules:
                if not rule.matches(name, ctx):
                    continue
                rule.hits += 1
                if rule.should_fire():
                    rule.fired += 1
                    fired_rule = rule
                    # Entry built UNDER the lock: a concurrent hit on
                    # the same rule must not bump ``hits`` between the
                    # firing decision and its record, and seq order
                    # must match append order in the in-memory trace.
                    self._seq += 1
                    entry = {
                        "seq": self._seq,
                        "point": name,
                        "action": rule.action,
                        "rule_id": rule.rule_id,
                        "hit": rule.hits,
                        "pid": os.getpid(),
                    }
                    self.trace.append(entry)
                    break  # first matching rule wins this hit
        if fired_rule is None:
            return None
        self._record(entry)
        return self._execute(fired_rule, name, entry)

    def _record(self, entry: Dict):
        logger.warning(
            "fault injected: %s action=%s rule=%s hit=%d",
            entry["point"], entry["action"], entry["rule_id"], entry["hit"],
        )
        # Persist BEFORE acting: a crash action must not lose its entry.
        if self._trace_path:
            try:
                with open(self._trace_path, "a") as f:
                    f.write(json.dumps(entry) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass

    def _execute(self, rule: FaultRule, name: str, entry: Dict):
        if rule.action == FaultAction.DELAY:
            time.sleep(rule.delay_s)
            return None
        if rule.action == FaultAction.RAISE:
            raise FaultInjected(name, rule.rule_id)
        if rule.action == FaultAction.CRASH:
            os.kill(os.getpid(), signal.SIGKILL)
            # Unreachable except in exotic test rigs that block SIGKILL
            # delivery semantics; fall through to a hard exit.
            os._exit(137)
        if rule.action == FaultAction.TRUNCATE:
            return {
                "action": FaultAction.TRUNCATE,
                "truncate_bytes": rule.truncate_bytes,
                "rule_id": rule.rule_id,
            }
        return None

    # ---- wire format -------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "label": self.label,
            "rules": [r.to_dict() for r in self.rules],
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        data = json.loads(text)
        rules = [FaultRule(**r) for r in data.get("rules", [])]
        return cls(rules, seed=data.get("seed", 0),
                   label=data.get("label", ""))


# ---------------------------------------------------------------------------
# Process-wide arming
# ---------------------------------------------------------------------------

_armed: Optional[FaultSchedule] = None
_arm_lock = threading.Lock()


def fault_point(name: str, **ctx) -> Optional[Dict]:
    """THE injection site call. Disarmed: one global read, return None.

    Armed: may raise :class:`FaultInjected`, sleep, SIGKILL the process,
    or return a directive dict (``truncate``) the caller applies.
    """
    sched = _armed
    if sched is None:
        return None
    return sched.hit(name, ctx)


def arm(schedule: FaultSchedule) -> FaultSchedule:
    global _armed
    with _arm_lock:
        _armed = schedule
    logger.warning(
        "fault schedule armed: seed=%d label=%s rules=%d",
        schedule.seed, schedule.label, len(schedule.rules),
    )
    return schedule


def disarm():
    global _armed
    with _arm_lock:
        _armed = None


def active_schedule() -> Optional[FaultSchedule]:
    return _armed


def arm_from_env() -> Optional[FaultSchedule]:
    """Arm from the JSON file named by ``DLROVER_TPU_FAULT_SCHEDULE``
    (subprocess rigging). No-op when unset/unreadable — a worker must
    never die because its chaos rigging file vanished."""
    path = os.getenv(SCHEDULE_ENV, "")
    if not path:
        return None
    try:
        with open(path) as f:
            schedule = FaultSchedule.from_json(f.read())
    except (OSError, ValueError, TypeError) as e:
        logger.warning("fault schedule %s unusable: %s", path, e)
        return None
    return arm(schedule)
