"""Structured training-event SDK: instants and duration spans.

Parity: reference dlrover/python/training_event/ (emitter.py, events as
begin/end pairs with a shared event_id; design
docs/design/training-event.md). Every control-plane state change —
rendezvous rounds, restarts, checkpoint commits, job phases — emits a
structured event so offline tooling can reconstruct exactly where a
job's time went (the input to goodput accounting).
"""

import itertools
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from dlrover_tpu.training_event.exporter import (
    EventExporter,
    build_default_exporter,
)


class EventType:
    INSTANT = "instant"
    BEGIN = "begin"
    END = "end"


@dataclass
class Event:
    name: str
    event_type: str = EventType.INSTANT
    target: str = ""  # emitting component: master|agent|trainer/...
    event_id: str = ""
    timestamp: float = field(default_factory=time.time)
    pid: int = field(default_factory=os.getpid)
    content: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(
            {
                "name": self.name,
                "type": self.event_type,
                "target": self.target,
                "event_id": self.event_id,
                "ts": round(self.timestamp, 6),
                "pid": self.pid,
                "content": self.content,
            },
            default=str,
        )


class DurationSpan:
    """begin()/end() pair sharing an event_id; usable as a context
    manager (exceptions mark the span failed)."""

    def __init__(self, emitter: "EventEmitter", name: str,
                 content: Optional[Dict] = None):
        self._emitter = emitter
        self.name = name
        self.content = dict(content or {})
        self.event_id = f"{os.getpid()}-{next(_span_counter)}"
        self._began = 0.0

    def begin(self) -> "DurationSpan":
        self._began = time.time()
        self._emitter.emit(
            Event(
                name=self.name,
                event_type=EventType.BEGIN,
                target=self._emitter.target,
                event_id=self.event_id,
                # Copy: callers may mutate span.content before end(),
                # and the async exporter serializes on another thread.
                content=dict(self.content),
            )
        )
        return self

    def end(self, success: bool = True, **extra):
        content = dict(self.content)
        content.update(extra)
        content["success"] = success
        if self._began:
            content["duration_s"] = round(time.time() - self._began, 6)
        self._emitter.emit(
            Event(
                name=self.name,
                event_type=EventType.END,
                target=self._emitter.target,
                event_id=self.event_id,
                content=content,
            )
        )

    def fail(self, error: str = ""):
        self.end(success=False, error=error)

    def __enter__(self):
        return self.begin()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.end()
        else:
            self.fail(str(exc))
        return False


_span_counter = itertools.count(0)


class EventEmitter:
    def __init__(self, target: str, exporter: Optional[EventExporter] = None):
        self.target = target
        self._exporter = exporter or build_default_exporter()

    def emit(self, event: Event):
        try:
            self._exporter.export(event)
        except Exception:
            pass  # observability must never break the job

    def instant(self, name: str, content: Optional[Dict] = None):
        self.emit(
            Event(
                name=name,
                event_type=EventType.INSTANT,
                target=self.target,
                content=dict(content or {}),
            )
        )

    def duration(self, name: str, content: Optional[Dict] = None) -> DurationSpan:
        return DurationSpan(self, name, content)


_emitters: Dict[str, EventEmitter] = {}
_emitters_lock = threading.Lock()


def get_emitter(target: str) -> EventEmitter:
    with _emitters_lock:
        if target not in _emitters:
            _emitters[target] = EventEmitter(target)
        return _emitters[target]
