"""Predefined event vocabularies per component.

Parity: reference dlrover/python/training_event/predefined/_dlrover.py
:39-269 — typed helpers so event names stay consistent across the
codebase and downstream analysis.
"""

from typing import Dict, Optional

from dlrover_tpu.training_event.emitter import DurationSpan, get_emitter


class MasterEvents:
    _e = staticmethod(lambda: get_emitter("master"))

    @classmethod
    def job_stage(cls, stage: str):
        cls._e().instant("job_stage", {"stage": stage})

    @classmethod
    def node_relaunch(cls, node_id: int, rank: int, reason: str):
        cls._e().instant(
            "node_relaunch",
            {"node_id": node_id, "rank": rank, "reason": reason},
        )

    @classmethod
    def node_status(cls, node_id: int, status: str, reason: str = ""):
        cls._e().instant(
            "node_status",
            {"node_id": node_id, "status": status, "reason": reason},
        )

    @classmethod
    def rdzv_round(cls, name: str, round_id: int, world_size: int):
        cls._e().instant(
            "rdzv_round",
            {"rdzv": name, "round": round_id, "world_size": world_size},
        )

    @classmethod
    def diagnosis_action(cls, action_type: str, reason: str):
        cls._e().instant(
            "diagnosis_action", {"action": action_type, "reason": reason}
        )

    @classmethod
    def scale_plan(cls, comment: str, target: int):
        cls._e().instant(
            "scale_plan", {"comment": comment, "target": target}
        )


class AgentEvents:
    _e = staticmethod(lambda: get_emitter("agent"))

    @classmethod
    def rendezvous(cls, content: Optional[Dict] = None) -> DurationSpan:
        return cls._e().duration("rendezvous", content)

    @classmethod
    def start_workers(cls, restart_count: int) -> DurationSpan:
        return cls._e().duration(
            "start_workers", {"restart_count": restart_count}
        )

    @classmethod
    def worker_failure(cls, exit_codes: Dict[int, int], decision: str):
        cls._e().instant(
            "worker_failure",
            {"exit_codes": exit_codes, "decision": decision},
        )

    @classmethod
    def node_check(cls) -> DurationSpan:
        return cls._e().duration("node_check")


class TrainerEvents:
    _e = staticmethod(lambda: get_emitter("trainer"))

    @classmethod
    def ckpt_save_memory(cls, step: int) -> DurationSpan:
        return cls._e().duration("ckpt_save_memory", {"step": step})

    @classmethod
    def ckpt_persist(cls, step: int) -> DurationSpan:
        return cls._e().duration("ckpt_persist", {"step": step})

    @classmethod
    def ckpt_restore(cls, step: int, source: str):
        cls._e().instant(
            "ckpt_restore", {"step": step, "source": source}
        )
