from dlrover_tpu.training_event.emitter import (  # noqa: F401
    DurationSpan,
    Event,
    EventEmitter,
    get_emitter,
)
from dlrover_tpu.training_event.predefined import (  # noqa: F401
    AgentEvents,
    MasterEvents,
    TrainerEvents,
)
