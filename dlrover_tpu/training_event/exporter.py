"""Event exporters: where structured events go.

Parity: reference dlrover/python/training_event/exporter.py — an async
file exporter (JSON lines, one file per process per day) and a console
exporter, selected by env:

- DLROVER_TPU_EVENT_EXPORTER = file|console|off   (default: file)
- DLROVER_TPU_EVENT_DIR      = directory for event files
                               (default: /tmp/dlrover_tpu_events)

Loss accounting: the async exporter must never block the training or
control path, so it drops on a full queue — but a silent drop poisons
every downstream consumer (the timeline merger reconstructs goodput
from these files). Drops and write failures are therefore counted in
the observability registry (scraped via the master's /metrics) and
surfaced with a rate-limited warning, and ``close()`` drains whatever
the writer thread did not get to (registered via ``atexit``).
"""

import abc
import atexit
import os
import queue
import threading
import time

from dlrover_tpu.common.log import logger
from dlrover_tpu.observability.registry import default_registry

_WARN_INTERVAL_S = 30.0


def _drop_counter():
    return default_registry().counter(
        "training_event_dropped_total",
        "training events dropped on a full exporter queue",
    )


def _write_failure_counter():
    return default_registry().counter(
        "training_event_write_failures_total",
        "training event writes that raised",
    )


def _exported_counter():
    return default_registry().counter(
        "training_event_exported_total",
        "training events successfully written",
    )


class EventExporter(abc.ABC):
    @abc.abstractmethod
    def export(self, event):
        ...

    def close(self):
        pass


class ConsoleExporter(EventExporter):
    def export(self, event):
        logger.info("[event] %s", event.to_json())


class NullExporter(EventExporter):
    def export(self, event):
        pass


class AsyncFileExporter(EventExporter):
    """JSON-lines file writer on a daemon thread; drops events rather
    than ever blocking the training/control path — but counts what it
    drops and flushes its queue on close."""

    def __init__(self, directory: str, max_queue: int = 4096):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        # Bind (and thereby pre-register) the loss counters once: a
        # /metrics scrape shows them at 0 from the first scrape
        # (absence != zero drops), and the per-event paths skip the
        # registry lock.
        self._dropped = _drop_counter()
        self._write_failures = _write_failure_counter()
        self._exported = _exported_counter()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._file = None
        self._file_day = ""
        self._stopped = threading.Event()
        self._closed = False
        self._last_drop_warn = 0.0
        self._last_write_warn = 0.0
        self._thread = threading.Thread(
            target=self._loop, name="event-exporter", daemon=True
        )
        self._thread.start()
        # The interpreter exits through atexit before daemon threads are
        # killed: whatever is still queued gets one last synchronous
        # drain instead of vanishing.
        atexit.register(self.close)

    def export(self, event):
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            self._dropped.inc()
            now = time.monotonic()
            if now - self._last_drop_warn > _WARN_INTERVAL_S:
                self._last_drop_warn = now
                logger.warning(
                    "event exporter queue full; dropping (total dropped: "
                    "%d)",
                    int(self._dropped.value()),
                )

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._stopped.set()
        self._thread.join(timeout=2)
        # The writer thread may have died mid-drain: flush the remainder
        # synchronously so close() means "on disk". Skipped if the
        # thread is somehow still alive (wedged in a write) — two
        # writers interleaving the same line-buffered file is worse
        # than a delayed flush.
        if not self._thread.is_alive():
            while True:
                try:
                    event = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._write(event)
            if self._file:
                self._file.close()
                self._file = None
        # Writer still draining after the join timeout: leave the file
        # to it — closing under a live writer would turn the remaining
        # events into spurious write failures (it's line-buffered, so
        # everything written so far is already on disk).

    def _ensure_file(self):
        day = time.strftime("%Y%m%d")
        if self._file is None or day != self._file_day:
            if self._file:
                self._file.close()
            path = os.path.join(
                self._dir, f"events_{day}_{os.getpid()}.jsonl"
            )
            self._file = open(path, "a", buffering=1)
            self._file_day = day

    def _write(self, event):
        try:
            self._ensure_file()
            self._file.write(event.to_json() + "\n")
            self._exported.inc()
        except Exception:
            self._write_failures.inc()
            now = time.monotonic()
            if now - self._last_write_warn > _WARN_INTERVAL_S:
                self._last_write_warn = now
                logger.warning(
                    "event write failed (total failures: %d)",
                    int(self._write_failures.value()),
                    exc_info=True,
                )

    def _loop(self):
        while not self._stopped.is_set() or not self._queue.empty():
            try:
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            self._write(event)


def build_default_exporter() -> EventExporter:
    kind = os.getenv("DLROVER_TPU_EVENT_EXPORTER", "file").lower()
    if kind == "off":
        return NullExporter()
    if kind == "console":
        return ConsoleExporter()
    directory = os.getenv(
        "DLROVER_TPU_EVENT_DIR", "/tmp/dlrover_tpu_events"
    )
    try:
        return AsyncFileExporter(directory)
    except OSError:
        return ConsoleExporter()
