"""Event exporters: where structured events go.

Parity: reference dlrover/python/training_event/exporter.py — an async
file exporter (JSON lines, one file per process per day) and a console
exporter, selected by env:

- DLROVER_TPU_EVENT_EXPORTER = file|console|off   (default: file)
- DLROVER_TPU_EVENT_DIR      = directory for event files
                               (default: /tmp/dlrover_tpu_events)
"""

import abc
import os
import queue
import threading
import time

from dlrover_tpu.common.log import logger


class EventExporter(abc.ABC):
    @abc.abstractmethod
    def export(self, event):
        ...

    def close(self):
        pass


class ConsoleExporter(EventExporter):
    def export(self, event):
        logger.info("[event] %s", event.to_json())


class NullExporter(EventExporter):
    def export(self, event):
        pass


class AsyncFileExporter(EventExporter):
    """JSON-lines file writer on a daemon thread; drops events rather
    than ever blocking the training/control path."""

    def __init__(self, directory: str, max_queue: int = 4096):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._file = None
        self._file_day = ""
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="event-exporter", daemon=True
        )
        self._thread.start()

    def export(self, event):
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            pass

    def close(self):
        self._stopped.set()
        self._thread.join(timeout=2)
        if self._file:
            self._file.close()
            self._file = None

    def _ensure_file(self):
        day = time.strftime("%Y%m%d")
        if self._file is None or day != self._file_day:
            if self._file:
                self._file.close()
            path = os.path.join(
                self._dir, f"events_{day}_{os.getpid()}.jsonl"
            )
            self._file = open(path, "a", buffering=1)
            self._file_day = day

    def _loop(self):
        while not self._stopped.is_set() or not self._queue.empty():
            try:
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self._ensure_file()
                self._file.write(event.to_json() + "\n")
            except Exception:
                pass


def build_default_exporter() -> EventExporter:
    kind = os.getenv("DLROVER_TPU_EVENT_EXPORTER", "file").lower()
    if kind == "off":
        return NullExporter()
    if kind == "console":
        return ConsoleExporter()
    directory = os.getenv(
        "DLROVER_TPU_EVENT_DIR", "/tmp/dlrover_tpu_events"
    )
    try:
        return AsyncFileExporter(directory)
    except OSError:
        return ConsoleExporter()
