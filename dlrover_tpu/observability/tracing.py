"""Cross-process distributed tracing: spans, context propagation, sinks.

The signal plane every later control loop reads (docs/DESIGN.md §29):
a stdlib-only span layer — ``trace_id``/``span_id``/``parent_id``,
monotonic + wall timestamps, free-form attrs — whose context rides the
existing RPC envelopes (:class:`dlrover_tpu.common.comm.Message` grew a
``trace`` carrier) so one serving request or one training step yields
ONE coherent tree across processes:

    fleet.request → fleet.attempt (retry/hedge siblings)
      → serving.request → serving.queue_wait / prefill / decode

Design rules, same discipline as :func:`dlrover_tpu.fault.fault_point`:

- **Disarmed is free.** Every span site starts with one read of the
  module-level ``_tracer`` global; when None (the default, and the only
  state production jobs see unless an operator arms tracing) the site
  returns a shared no-op object. No locks, no allocation, no branches
  beyond the one check.
- **Armed is cheap.** A finished span is one dict append into a bounded
  ring plus (when a sink is configured) one buffered JSONL line. The
  serving bench A/Bs the armed cost (<2% tokens/s budget).
- **Hot loops emit retrospectively.** The engine/trainer never open
  spans inside their step loops — they already record the timestamps
  they need (submit/admit/first-token/finish), and emit the whole
  phase tree in one :meth:`Tracer.record_span` burst at completion.
  A disarmed process pays the one global check per completion, zero
  per-iteration.

Cross-process arming mirrors the fault plane: ``DLROVER_TPU_TRACE_FILE``
names the JSONL sink; a subprocess calls :func:`arm_from_env` early in
main (fleet replica workers do). The sink format is the flight-recorder
family's: one self-describing JSON object per line, mergeable by
``tools/trace_query.py`` and ``tools/merge_timeline.py``.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional

from dlrover_tpu.common.log import logger

TRACE_FILE_ENV = "DLROVER_TPU_TRACE_FILE"
SCHEMA_VERSION = 1

# Carrier keys (the wire format of a trace context). Deliberately a
# plain dict of two short strings so it pickles/JSONs through every
# transport this repo has (Message envelopes, WorkItem JSONL).
_CARRIER_TRACE = "trace_id"
_CARRIER_SPAN = "span_id"


def _new_trace_id() -> str:
    return os.urandom(12).hex()


def _new_span_id() -> str:
    return os.urandom(6).hex()


class Span:
    """One timed operation. Context-manager friendly::

        with tracing.span("rpc.get", request="TaskRequest") as sp:
            sp.set_attr("bytes", n)

    ``end()`` is idempotent; an exception inside the ``with`` marks the
    span ``status="error"`` and records the exception type.
    """

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "start_wall", "start_mono", "end_mono", "attrs", "status",
        "_tracer", "_token",
    )

    def __init__(self, tracer, name, kind, trace_id, parent_id,
                 attrs=None, start_mono=None, start_wall=None):
        self._tracer = tracer
        self._token = None
        self.name = str(name)
        self.kind = str(kind)
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.start_mono = (
            start_mono if start_mono is not None else time.monotonic()
        )
        self.start_wall = (
            start_wall if start_wall is not None
            else time.time() - (time.monotonic() - self.start_mono)
        )
        self.end_mono: Optional[float] = None
        self.attrs: Dict = dict(attrs) if attrs else {}
        self.status = "ok"

    # ---- mutation ----------------------------------------------------------

    def set_attr(self, key: str, value) -> "Span":
        self.attrs[str(key)] = value
        return self

    def inc_attr(self, key: str, amount: int = 1) -> int:
        """Counter-style attr: the retried-RPC contract (the SAME span
        carries ``retry: n``, not n sibling spans — at-most-once stays
        visible as one wire operation that was re-sent)."""
        value = int(self.attrs.get(key, 0)) + amount
        self.attrs[str(key)] = value
        return value

    # ---- lifecycle ---------------------------------------------------------

    def carrier(self) -> Dict[str, str]:
        """The propagation dict a child process/peer parents to."""
        return {_CARRIER_TRACE: self.trace_id, _CARRIER_SPAN: self.span_id}

    def end(self, status: Optional[str] = None,
            end_mono: Optional[float] = None):
        if self.end_mono is not None:
            return  # idempotent: crash paths may race a normal end
        if status is not None:
            self.status = status
        self.end_mono = (
            end_mono if end_mono is not None else time.monotonic()
        )
        self._tracer._finish(self)

    def __enter__(self) -> "Span":
        self._token = self._tracer._activate(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._deactivate(self._token)
        if exc_type is not None and self.status == "ok":
            self.status = "error"
            self.attrs.setdefault("error", exc_type.__name__)
        self.end()
        return False

    def to_dict(self) -> Dict:
        dur = (
            (self.end_mono - self.start_mono)
            if self.end_mono is not None else None
        )
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "ts": self.start_wall,
            "mono": self.start_mono,
            "dur_s": dur,
            "status": self.status,
            "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """The disarmed return of every span site: same surface as
    :class:`Span`, all no-ops. One shared instance — a disarmed span
    site allocates nothing."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    status = "noop"

    def set_attr(self, key, value):
        return self

    def inc_attr(self, key, amount=1):
        return 0

    def carrier(self):
        return None

    def end(self, status=None, end_mono=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Process-wide span factory, ring, and JSONL sink.

    Thread model: span *objects* belong to their creating thread (no
    internal locking — the owning site starts and ends them); the ring,
    the export buffer, and the sink file are shared and locked. The
    per-thread *active* span stack drives implicit parenting so nested
    ``with span(...)`` blocks form a tree without plumbing."""

    def __init__(
        self,
        service: str = "",
        sink_path: Optional[str] = None,
        ring_capacity: int = 4096,
        export_capacity: int = 1024,
        on_finish: Optional[Callable[[Dict], None]] = None,
    ):
        self.service = str(service)
        self._sink_path = sink_path
        self._sink_file = None
        self._lock = threading.Lock()
        self._local = threading.local()
        # Finished spans, newest last: the master serves /api/traces
        # from its own ring; workers drain ``exports`` to piggyback
        # span summaries on report RPCs.
        self._ring: "deque[Dict]" = deque(maxlen=ring_capacity)
        self._exports: "deque[Dict]" = deque(maxlen=export_capacity)
        self._dropped = 0
        self._on_finish = on_finish

    # ---- span creation -----------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    def current(self) -> Optional[Span]:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def current_carrier(self) -> Optional[Dict[str, str]]:
        sp = self.current()
        return sp.carrier() if sp is not None else None

    def start_span(
        self,
        name: str,
        kind: str = "internal",
        parent=None,
        attrs: Optional[Dict] = None,
        start_mono: Optional[float] = None,
        start_wall: Optional[float] = None,
    ) -> Span:
        """A live span. ``parent`` may be a :class:`Span`, a carrier
        dict from another process, or None — None parents to this
        thread's active span, or starts a fresh trace.

        ``start_mono``/``start_wall`` back-date the span to timestamps
        taken before it could be named (the servicer clocks dispatch
        BEFORE deserializing the request that names the span — §32's
        metric-vs-span agreement depends on both covering the same
        window)."""
        trace_id, parent_id = self._resolve_parent(parent)
        return Span(
            self, name, kind, trace_id, parent_id, attrs,
            start_mono=start_mono, start_wall=start_wall,
        )

    def record_span(
        self,
        name: str,
        start_mono: float,
        end_mono: float,
        kind: str = "internal",
        parent=None,
        attrs: Optional[Dict] = None,
        status: str = "ok",
    ) -> Span:
        """Retrospective span from already-recorded monotonic
        timestamps — the hot-loop pattern: the engine/trainer keeps
        plain floats during the loop and emits the whole phase tree in
        one burst at completion. Returns the finished span so children
        can parent to it."""
        trace_id, parent_id = self._resolve_parent(parent)
        now_mono = time.monotonic()
        start_wall = time.time() - (now_mono - start_mono)
        sp = Span(
            self, name, kind, trace_id, parent_id, attrs,
            start_mono=start_mono, start_wall=start_wall,
        )
        sp.status = status
        sp.end(end_mono=max(end_mono, start_mono))
        return sp

    def _resolve_parent(self, parent):
        if parent is None:
            parent = self.current()
        if isinstance(parent, Span):
            return parent.trace_id, parent.span_id
        if isinstance(parent, dict) and parent.get(_CARRIER_TRACE):
            span_id = parent.get(_CARRIER_SPAN)
            return (
                str(parent[_CARRIER_TRACE]),
                str(span_id) if span_id else None,
            )
        return _new_trace_id(), None

    # ---- activation (implicit parenting) -----------------------------------

    def _activate(self, span: Span) -> int:
        stack = self._stack()
        stack.append(span)
        return len(stack) - 1

    def _deactivate(self, token: int):
        stack = self._stack()
        # Defensive truncation, not pop: an abandoned child (site that
        # never exited its ``with``) must not leave the stack lying.
        del stack[token:]

    # ---- finish path -------------------------------------------------------

    def _finish(self, span: Span):
        record = span.to_dict()
        if self.service:
            record["service"] = self.service
        record["pid"] = os.getpid()
        with self._lock:
            if len(self._exports) == self._exports.maxlen:
                self._dropped += 1
            self._ring.append(record)
            self._exports.append(record)
            self._write_locked(record)
        if self._on_finish is not None:
            try:
                self._on_finish(record)
            except Exception:  # noqa: BLE001 — observer must not break sites
                logger.debug("trace on_finish hook failed", exc_info=True)

    def _write_locked(self, record: Dict):
        if not self._sink_path:
            return
        try:
            if self._sink_file is None:
                os.makedirs(
                    os.path.dirname(self._sink_path) or ".", exist_ok=True
                )
                self._sink_file = open(self._sink_path, "a")
            self._sink_file.write(json.dumps(record) + "\n")
            self._sink_file.flush()
        except OSError:
            # A full/vanished disk must not take down the traced job.
            self._sink_path = None
            self._sink_file = None

    # ---- consumption -------------------------------------------------------

    def finished(self, last_n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            out = list(self._ring)
        return out[-last_n:] if last_n is not None else out

    def set_on_finish(self, callback: Optional[Callable[[Dict], None]]):
        """Install (or clear) the finished-span observer — the master
        hooks its TraceAggregator here so its own server spans reach
        /api/traces without a sink round-trip."""
        self._on_finish = callback

    def drain_exports(self, max_n: int = 256) -> List[Dict]:
        """Pop up to ``max_n`` finished spans for piggybacking on a
        report RPC (worker -> master push). Dropped-by-overflow count
        rides along as telemetry honesty."""
        out: List[Dict] = []
        with self._lock:
            while self._exports and len(out) < max_n:
                out.append(self._exports.popleft())
        return out

    def close(self):
        with self._lock:
            if self._sink_file is not None:
                try:
                    self._sink_file.close()
                except OSError:
                    pass
                self._sink_file = None


# ---------------------------------------------------------------------------
# Process-wide arming (fault_point discipline: disarmed = one global read)
# ---------------------------------------------------------------------------

_tracer: Optional[Tracer] = None
_arm_lock = threading.Lock()


def arm(tracer: Tracer) -> Tracer:
    global _tracer
    with _arm_lock:
        _tracer = tracer
    return tracer


def disarm():
    global _tracer
    with _arm_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None


def active_tracer() -> Optional[Tracer]:
    """THE armed-check every span site performs first. None = disarmed
    (the production default): the site must do nothing else."""
    return _tracer


def arm_from_env(service: str = "") -> Optional[Tracer]:
    """Arm from ``DLROVER_TPU_TRACE_FILE`` (subprocess rigging, the
    fault plane's ``arm_from_env`` pattern). No-op when unset."""
    path = os.getenv(TRACE_FILE_ENV, "")
    if not path:
        return None
    return arm(Tracer(service=service, sink_path=path))


def span(name: str, kind: str = "internal", parent=None, **attrs):
    """Context-managed span site. Disarmed: one global check, returns
    the shared no-op span."""
    tracer = _tracer
    if tracer is None:
        return NOOP_SPAN
    return tracer.start_span(name, kind=kind, parent=parent,
                             attrs=attrs or None)


def server_span(name: str, carrier, start_mono=None, start_wall=None,
                **attrs):
    """A server-side span parented to a remote carrier (or a fresh
    trace when the caller sent none). ``start_mono``/``start_wall``
    optionally back-date it to pre-deserialize dispatch clocks."""
    tracer = _tracer
    if tracer is None:
        return NOOP_SPAN
    parent = carrier if isinstance(carrier, dict) else None
    return tracer.start_span(name, kind="server", parent=parent,
                             attrs=attrs or None,
                             start_mono=start_mono, start_wall=start_wall)


def current_carrier() -> Optional[Dict[str, str]]:
    """The active span's propagation dict, for stamping onto outbound
    RPC envelopes. Disarmed (or no active span): None."""
    tracer = _tracer
    if tracer is None:
        return None
    return tracer.current_carrier()


def bump_current(key: str, amount: int = 1):
    """Increment a counter attr on the active span (transport retry
    accounting deep inside the stub, where the span object is not in
    scope). Disarmed or spanless: no-op."""
    tracer = _tracer
    if tracer is None:
        return
    sp = tracer.current()
    if sp is not None:
        sp.inc_attr(key, amount)


def record_span(name, start_mono, end_mono, kind="internal", parent=None,
                attrs=None, status="ok"):
    """Module-level retrospective emission; disarmed: one check, None."""
    tracer = _tracer
    if tracer is None:
        return None
    return tracer.record_span(
        name, start_mono, end_mono, kind=kind, parent=parent,
        attrs=attrs, status=status,
    )


# ---------------------------------------------------------------------------
# Master-side aggregation: recent trace trees + file loading
# ---------------------------------------------------------------------------


class TraceAggregator:
    """Bounded store of finished span records keyed by trace, fed by
    the master's own tracer (``on_finish`` hook) and by workers pushing
    drained spans over the existing DiagnosisDataReport verb. Serves
    ``/api/traces``."""

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512):
        self._lock = threading.Lock()
        self._max_traces = max_traces
        self._max_spans = max_spans_per_trace
        # trace_id -> list of span records, insertion-ordered dict as an
        # LRU-by-arrival of traces.
        self._traces: "Dict[str, List[Dict]]" = {}
        # Cap overflows are BOUNDED behavior, not silent behavior: every
        # span lost to trace eviction or a full per-trace bucket is
        # counted, locally and on /metrics (§32 buffer-accounting law).
        self._dropped = {"trace_cap": 0, "span_cap": 0}
        from dlrover_tpu.observability.registry import default_registry

        self._dropped_counter = default_registry().counter(
            "trace_ingest_dropped_total",
            "spans lost at the master's trace aggregator caps",
            labelnames=("reason",),
        )

    def ingest(self, spans: Iterable[Dict]):
        with self._lock:
            for record in spans or ():
                if not isinstance(record, dict):
                    continue
                trace_id = record.get("trace_id")
                if not trace_id:
                    continue
                bucket = self._traces.get(trace_id)
                if bucket is None:
                    bucket = self._traces[trace_id] = []
                    while len(self._traces) > self._max_traces:
                        evicted = self._traces.pop(
                            next(iter(self._traces))
                        )
                        if evicted:
                            self._dropped["trace_cap"] += len(evicted)
                            self._dropped_counter.inc(
                                len(evicted), reason="trace_cap"
                            )
                if len(bucket) < self._max_spans:
                    bucket.append(dict(record))
                else:
                    self._dropped["span_cap"] += 1
                    self._dropped_counter.inc(reason="span_cap")

    def ingest_one(self, record: Dict):
        self.ingest((record,))

    def stats(self) -> Dict:
        """Occupancy + drop accounting for /api/traces and
        /api/control_plane: a bounded buffer that cannot report its
        occupancy and drops is indistinguishable from a lossless one."""
        with self._lock:
            spans = sum(len(b) for b in self._traces.values())
            return {
                # Normalized occupancy/drops keys: every bounded
                # buffer on /api/control_plane speaks the same schema.
                "occupancy": spans,
                "drops": sum(self._dropped.values()),
                "traces": len(self._traces),
                "spans": spans,
                "max_traces": self._max_traces,
                "max_spans_per_trace": self._max_spans,
                "dropped": dict(self._dropped),
            }

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def spans(self, trace_id: str) -> List[Dict]:
        with self._lock:
            return [dict(r) for r in self._traces.get(trace_id, ())]

    def tree(self, trace_id: str) -> List[Dict]:
        """Root-level spans of a trace with nested ``children`` lists
        (a span whose parent never arrived is promoted to root — trees
        must render even when one process's spans were lost)."""
        return build_trees(self.spans(trace_id))

    def recent(self, limit: int = 20) -> List[Dict]:
        """Newest-trace-first summaries for the dashboard list view."""
        with self._lock:
            items = list(self._traces.items())[-limit:]
        out = []
        for trace_id, spans in reversed(items):
            roots = [s for s in spans if not s.get("parent_id")]
            root = roots[0] if roots else (spans[0] if spans else {})
            out.append({
                "trace_id": trace_id,
                "root": root.get("name", ""),
                "service": root.get("service", ""),
                "status": root.get("status", ""),
                "dur_s": root.get("dur_s"),
                "spans": len(spans),
            })
        return out


def build_trees(spans: List[Dict]) -> List[Dict]:
    """Nest a flat span list into parent->children trees (shared by the
    aggregator, the query CLI, and the soak's trace invariant)."""
    by_id = {}
    for record in spans:
        node = dict(record)
        node["children"] = []
        by_id[node.get("span_id")] = node
    roots = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda c: c.get("mono") or 0.0)
    roots.sort(key=lambda c: c.get("mono") or 0.0)
    return roots


def load_spans(paths: Iterable[str]) -> List[Dict]:
    """Read span JSONL files (tolerant of torn tails — a SIGKILLed
    process's last line may be partial)."""
    out: List[Dict] = []
    for path in paths:
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(record, dict):
                        out.append(record)
        except OSError:
            continue
    return out
