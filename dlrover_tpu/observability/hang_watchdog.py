"""Worker-side hang watchdog: rolling deadlines + all-thread stack dumps.

The master's :class:`TrainingHangDiagnostician` can only see that steps
STOPPED (global-step stagnation); it cannot see WHERE a live-but-wedged
worker is stuck. This module closes that gap from inside the worker:

- :func:`dump_all_stacks` snapshots every Python thread's frames via
  ``sys._current_frames()`` — the evidence that names the blocked frame
  (a collective wait, a lock, a storage read).
- :class:`HangWatchdog` tracks a progress beacon (``beat()`` after every
  step / request completion) and a ROLLING deadline — a multiple of the
  EWMA of recent beat intervals, floored — so a job whose steps take 2s
  and a job whose steps take 90s both get a meaningful "too long". On
  expiry it writes a flight-recorder-style JSON dump (ring-adjacent
  path, atomic rename) the agent collects, and fires at most once per
  hang (re-arming on the next beat).

The dump is also reported to the master best-effort (see
``ElasticTrainer``/agent wiring) as ``stack_dump`` diagnosis data, which
the hang diagnostician folds into its escalation message — "hung at
step N" becomes "hung at step N, rank 3 blocked in psum_wait".
"""

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.observability import flight_recorder

SCHEMA_VERSION = 1


def dump_all_stacks() -> Dict[str, List[str]]:
    """{thread label: [frame strings, innermost last]} for every live
    Python thread. Pure introspection — safe to call from signal
    handlers and watchdog threads; never raises."""
    try:
        frames = sys._current_frames()
    except Exception:  # noqa: BLE001 — diagnosis must not throw
        return {}
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in frames.items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        try:
            stack = [
                f"{fs.filename}:{fs.lineno} {fs.name}"
                for fs in traceback.extract_stack(frame)
            ]
        except Exception:  # noqa: BLE001
            stack = ["<unreadable>"]
        out[label] = stack
    return out


def hang_dump_path(node_rank: int, local_rank: int) -> str:
    """Same pure-function contract as ``flight_recorder.dump_path`` so
    the agent can reconstruct it for a worker it did not spawn."""
    return os.path.join(
        flight_recorder.flight_dir(),
        f"hang_node{node_rank}_rank{local_rank}.json",
    )


def write_stack_dump(
    path: str,
    reason: str = "",
    meta: Optional[Dict] = None,
    extra: Optional[Dict] = None,
) -> Optional[str]:
    """Atomic all-thread stack dump (tmp + rename, the flight-recorder
    dump discipline). Returns the path, or None on failure — runs on
    watchdog/signal paths and must never raise."""
    try:
        record = {
            "schema": SCHEMA_VERSION,
            "kind": "stack_dump",
            "reason": reason,
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "meta": dict(meta or {}),
            "stacks": dump_all_stacks(),
        }
        if extra:
            record.update(extra)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — crash-adjacent path
        return None


class HangWatchdog:
    """Rolling-deadline hang detector around a progress beacon.

    ``beat()`` after every unit of progress (a training step, a served
    request). ``check()`` — called by the background thread, or directly
    by tests with a fake clock — compares silence against the rolling
    deadline ``max(min_deadline_s, deadline_factor x EWMA(beat gap))``
    and dumps all-thread stacks once per hang episode."""

    def __init__(
        self,
        name: str = "train",
        dump_path: Optional[str] = None,
        deadline_factor: float = 8.0,
        min_deadline_s: float = 30.0,
        poll_interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        on_hang: Optional[Callable[[Dict], None]] = None,
        meta: Optional[Dict] = None,
    ):
        self.name = str(name)
        self._dump_path = dump_path
        self._factor = float(deadline_factor)
        self._min_deadline_s = float(min_deadline_s)
        self._poll_interval_s = float(poll_interval_s)
        self._clock = clock
        self._on_hang = on_hang
        self._meta = dict(meta or {})
        self._lock = threading.Lock()
        self._last_beat: Optional[float] = None
        self._gap_ewma: Optional[float] = None
        self._beats = 0
        self._fired_this_hang = False
        self.dumps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from dlrover_tpu.observability.registry import default_registry

        self._dump_counter = default_registry().counter(
            "hang_watchdog_dumps_total",
            "stack dumps captured by the hang watchdog",
        )

    # ---- beacon ------------------------------------------------------------

    def beat(self, now: Optional[float] = None):
        now = now if now is not None else self._clock()
        with self._lock:
            # A beat that ENDS a detected hang does not feed the EWMA:
            # the pathological gap is exactly what the rolling deadline
            # must not normalize toward.
            if self._last_beat is not None and not self._fired_this_hang:
                gap = max(now - self._last_beat, 0.0)
                self._gap_ewma = (
                    gap if self._gap_ewma is None
                    else 0.3 * gap + 0.7 * self._gap_ewma
                )
            self._last_beat = now
            self._beats += 1
            self._fired_this_hang = False

    def deadline_s(self) -> float:
        with self._lock:
            ewma = self._gap_ewma or 0.0
        return max(self._min_deadline_s, self._factor * ewma)

    # ---- detection ---------------------------------------------------------

    def check(self, now: Optional[float] = None) -> Optional[str]:
        """One watchdog evaluation; returns the dump path when this call
        captured a hang, else None."""
        now = now if now is not None else self._clock()
        with self._lock:
            if self._last_beat is None or self._fired_this_hang:
                return None
            silence = now - self._last_beat
        deadline = self.deadline_s()
        if silence <= deadline:
            return None
        with self._lock:
            if self._fired_this_hang:
                return None
            self._fired_this_hang = True
        self.dumps += 1
        self._dump_counter.inc()
        info = {
            "name": self.name,
            "hang_for_s": round(silence, 3),
            "deadline_s": round(deadline, 3),
            "beats": self._beats,
        }
        logger.warning(
            "hang watchdog %s: no progress for %.1fs (deadline %.1fs); "
            "dumping all-thread stacks",
            self.name, silence, deadline,
        )
        path = None
        if self._dump_path:
            path = write_stack_dump(
                self._dump_path,
                reason=f"hang:{self.name}",
                meta=self._meta,
                extra=info,
            )
        if self._on_hang is not None:
            try:
                report = dict(info)
                report["stacks"] = dump_all_stacks()
                self._on_hang(report)
            except Exception:  # noqa: BLE001 — diagnosis best-effort
                logger.debug("hang watchdog hook failed", exc_info=True)
        # Truthy even when no dump path is configured (in-process hooks
        # only): callers distinguish "fired" from "still fine".
        return path or "captured"

    # ---- background thread -------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"hang-watchdog-{self.name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self):
        while not self._stop.wait(self._poll_interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog never dies
                logger.debug("hang watchdog check failed", exc_info=True)


# ---------------------------------------------------------------------------
# Process-wide watchdog (flight-recorder discipline)
# ---------------------------------------------------------------------------

_watchdog: Optional[HangWatchdog] = None
_watchdog_lock = threading.Lock()


def install_watchdog(
    node_rank: int = 0,
    local_rank: int = 0,
    **kwargs,
) -> HangWatchdog:
    """Create + start the process watchdog (idempotent), dumping to the
    agent-collectable ``hang_dump_path``."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is not None:
            return _watchdog
        kwargs.setdefault(
            "dump_path", hang_dump_path(node_rank, local_rank)
        )
        meta = kwargs.pop("meta", {})
        meta.setdefault("node_rank", node_rank)
        meta.setdefault("local_rank", local_rank)
        wd = HangWatchdog(meta=meta, **kwargs)
        wd.start()
        _watchdog = wd
        return wd


def active_watchdog() -> Optional[HangWatchdog]:
    return _watchdog


def reset_watchdog():
    """Tests only."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is not None:
            _watchdog.stop()
        _watchdog = None


def collect_hang_dumps(node_rank: int, local_ranks,
                       max_age_s: Optional[float] = None) -> Dict[int, Dict]:
    """Agent-side fetch, mirroring ``flight_recorder.collect_dumps``."""
    out: Dict[int, Dict] = {}
    now = time.time()
    for lr in local_ranks:
        path = hang_dump_path(node_rank, lr)
        try:
            if max_age_s is not None and (
                now - os.path.getmtime(path) > max_age_s
            ):
                continue
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict) and data.get("kind") == "stack_dump":
            out[lr] = data
    return out
