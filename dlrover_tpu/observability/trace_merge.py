"""Clock-aligned job timeline merger.

Fuses every timing artifact one job produces into a single
chrome-trace/Perfetto JSON:

- training_event JSONL files (master/agent/trainer control-plane spans;
  begin/end pairs matched by event_id, instants kept as instants),
- tpu_timer chrome-trace dumps (per-rank kernel/step slices on
  CLOCK_MONOTONIC, shifted onto the epoch clock via the ``clock_sync``
  anchor ``tpu_timer/dump.py`` embeds at fetch time),
- flight-recorder dumps (per-rank step slices with data-wait /
  ckpt-blocked sub-slices),
- the master's goodput phase ledger (``PerfMonitor.phase_records()``,
  served at ``/api/phases``), rendered as a job-level phase track plus
  a running-goodput counter lane.

Everything lands on ONE clock (epoch microseconds — chrome tracing
only cares about consistency) with per-rank tracks, so "where did the
job's time go" is one file in ui.perfetto.dev. The merger also
RECONSTRUCTS goodput from the phase records it rendered and reports it
in the metadata, so the timeline can be cross-checked against the
live ``PerfMonitor.goodput()`` number — if they diverge, the trace is
lying and the bug is here.
"""

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from dlrover_tpu.common.constants import GoodputPhase

# Track (pid) allocation: ranks keep their own number, control-plane
# lanes live far above any plausible rank count.
JOB_PID = 9000
TARGET_PIDS = {"master": 9001, "agent": 9002, "trainer": 9003}
_EXTRA_TARGET_BASE = 9010

# Monotonic microseconds-since-boot never reach this; epoch
# microseconds passed it in 1973.
_EPOCH_US_FLOOR = 1e14


def _meta(pid: int, name: str) -> Dict:
    return {
        "ph": "M",
        "pid": pid,
        "name": "process_name",
        "args": {"name": name},
    }


# ---------------------------------------------------------------------------
# training_event JSONL -> control-plane spans
# ---------------------------------------------------------------------------


def load_events_jsonl(paths: Iterable[str]) -> List[Dict]:
    events: List[Dict] = []
    for path in paths:
        try:
            with open(path, errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(ev, dict) and "name" in ev:
                        events.append(ev)
        except OSError:
            continue
    return events


def _target_pid(target: str, extra: Dict[str, int]) -> int:
    base = (target or "unknown").split("/", 1)[0]
    if base in TARGET_PIDS:
        return TARGET_PIDS[base]
    if base not in extra:
        extra[base] = _EXTRA_TARGET_BASE + len(extra)
    return extra[base]


def events_to_trace(events: List[Dict]) -> List[Dict]:
    """Chrome events from training_event records. Begin/end pairs with
    a shared event_id become one X slice; an unmatched end still yields
    a slice when it carries duration_s; instants become ph="i"."""
    extra_targets: Dict[str, int] = {}
    out: List[Dict] = []
    open_begins: Dict[str, Dict] = {}
    seen_pids: Dict[int, str] = {}

    def pid_of(ev: Dict) -> int:
        target = str(ev.get("target", ""))
        pid = _target_pid(target, extra_targets)
        seen_pids.setdefault(pid, target.split("/", 1)[0] or "unknown")
        return pid

    for ev in sorted(events, key=lambda e: float(e.get("ts", 0.0))):
        etype = ev.get("type", "instant")
        ts_us = float(ev.get("ts", 0.0)) * 1e6
        pid = pid_of(ev)
        tid = int(ev.get("pid", 0))
        if etype == "begin" and ev.get("event_id"):
            open_begins[ev["event_id"]] = ev
            continue
        if etype == "end":
            begin = open_begins.pop(ev.get("event_id", ""), None)
            content = ev.get("content") or {}
            if begin is not None:
                start_us = float(begin.get("ts", 0.0)) * 1e6
                dur_us = max(ts_us - start_us, 0.0)
            elif "duration_s" in content:
                dur_us = float(content["duration_s"]) * 1e6
                start_us = ts_us - dur_us
            else:
                start_us, dur_us = ts_us, 0.0
            out.append(
                {
                    "name": ev.get("name", ""),
                    "ph": "X",
                    "ts": start_us,
                    "dur": dur_us,
                    "pid": pid,
                    "tid": tid,
                    "args": content,
                }
            )
            continue
        out.append(
            {
                "name": ev.get("name", ""),
                "ph": "i",
                "ts": ts_us,
                "pid": pid,
                "tid": tid,
                "s": "t",
                "args": ev.get("content") or {},
            }
        )
    # A begin whose end never arrived (worker died mid-span) is itself
    # a signal: emit it as a zero-duration slice flagged unfinished.
    for ev in open_begins.values():
        out.append(
            {
                "name": f"{ev.get('name', '')} (unfinished)",
                "ph": "X",
                "ts": float(ev.get("ts", 0.0)) * 1e6,
                "dur": 0.0,
                "pid": pid_of(ev),
                "tid": int(ev.get("pid", 0)),
                "args": ev.get("content") or {},
            }
        )
    metas = [_meta(pid, name) for pid, name in sorted(seen_pids.items())]
    return metas + out


# ---------------------------------------------------------------------------
# tpu_timer chrome traces -> aligned kernel slices
# ---------------------------------------------------------------------------


def align_trace_events(
    trace: Dict, rank: int
) -> Tuple[List[Dict], Optional[float]]:
    """Shift a tpu_timer trace onto the epoch clock; returns (events,
    offset_us or None when the trace had no anchor and is left on its
    own clock for the caller to place)."""
    events = [
        e
        for e in trace.get("traceEvents", [])
        if e.get("ph") in ("X", "i", "C")
    ]
    sync = trace.get("clock_sync") or {}
    offset: Optional[float] = None
    if "epoch_minus_mono_us" in sync:
        offset = float(sync["epoch_minus_mono_us"])
    elif events:
        ts_vals = sorted(float(e.get("ts", 0.0)) for e in events)
        if ts_vals[len(ts_vals) // 2] > _EPOCH_US_FLOOR:
            offset = 0.0  # already epoch microseconds
    out = []
    for e in events:
        e2 = dict(e)
        e2["pid"] = rank
        if offset is not None:
            e2["ts"] = float(e2.get("ts", 0.0)) + offset
        out.append(e2)
    return out, offset


# ---------------------------------------------------------------------------
# flight recorder dumps -> per-rank step slices
# ---------------------------------------------------------------------------


# Flight slices get their own thread tracks on the rank's pid: kernel
# slices from the rank's tpu_timer trace keep their native tids
# (usually small ints), and same-tid X events must strictly nest for
# chrome/Perfetto — flight steps only partially overlap kernels.
FLIGHT_STEP_TID = 1001
FLIGHT_WAIT_TID = 1002


def flight_to_trace(dump: Dict, rank: int) -> List[Dict]:
    out: List[Dict] = [
        {
            "ph": "M",
            "pid": rank,
            "tid": FLIGHT_STEP_TID,
            "name": "thread_name",
            "args": {"name": "flight steps"},
        },
        {
            "ph": "M",
            "pid": rank,
            "tid": FLIGHT_WAIT_TID,
            "name": "thread_name",
            "args": {"name": "flight waits"},
        },
    ]
    for rec in dump.get("steps", []):
        end_us = float(rec.get("ts", 0.0)) * 1e6
        dur_us = max(float(rec.get("step_time_s", 0.0)), 0.0) * 1e6
        start_us = end_us - dur_us
        # Event annotations (FlightRecorder.annotate: restore, re-mesh)
        # share the ring with step records; name them by their event.
        name = rec.get("event") or f"step {rec.get('step', '?')}"
        out.append(
            {
                "name": name,
                "ph": "X",
                "ts": start_us,
                "dur": dur_us,
                "pid": rank,
                "tid": FLIGHT_STEP_TID,
                "args": {
                    k: rec[k]
                    for k in (
                        "step",
                        "data_wait_s",
                        "ckpt_block_s",
                        "rdzv_round",
                        "seconds",
                        "mb_per_s",
                    )
                    if k in rec
                },
            }
        )
        # Waits as sub-slices at the front of the step: where the step's
        # wall time went when it was not compute.
        cursor = start_us
        for key, label in (
            ("data_wait_s", "data_wait"),
            ("ckpt_block_s", "ckpt_blocked"),
        ):
            wait_us = max(float(rec.get(key, 0.0)), 0.0) * 1e6
            if wait_us <= 0:
                continue
            out.append(
                {
                    "name": label,
                    "ph": "X",
                    "ts": cursor,
                    "dur": min(wait_us, max(end_us - cursor, 0.0)),
                    "pid": rank,
                    "tid": FLIGHT_WAIT_TID,
                    "args": {},
                }
            )
            cursor += wait_us
    return out


# ---------------------------------------------------------------------------
# goodput phase ledger -> job lane + reconstruction
# ---------------------------------------------------------------------------


def phases_to_trace(phases: Dict) -> List[Dict]:
    """Job-level lane: one slice per (node, phase) interval (tid=node),
    plus a running-goodput counter sampled at every interval end."""
    records = sorted(
        phases.get("records", []), key=lambda r: float(r.get("end", 0.0))
    )
    init_time = float(phases.get("init_time", 0.0))
    out: List[Dict] = [_meta(JOB_PID, "job goodput")]
    train_per_node: Dict[int, float] = {}
    # cause -> node -> seconds: the lane renders the per-node MEAN so
    # it agrees with goodput_attribution()'s averaging basis (a 0.4s
    # lockstep pause reported by 4 nodes is 0.4s of wall, not 1.6s).
    lost_by_cause: Dict[str, Dict[int, float]] = {}
    for rec in records:
        start = float(rec.get("start", 0.0))
        end = float(rec.get("end", 0.0))
        node = int(rec.get("node_id", 0))
        phase = str(rec.get("phase", ""))
        cause = rec.get("cause")
        args: Dict = {"node_id": node}
        if cause:
            args["cause"] = cause
        out.append(
            {
                "name": phase,
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(end - start, 0.0) * 1e6,
                "pid": JOB_PID,
                "tid": node,
                "args": args,
            }
        )
        if cause:
            # §34 lost-time lane: cumulative per-node-mean seconds per
            # cause, a counter track beside the goodput one — the
            # timeline shows WHERE the lost time went as it accrues.
            per_node = lost_by_cause.setdefault(cause, {})
            per_node[node] = per_node.get(node, 0.0) + (end - start)
            out.append(
                {
                    "name": "lost_by_cause",
                    "ph": "C",
                    "ts": end * 1e6,
                    "pid": JOB_PID,
                    "args": {
                        c: round(sum(nodes.values()) / len(nodes), 6)
                        for c, nodes in sorted(lost_by_cause.items())
                    },
                }
            )
        if phase == GoodputPhase.TRAIN:
            train_per_node[node] = (
                train_per_node.get(node, 0.0) + (end - start)
            )
        if train_per_node:
            wall = max(end - init_time, 1e-9)
            ratios = [
                min(t / wall, 1.0) for t in train_per_node.values()
            ]
            out.append(
                {
                    "name": "goodput",
                    "ph": "C",
                    "ts": end * 1e6,
                    "pid": JOB_PID,
                    "args": {
                        "goodput": round(sum(ratios) / len(ratios), 6)
                    },
                }
            )
    return out


def reconstruct_goodput(phases: Dict) -> float:
    """Recompute goodput from the phase records exactly the way
    ``PerfMonitor.goodput()`` does — the merge's cross-check."""
    records = phases.get("records", [])
    init_time = float(phases.get("init_time", 0.0))
    if not records:
        return 0.0
    max_end = max(float(r.get("end", 0.0)) for r in records)
    wall = max(max_end - init_time, 1e-9)
    train_per_node: Dict[int, float] = {}
    for rec in records:
        if str(rec.get("phase", "")) != GoodputPhase.TRAIN:
            continue
        node = int(rec.get("node_id", 0))
        dur = float(rec.get("end", 0.0)) - float(rec.get("start", 0.0))
        if dur > 0:
            train_per_node[node] = train_per_node.get(node, 0.0) + dur
    if not train_per_node:
        return 0.0
    ratios = [min(t / wall, 1.0) for t in train_per_node.values()]
    return sum(ratios) / len(ratios)


# ---------------------------------------------------------------------------
# The merge
# ---------------------------------------------------------------------------


def merge_job_timeline(
    event_files: Iterable[str] = (),
    rank_traces: Optional[Dict[int, Dict]] = None,
    flight_dumps: Optional[Dict[int, Dict]] = None,
    phases: Optional[Dict] = None,
) -> Dict:
    """One chrome-trace dict from every signal source; see module doc."""
    merged: List[Dict] = []
    unanchored: List[Tuple[int, List[Dict]]] = []
    clock_offsets: Dict[str, Optional[float]] = {}

    ranks = set()
    for rank in sorted(rank_traces or {}):
        aligned, offset = align_trace_events(
            (rank_traces or {})[rank], rank
        )
        clock_offsets[str(rank)] = offset
        if offset is None:
            unanchored.append((rank, aligned))
        else:
            merged.extend(aligned)
        ranks.add(rank)
    for rank in sorted(flight_dumps or {}):
        merged.extend(flight_to_trace((flight_dumps or {})[rank], rank))
        ranks.add(rank)

    event_list = load_events_jsonl(event_files)
    merged.extend(events_to_trace(event_list))
    if phases:
        merged.extend(phases_to_trace(phases))

    # Best-effort placement for traces with no clock anchor: start them
    # at the earliest epoch timestamp any anchored source produced.
    anchor_ts = [
        float(e.get("ts", 0.0))
        for e in merged
        if e.get("ph") in ("X", "i", "C")
    ]
    base = min(anchor_ts) if anchor_ts else 0.0
    for rank, events in unanchored:
        if not events:
            continue
        t0 = min(float(e.get("ts", 0.0)) for e in events)
        shift = base - t0
        clock_offsets[str(rank)] = shift
        for e in events:
            e["ts"] = float(e.get("ts", 0.0)) + shift
        merged.extend(events)

    rank_metas = [_meta(r, f"rank {r}") for r in sorted(ranks)]
    result = {
        "traceEvents": rank_metas + merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "ranks": sorted(ranks),
            "num_events": len(event_list),
            "clock_offsets_us": clock_offsets,
        },
    }
    if phases:
        result["metadata"]["reconstructed_goodput"] = round(
            reconstruct_goodput(phases), 6
        )
        # Per-node MEAN per cause — the same averaging basis as
        # goodput_attribution(), so the two §34 surfaces agree.
        lost: Dict[str, Dict[int, float]] = {}
        for rec in phases.get("records", []):
            cause = rec.get("cause")
            if not cause:
                continue
            dur = float(rec.get("end", 0.0)) - float(
                rec.get("start", 0.0)
            )
            if dur > 0:
                per_node = lost.setdefault(cause, {})
                node = int(rec.get("node_id", 0))
                per_node[node] = per_node.get(node, 0.0) + dur
        if lost:
            result["metadata"]["lost_seconds_by_cause"] = {
                c: round(sum(nodes.values()) / len(nodes), 6)
                for c, nodes in sorted(lost.items())
            }
    return result


# ---------------------------------------------------------------------------
# Validation (smoke tests / CI)
# ---------------------------------------------------------------------------


def validate_merged(trace: Dict) -> List[str]:
    """Schema problems in a merged trace; empty list means valid."""
    problems: List[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    pids_named = set()
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("X", "i", "C", "M", "B", "E"):
            problems.append(f"event {i}: bad ph {ph!r}")
            continue
        if ph == "M":
            if e.get("name") == "process_name":
                pids_named.add(e.get("pid"))
            continue
        if not isinstance(e.get("ts"), (int, float)):
            problems.append(f"event {i}: non-numeric ts")
        if ph == "X" and not isinstance(e.get("dur"), (int, float)):
            problems.append(f"event {i}: X without numeric dur")
        if "pid" not in e:
            problems.append(f"event {i}: missing pid")
    if not pids_named:
        problems.append("no process_name metadata rows")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    return problems


def write_merged(trace: Dict, path: str, pretty: bool = False):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f, indent=2 if pretty else None)
    os.replace(tmp, path)
