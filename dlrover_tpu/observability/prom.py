"""Prometheus text exposition for the observability hub.

Renders the process registry plus the master's live job-level views
(PerfMonitor goodput/phase ledger, JobMetricContext aggregates) into one
text/plain body, served by ``DashboardServer`` at ``/metrics``. The
output round-trips through the in-repo scraper
(:func:`dlrover_tpu.diagnosis.collectors.parse_prometheus_text`), so the
master can scrape itself with the same code path it uses for the
tpu_timer daemons — one scrape covers the whole job.
"""

from typing import Dict, List, Optional

from dlrover_tpu.observability.registry import (
    Histogram,
    MetricsRegistry,
    default_registry,
)

# Precomputed per-histogram quantile gauges: (suffix, q). Consumers
# (dashboard panels, the autoscaler's latency checks) read a gauge
# instead of re-deriving quantiles from cumulative buckets client-side.
_QUANTILE_GAUGES = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in labels.items()
        )
        return f"{name}{{{body}}} {value:.10g}"
    return f"{name} {value:.10g}"


def render_registry(registry: Optional[MetricsRegistry] = None) -> str:
    """Exposition for every family in the registry (# HELP/# TYPE)."""
    registry = registry or default_registry()
    lines: List[str] = []
    for family in sorted(registry.families(), key=lambda f: f.name):
        if family.help:
            lines.append(f"# HELP {family.name} {family.help}")
        lines.append(f"# TYPE {family.name} {family.kind}")
        for name, labels, value in family.samples():
            lines.append(_format_sample(name, labels, value))
        if isinstance(family, Histogram):
            lines.extend(_quantile_lines(family))
    return "\n".join(lines) + ("\n" if lines else "")


def _quantile_lines(family: Histogram) -> List[str]:
    """``<name>_p50/_p95/_p99`` gauges per labelled child, computed at
    scrape time from the cumulative buckets."""
    children = sorted(
        {
            tuple(sorted(labels.items()))
            for name, labels, _v in family.samples()
            if name == f"{family.name}_count"
        }
    )
    lines: List[str] = []
    for suffix, q in _QUANTILE_GAUGES:
        emitted_type = False
        for child in children:
            labels = dict(child)
            value = family.quantile(q, **labels)
            if value is None:
                continue
            if not emitted_type:
                lines.append(f"# TYPE {family.name}_{suffix} gauge")
                emitted_type = True
            lines.append(
                _format_sample(f"{family.name}_{suffix}", labels, value)
            )
    return lines


def render_perf(perf_monitor) -> str:
    """Live job-level metrics computed at scrape time: goodput changes
    with the wall clock even without new reports, so these are rendered
    fresh rather than cached in the registry."""
    lines = [
        "# TYPE dlrover_global_step gauge",
        _format_sample(
            "dlrover_global_step", {}, float(perf_monitor.global_step)
        ),
        "# TYPE dlrover_running_speed_steps_per_s gauge",
        _format_sample(
            "dlrover_running_speed_steps_per_s",
            {},
            perf_monitor.running_speed(),
        ),
        "# TYPE dlrover_goodput gauge",
        _format_sample("dlrover_goodput", {}, perf_monitor.goodput()),
        "# TYPE dlrover_goodput_phase_seconds gauge",
    ]
    for phase, secs in sorted(perf_monitor.phase_breakdown().items()):
        lines.append(
            _format_sample(
                "dlrover_goodput_phase_seconds", {"name": phase}, secs
            )
        )
    return "\n".join(lines) + "\n"


def render_job_context(context) -> str:
    """JobMetricContext job-level aggregates: the latest value per
    (node, metric) plus per-metric means over reporting nodes."""
    if context is None:
        return ""
    summary = context.summary()
    if not summary:
        return ""
    lines = [
        "# TYPE dlrover_job_node_metric gauge",
    ]
    keys = set()
    for node_id, metrics in sorted(summary.items()):
        for key, value in sorted(metrics.items()):
            if key != "unreachable_scrapes":
                keys.add(key)
            lines.append(
                _format_sample(
                    "dlrover_job_node_metric",
                    {"name": f"{node_id}:{key}"},
                    value,
                )
            )
    lines.append("# TYPE dlrover_job_metric_mean gauge")
    for key in sorted(keys):
        mean = context.job_gauge_mean(key)
        if mean is not None:
            lines.append(
                _format_sample(
                    "dlrover_job_metric_mean", {"name": key}, mean
                )
            )
    return "\n".join(lines) + "\n"


def master_metrics_text(
    perf_monitor=None,
    metric_context=None,
    registry: Optional[MetricsRegistry] = None,
) -> str:
    """The full master /metrics body: registry + live perf + job
    aggregates."""
    parts = [render_registry(registry)]
    if perf_monitor is not None:
        parts.append(render_perf(perf_monitor))
    if metric_context is not None:
        parts.append(render_job_context(metric_context))
    return "".join(p for p in parts if p)
