"""Process-wide, thread-safe metrics registry.

Parity: the reference exposes its counters through whatever Prometheus
client the Python process happens to carry; this repo vendors the tiny
subset it needs (counter/gauge/histogram families with labels, text
exposition via :mod:`dlrover_tpu.observability.prom`) so the master,
agent, exporters, and flash_ckpt can all report into ONE registry with
zero third-party deps, and one scrape of the master covers the job.

Registration is idempotent: asking for an existing family name returns
the existing collector (modules register independently without import
order mattering), but re-registering under a different metric type is a
programming error and raises.
"""

import threading
from typing import Dict, Iterable, List, Optional, Tuple

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


class _Family:
    """Base: a named metric with labelled children."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], float] = {}
        if not self.labelnames:
            # A label-less family exposes its zero immediately: on a
            # scrape, "0 drops" and "metric missing" must not look the
            # same.
            self._children[()] = 0.0

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """[(suffix-less name, labels, value)] for exposition."""
        with self._lock:
            return [
                (self.name, dict(zip(self.labelnames, key)), value)
                for key, value in sorted(self._children.items())
            ]


class Counter(_Family):
    """Monotonically increasing counter."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._children.get(key, 0.0)


class Gauge(_Family):
    """Set-to-current-value metric."""

    kind = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._children.get(key, 0.0)


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str,
        labelnames: Tuple[str, ...],
        buckets: Tuple[float, ...] = _DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(buckets))
        # child -> [bucket counts..., +Inf count, sum]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}
        if not self.labelnames:
            self._hist[()] = [0.0] * (len(self.buckets) + 2)

    def observe(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            state = self._hist.get(key)
            if state is None:
                state = [0.0] * (len(self.buckets) + 2)
                self._hist[key] = state
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    state[i] += 1
            state[len(self.buckets)] += 1  # +Inf / count
            state[len(self.buckets) + 1] += value  # sum

    def count(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._hist.get(key)
            return state[len(self.buckets)] if state else 0.0

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-quantile (0..1) from the cumulative buckets —
        Prometheus ``histogram_quantile`` semantics (linear
        interpolation inside the target bucket), precomputed server-
        side so scrapers need no quantile math. None with no samples;
        observations beyond the last finite bucket clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        key = self._key(labels)
        with self._lock:
            state = self._hist.get(key)
            if state is None:
                return None
            counts = list(state[: len(self.buckets)])
            total = state[len(self.buckets)]
        if total <= 0:
            return None
        target = q * total
        prev_bound = 0.0
        prev_count = 0.0
        for bound, cum in zip(self.buckets, counts):
            if cum >= target:
                in_bucket = cum - prev_count
                if in_bucket <= 0:
                    return bound
                frac = (target - prev_count) / in_bucket
                return prev_bound + frac * (bound - prev_bound)
            prev_bound = bound
            prev_count = cum
        return self.buckets[-1] if self.buckets else None

    def sum(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._hist.get(key)
            return state[len(self.buckets) + 1] if state else 0.0

    def samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        out: List[Tuple[str, Dict[str, str], float]] = []
        with self._lock:
            for key, state in sorted(self._hist.items()):
                base = dict(zip(self.labelnames, key))
                for i, bound in enumerate(self.buckets):
                    labels = dict(base)
                    labels["le"] = repr(bound)
                    out.append((f"{self.name}_bucket", labels, state[i]))
                labels = dict(base)
                labels["le"] = "+Inf"
                out.append(
                    (f"{self.name}_bucket", labels, state[len(self.buckets)])
                )
                out.append(
                    (f"{self.name}_count", base, state[len(self.buckets)])
                )
                out.append(
                    (
                        f"{self.name}_sum",
                        dict(base),
                        state[len(self.buckets) + 1],
                    )
                )
        return out


class MetricsRegistry:
    """Family registry; one per process via :func:`default_registry`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _register(self, cls, name, help_text, labelnames, **kwargs):
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}"
                    )
                # Mismatched declarations must fail HERE, at the
                # conflicting registration — not later as a label
                # ValueError on some unrelated update path.
                if tuple(labelnames) != existing.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with "
                        f"labels {existing.labelnames}, not "
                        f"{tuple(labelnames)}"
                    )
                buckets = kwargs.get("buckets")
                if (
                    buckets is not None
                    and tuple(sorted(buckets)) != existing.buckets
                ):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {existing.buckets}"
                    )
                return existing
            if "buckets" in kwargs and kwargs["buckets"] is None:
                kwargs["buckets"] = _DEFAULT_BUCKETS
            family = cls(name, help_text, tuple(labelnames), **kwargs)
            self._families[name] = family
            return family

    def counter(
        self, name: str, help_text: str = "", labelnames: Iterable[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help_text, labelnames)

    def gauge(
        self, name: str, help_text: str = "", labelnames: Iterable[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help_text, labelnames)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        labelnames: Iterable[str] = (),
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> Histogram:
        """``buckets=None`` means "no opinion": accept an existing
        family's buckets, or the defaults when creating — so modules
        can fetch a histogram without knowing who declared it."""
        return self._register(
            Histogram, name, help_text, labelnames, buckets=buckets
        )

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default


def reset_default_registry():
    """Tests only: drop every family registered so far."""
    global _default
    with _default_lock:
        _default = None
