"""Per-step flight recorder: the last N steps of every worker survive it.

A fixed-size ring of per-step timing records — data-wait, step wall
time, checkpoint-blocked time, the rendezvous round — kept entirely on
the host side of the training loop (plain Python floats; this module
must never import jax, and recording is a deque append under a lock, so
nothing is added inside the jitted step). On crash, SIGTERM, or
interpreter exit the ring is dumped as JSON to a per-worker path the
agent knows how to find, so diagnosis can read exactly what the dead
worker's last steps looked like (the postmortem the paper's goodput
story needs: WAS it data-starved / ckpt-blocked just before it died?).

Worker side (wired by ``trainer/runtime.init_distributed``)::

    rec = flight_recorder.active_recorder()
    rec.record_step(step, step_time_s=dt, data_wait_s=w)

Agent side (``agent/training.py`` on worker death)::

    dumps = flight_recorder.collect_dumps(node_rank, range(nproc))
"""

import json
import os
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

from dlrover_tpu.common.log import logger

FLIGHT_DIR_ENV = "DLROVER_TPU_FLIGHT_DIR"
SCHEMA_VERSION = 1


def flight_dir() -> str:
    return os.getenv(
        FLIGHT_DIR_ENV,
        os.path.join(tempfile.gettempdir(), "dlrover_tpu_flight"),
    )


def dump_path(node_rank: int, local_rank: int) -> str:
    """The agent reconstructs this same path to fetch a dead worker's
    ring — keep it a pure function of (node_rank, local_rank)."""
    return os.path.join(
        flight_dir(), f"flight_node{node_rank}_rank{local_rank}.json"
    )


class FlightRecorder:
    """Bounded ring of step records + crash-dump plumbing."""

    def __init__(
        self,
        capacity: int = 512,
        meta: Optional[Dict] = None,
        registry=None,
    ):
        self._lock = threading.Lock()
        self._ring: "deque[Dict]" = deque(maxlen=capacity)
        self.meta = dict(meta or {})
        self._dump_target: Optional[str] = None
        self._installed_signals: Dict[int, object] = {}
        if registry is None:
            from dlrover_tpu.observability.registry import default_registry

            registry = default_registry()
        self._step_hist = registry.histogram(
            "flight_step_seconds",
            "per-step wall time recorded by the flight recorder",
        )
        self._steps_total = registry.counter(
            "flight_steps_recorded_total",
            "steps recorded by the flight recorder",
        )

    # ---- recording (hot path: host Python between steps) ------------------

    def record_step(
        self,
        step: int,
        step_time_s: float = 0.0,
        data_wait_s: float = 0.0,
        ckpt_block_s: float = 0.0,
        rdzv_round: int = -1,
        **extras,
    ):
        record = {
            "step": int(step),
            "ts": time.time(),
            "step_time_s": float(step_time_s),
            "data_wait_s": float(data_wait_s),
            "ckpt_block_s": float(ckpt_block_s),
            "rdzv_round": int(rdzv_round),
        }
        if extras:
            record.update(extras)
        with self._lock:
            self._ring.append(record)
        self._step_hist.observe(record["step_time_s"])
        self._steps_total.inc()

    def annotate(self, event: str, **fields):
        """Append a non-step event record (checkpoint restore, re-mesh,
        ...) to the ring. It rides the same crash dump / timeline merge
        as step records but touches no step metrics."""
        record = {"event": str(event), "ts": time.time()}
        record.update(fields)
        with self._lock:
            self._ring.append(record)

    # ---- snapshots / dumps -------------------------------------------------

    def snapshot(self, last_n: Optional[int] = None) -> Dict:
        # Bounded acquire: dump() runs inside signal handlers on the
        # MAIN thread, which may have interrupted record_step while it
        # held this (non-reentrant) lock — a blocking acquire would
        # deadlock the dying worker. On timeout the interrupted frame
        # is frozen until we return, so reading without the lock is
        # safe from it; other threads racing an append at worst cost
        # one retry of the list copy.
        acquired = self._lock.acquire(timeout=1.0)
        try:
            for _ in range(3):
                try:
                    steps = list(self._ring)
                    break
                except RuntimeError:  # deque mutated during iteration
                    continue
            else:
                steps = []
        finally:
            if acquired:
                self._lock.release()
        if last_n is not None:
            steps = steps[-last_n:]
        return {
            "schema": SCHEMA_VERSION,
            "meta": dict(self.meta),
            "dumped_at": time.time(),
            "pid": os.getpid(),
            "steps": steps,
        }

    def dump(self, path: Optional[str] = None,
             with_stacks: bool = False) -> Optional[str]:
        """Atomic JSON dump (tmp + rename: the agent may read while the
        worker is dying). Returns the path, or None on failure — the
        dump runs on crash paths and must never raise.

        ``with_stacks`` adds every thread's current frames (the
        on-demand SIGUSR1 diagnostics payload)."""
        path = path or self._dump_target
        if not path:
            return None
        try:
            snapshot = self.snapshot()
            if with_stacks:
                from dlrover_tpu.observability.hang_watchdog import (
                    dump_all_stacks,
                )

                snapshot["stacks"] = dump_all_stacks()
                snapshot["on_demand"] = True
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f)
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 - crash path
            return None

    # ---- crash hooks -------------------------------------------------------

    def install_crash_dump(
        self,
        path: str,
        signals: Iterable[int] = (signal.SIGTERM,),
    ):
        """Dump the ring when the process dies abnormally: on the given
        signals (chaining any previous handler), on an unhandled
        exception, and at interpreter exit (covers clean exits too —
        a fresh dump file is never wrong)."""
        import atexit

        self._dump_target = path

        for signum in signals:
            try:
                prev = signal.signal(signum, self._make_handler(signum))
                self._installed_signals[signum] = prev
            except (ValueError, OSError):  # non-main thread / weird env
                pass

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.dump()
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook
        atexit.register(self.dump)

    def on_demand_path(self) -> Optional[str]:
        """Sibling of the crash-dump path: the exit/crash dump must not
        clobber an operator's on-demand capture (atexit re-dumps the
        ring on every clean exit)."""
        if not self._dump_target:
            return None
        base, ext = os.path.splitext(self._dump_target)
        return f"{base}.ondemand{ext or '.json'}"

    def install_on_demand_dump(self, signum: Optional[int] = None):
        """SIGUSR1 = live diagnostics: dump the ring PLUS all-thread
        stacks to an agent-collectable sibling path and KEEP RUNNING —
        an operator (or the agent, suspecting a wedge) can interrogate
        a worker without killing it. Previous crash/SIGTERM behavior
        is untouched; the handler never re-raises the signal."""
        if signum is None:
            signum = getattr(signal, "SIGUSR1", None)
            if signum is None:  # platform without SIGUSR1
                return

        def handler(sig, frame):
            # No logging in the handler: the signal may have interrupted
            # a frame holding the logging module's non-reentrant handler
            # lock — logger.info here would deadlock the very process
            # this dump is meant to leave running. The dump path is a
            # pure function of (node_rank, local_rank); operators know
            # where to look.
            self.dump(path=self.on_demand_path(), with_stacks=True)

        try:
            signal.signal(signum, handler)
        except (ValueError, OSError):  # non-main thread / weird env
            pass

    def _make_handler(self, signum):
        def handler(sig, frame):
            self.dump()
            prev = self._installed_signals.get(signum)
            if callable(prev):
                prev(sig, frame)
                return
            if prev == signal.SIG_IGN:
                # The process had deliberately ignored this signal
                # (e.g. a supervisor-managed drain); keep ignoring it.
                return
            # Default disposition: re-deliver so the exit code still
            # says "killed by signal" (the agent's monitor reads it).
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        return handler


# ---------------------------------------------------------------------------
# Process-wide recorder (wired by trainer/runtime.init_distributed)
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def install_recorder(
    node_rank: int,
    local_rank: int,
    capacity: int = 512,
    meta: Optional[Dict] = None,
) -> FlightRecorder:
    """Create the process recorder and arm its crash dump; idempotent."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            return _recorder
        full_meta = {"node_rank": node_rank, "local_rank": local_rank}
        full_meta.update(meta or {})
        rec = FlightRecorder(capacity=capacity, meta=full_meta)
        rec.install_crash_dump(dump_path(node_rank, local_rank))
        rec.install_on_demand_dump()
        _recorder = rec
        logger.info(
            "flight recorder armed -> %s",
            dump_path(node_rank, local_rank),
        )
        return rec


def active_recorder() -> Optional[FlightRecorder]:
    """The process recorder IF one was installed, else None — callers on
    the training path must not create one as a side effect."""
    return _recorder


def reset_recorder():
    """Tests only."""
    global _recorder
    with _recorder_lock:
        _recorder = None


# ---------------------------------------------------------------------------
# Agent-side retrieval
# ---------------------------------------------------------------------------


def load_dump(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "steps" not in data:
        return None
    return data


def collect_dumps(
    node_rank: int,
    local_ranks: Iterable[int],
    max_age_s: Optional[float] = None,
    last_n: Optional[int] = None,
) -> Dict[int, Dict]:
    """The agent's fetch after worker death: {local_rank: dump}. Stale
    files from a previous incarnation are skipped via ``max_age_s``."""
    out: Dict[int, Dict] = {}
    now = time.time()
    for lr in local_ranks:
        path = dump_path(node_rank, lr)
        if max_age_s is not None:
            try:
                if now - os.path.getmtime(path) > max_age_s:
                    continue
            except OSError:
                continue
        dump = load_dump(path)
        if dump is None:
            continue
        if last_n is not None:
            dump = dict(dump)
            dump["steps"] = dump["steps"][-last_n:]
        out[lr] = dump
    return out


def last_steps(dump: Dict, n: int = 16) -> List[Dict]:
    return list(dump.get("steps", []))[-n:]
