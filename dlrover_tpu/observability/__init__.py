"""Unified observability hub.

One subsystem closes the loop between the raw signals this repo already
emits (training_event JSONL spans, tpu_timer chrome traces, the master's
goodput phase ledger) and the two artifacts an operator actually wants
from a job: ONE Prometheus scrape (`/metrics` on the master dashboard)
and ONE merged timeline (``tools/merge_timeline.py``).

- :mod:`registry` — process-wide, thread-safe metrics registry
  (counters/gauges/histograms with labels) every component reports into.
- :mod:`prom` — Prometheus text exposition for the registry plus the
  master's live job-level metrics (goodput, phase seconds, speed).
- :mod:`flight_recorder` — fixed-size ring of per-step timing records
  kept off the jitted path, dumped as JSON on crash/SIGTERM so the last
  N steps of a dead worker survive for diagnosis.
- :mod:`trace_merge` — clock-offset-aligned fusion of all signal
  sources into a single chrome-trace/Perfetto JSON per job.
- :mod:`tracing` — cross-process distributed tracing: spans with
  trace/span/parent ids, context carried on the RPC envelopes, JSONL
  sinks, and the master-side trace aggregator behind ``/api/traces``.
- :mod:`hang_watchdog` — worker-side rolling-deadline hang detection
  with all-thread ``sys._current_frames()`` stack dumps the agent
  collects.
"""

from dlrover_tpu.observability.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    reset_default_registry,
)
