"""Device mesh construction for elastic TPU training.

Axis convention (slowest-varying first; ``tp`` innermost so tensor-parallel
collectives ride the fastest ICI links):

- ``dcn``: the SLICE axis — data parallel across TPU slices over DCN
  (data-center network). Only batch rides it; every other axis stays
  inside a slice so its collectives ride ICI. Group-major rendezvous
  rank order (rdzv_manager._order_world) makes each node group's hosts
  contiguous, which is exactly the layout that maps groups onto dcn
  rows here.
- ``dp``: data parallel / FSDP within a slice (params' embed dim
  sharded here, ZeRO-style)
- ``ep``: expert parallel; also an extra batch axis outside MoE layers
- ``pp``: pipeline stages
- ``sp``: sequence/context parallel (ring attention)
- ``tp``: tensor parallel (heads / mlp / vocab)

The reference's ``node_unit`` rendezvous concept (rdzv_manager.py:159-181)
becomes :func:`legal_mesh_shapes`: on a TPU slice the mesh shape is
physical, so losing a host means re-meshing to the largest feasible shape.
"""

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

AXIS_NAMES = ("dcn", "dp", "ep", "pp", "sp", "tp")

# Batch is sharded over the slice axis plus both pure-data and expert
# axes.
BATCH_AXES = ("dcn", "dp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each mesh axis; product must equal the device count."""

    dp: int = 1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1
    dcn: int = 1  # slices (inter-slice data parallel over DCN)

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dcn, self.dp, self.ep, self.pp, self.sp, self.tp)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @property
    def data_parallel_size(self) -> int:
        return self.dcn * self.dp * self.ep

    @property
    def devices_per_slice(self) -> int:
        return self.num_devices // self.dcn

    def describe(self) -> str:
        return "x".join(
            f"{n}={s}" for n, s in zip(AXIS_NAMES, self.shape) if s > 1
        ) or "single"


def build_mesh(config: MeshConfig, devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` with the canonical axis order.

    On real TPU hardware, uses ``mesh_utils.create_device_mesh`` (single
    slice) or ``create_hybrid_device_mesh`` (dcn > 1: per-slice ICI
    meshes glued along the slice axis) so the logical mesh respects the
    physical topology; on CPU/virtual devices falls back to a plain
    reshape — devices arriving in group-major rank order land one node
    group per dcn row.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if config.num_devices != n:
        raise ValueError(
            f"mesh {config.shape} needs {config.num_devices} devices, "
            f"have {n}"
        )
    if devices and devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        ici_shape = (1,) + config.shape[1:]
        if config.dcn > 1:
            dcn_shape = (config.dcn,) + (1,) * (len(config.shape) - 1)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        else:
            dev_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices
            )
    else:
        dev_array = np.asarray(devices).reshape(config.shape)
    return Mesh(dev_array, AXIS_NAMES)


def factorize_devices(
    n: int,
    max_tp: int = 8,
    max_pp: int = 8,
    want_sp: bool = True,
    want_ep: bool = True,
) -> MeshConfig:
    """Pick a reasonable axis decomposition for ``n`` devices.

    Spreads factors of two round-robin over (tp, pp, sp, ep) — tp first
    each round so it grows fastest up to ``max_tp`` — and sends the
    remainder (including any odd factor) to dp. Note: configs with pp>1
    need ``trainer.pipeline.pipelined_forward``; pass ``max_pp=1`` when
    targeting the plain forward path.

    factorize_devices(8)  -> tp=2 pp=2 sp=2
    factorize_devices(64) -> tp=4 pp=4 sp=2 ep=2
    """
    sizes = {"tp": 1, "pp": 1, "sp": 1, "ep": 1}
    caps = {
        "tp": max_tp,
        "pp": max_pp,
        "sp": 2 if want_sp else 1,
        "ep": 2 if want_ep else 1,
    }
    remaining = n
    progress = True
    while remaining % 2 == 0 and remaining > 1 and progress:
        progress = False
        for ax in ("tp", "pp", "sp", "ep"):
            if remaining % 2 == 0 and remaining > 1 and (
                sizes[ax] * 2 <= caps[ax]
            ):
                sizes[ax] *= 2
                remaining //= 2
                progress = True
    return MeshConfig(dp=remaining, **sizes)


def legal_mesh_shapes(
    num_hosts: int, chips_per_host: int = 4
) -> List[Tuple[int, int]]:
    """Feasible (hosts, chips) configurations at or below ``num_hosts``.

    TPU slices only come in certain shapes (powers of two hosts for v5e
    pods); the elastic re-mesh path picks the largest entry still
    satisfiable after a host loss — the analogue of the reference's
    ``node_unit`` rounding (servicer.py:708).
    """
    shapes = []
    h = 1
    while h <= num_hosts:
        shapes.append((h, h * chips_per_host))
        h *= 2
    return shapes


def largest_legal_hosts(available_hosts: int, chips_per_host: int = 4) -> int:
    """Largest power-of-two host count <= available (0 if none)."""
    shapes = legal_mesh_shapes(available_hosts, chips_per_host)
    return shapes[-1][0] if shapes else 0


def mesh_config_for_slices(
    num_devices: int,
    num_slices: int = 1,
    max_tp: int = 8,
    max_pp: int = 1,
    want_sp: bool = False,
    want_ep: bool = False,
) -> MeshConfig:
    """Multi-slice mesh recipe: data parallel across slices over DCN
    (``dcn=num_slices``), everything else factorized INSIDE a slice so
    its collectives ride ICI. ``num_slices`` usually comes from
    ``DistributedContext.num_slices`` (node groups / node_unit).
    """
    if num_devices % max(num_slices, 1):
        raise ValueError(
            f"{num_devices} devices not divisible by {num_slices} slices"
        )
    per_slice = num_devices // max(num_slices, 1)
    intra = factorize_devices(
        per_slice, max_tp=max_tp, max_pp=max_pp,
        want_sp=want_sp, want_ep=want_ep,
    )
    return dataclasses.replace(intra, dcn=max(num_slices, 1))
