"""Parallelism layer: device meshes, logical-axis sharding rules, and the
dp/fsdp/tp/pp/sp/ep strategy toolkit for JAX on TPU slices.

The reference (Mu-L/dlrover) delegates parallelism to torch frameworks and
is only parallelism-*aware* (SURVEY.md section 2.9). The TPU rebuild makes
parallelism first-class: a single ``jax.sharding.Mesh`` with axes
``(dp, ep, pp, sp, tp)`` and GSPMD sharding propagation, with shard_map
islands only where manual collectives beat the compiler (ring attention).
"""

from dlrover_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    build_mesh,
    factorize_devices,
    legal_mesh_shapes,
)
from dlrover_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    current_mesh,
    logical_to_spec,
    spec_tree,
    with_logical_constraint,
)
