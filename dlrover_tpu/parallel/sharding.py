"""Logical-axis sharding rules (GSPMD style).

Model code annotates tensors with *logical* axis names; a rule table maps
those to mesh axes. Swapping parallelism strategy = swapping the rule
table, not the model. This replaces the reference's per-framework
parallelism awareness (Megatron tp/pp ranks, FSDP shard counts —
SURVEY.md section 2.9) with a single declarative layer.
"""

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]

# logical axis -> mesh axis (or tuple of mesh axes)
# Batch leads with the slice (dcn) axis: inter-slice traffic is then
# only the data-parallel gradient allreduce; FSDP (embed -> dp), tp, sp
# and ep all stay intra-slice on ICI.
DEFAULT_RULES: Tuple[Tuple[str, MeshAxes], ...] = (
    ("batch", ("dcn", "dp", "ep")),
    ("seq", "sp"),
    ("embed", "dp"),       # FSDP: params' embed dim sharded over dp (ZeRO)
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("head_dim", None),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("stage", "pp"),
    ("layer", None),
    ("expert", "ep"),
    ("capacity", None),
    ("norm", None),
    ("micro", None),
)


def rules_dict(
    rules: Sequence[Tuple[str, MeshAxes]] = DEFAULT_RULES,
) -> Dict[str, MeshAxes]:
    return dict(rules)


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Sequence[Tuple[str, MeshAxes]] = DEFAULT_RULES,
) -> P:
    """("batch","seq","embed") -> PartitionSpec(("dp","ep"), "sp", "dp")."""
    table = rules_dict(rules)
    out = []
    used = set()
    for ax in logical_axes:
        mesh_ax = table.get(ax) if ax is not None else None
        # A mesh axis may appear at most once in a spec; later logical
        # axes that map to an already-used mesh axis stay unsharded.
        if mesh_ax is None:
            out.append(None)
            continue
        flat = (mesh_ax,) if isinstance(mesh_ax, str) else tuple(mesh_ax)
        free = tuple(a for a in flat if a not in used)
        if not free:
            out.append(None)
            continue
        used.update(free)
        out.append(free[0] if len(free) == 1 else free)
    return P(*out)


def spec_tree(logical_tree, rules=DEFAULT_RULES):
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    is_axes = lambda x: isinstance(x, tuple) and all(  # noqa: E731
        a is None or isinstance(a, str) for a in x
    )
    return jax.tree_util.tree_map(
        lambda axes: logical_to_spec(axes, rules), logical_tree,
        is_leaf=is_axes,
    )


def sharding_tree(specs, mesh: Mesh):
    """PartitionSpec pytree -> NamedSharding pytree."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def with_logical_constraint(
    x, logical_axes: Sequence[Optional[str]], rules=DEFAULT_RULES
):
    """Annotate an intermediate with a sharding constraint by logical axes.

    No-op outside a mesh context (single-device eager/test paths). Model
    code must be *traced* inside ``with mesh:`` for constraints to apply —
    the train-step factory wraps its jitted callables accordingly.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    spec = logical_to_spec(logical_axes, rules)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec)
    )


def current_mesh() -> Optional[Mesh]:
    """The mesh from the innermost ``with mesh:`` context, if any."""
    try:
        from jax._src import mesh as mesh_lib

        env_mesh = mesh_lib.thread_resources.env.physical_mesh
        if env_mesh.empty:
            return None
        return env_mesh
    except Exception:
        return None
