"""Run the ElasticJob reconciler in-cluster:
``python -m dlrover_tpu.operator --namespace default``."""

import argparse
import signal
import threading

from dlrover_tpu.operator.reconciler import ElasticJobReconciler


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="dlrover-tpu elasticjob operator")
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--resync_interval", type=float, default=30.0)
    ns = ap.parse_args(argv)

    reconciler = ElasticJobReconciler(
        namespace=ns.namespace, resync_interval_s=ns.resync_interval
    )
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    reconciler.start()
    reconciler.resync()
    stop.wait()
    reconciler.stop()
    reconciler.join()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
