"""ElasticJob reconciler: the operator-side control loop.

Parity: reference go/elasticjob/pkg/controllers/elasticjob_controller.go:
85-374 + master.go:56-181 — watches ElasticJob custom resources and, for
each, creates the job-master pod and its service, tracks replica/job
phases into the CR status, and garbage-collects everything when the CR
is deleted. The reference implements this in Go with controller-runtime;
here it is a small Python watch loop over the same narrow K8sApi surface
the scaler/watcher use, testable against FakeK8sApi.

ElasticJob spec shape (deploy/elasticjob_crd.yaml):

    apiVersion: elastic.iml.github.io/v1alpha1
    kind: ElasticJob
    metadata: {name: my-job}
    spec:
      image: ghcr.io/example/dlrover-tpu:latest
      nodeUnit: 2                 # hosts per TPU slice block
      masterResource: {cpu: 2, memory_mb: 4096}
      replicaSpecs:
        worker:
          replicas: 8
          resource: {tpu_chips: 4, tpu_type: tpu-v5e, memory_mb: 16384}
          topology: 4x4

Run in-cluster: ``python -m dlrover_tpu.operator --namespace default``.
"""

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.master.scheduler.k8s_client import (
    ELASTICJOB_GROUP,
    ELASTICJOB_PLURAL,
    ELASTICJOB_VERSION,
    K8sApi,
    get_k8s_api,
)


class JobPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


MASTER_PORT = 50001


def master_name(job_name: str) -> str:
    return f"{job_name}-dlrover-master"


def master_pod_manifest(job: Dict, namespace: str) -> Dict:
    """The job-master pod for an ElasticJob (reference master.go:56-181
    NewMasterTemplateToJob)."""
    name = job["metadata"]["name"]
    spec = job.get("spec", {})
    image = spec.get("image", "dlrover-tpu:latest")
    res = spec.get("masterResource", {})
    replicas = (
        spec.get("replicaSpecs", {}).get("worker", {}).get("replicas", 1)
    )
    args = [
        "python",
        "-m",
        "dlrover_tpu.master.main",
        "--platform",
        "gke_tpu",
        "--job_name",
        name,
        "--namespace",
        namespace,
        "--node_num",
        str(replicas),
        "--port",
        str(MASTER_PORT),
    ]
    node_unit = spec.get("nodeUnit", 0)
    if node_unit:
        args += ["--node_unit", str(node_unit)]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": master_name(name),
            "labels": {
                "job-name": name,
                "role": "dlrover-master",
            },
            "ownerReferences": [owner_reference(job)],
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [
                {
                    "name": "master",
                    "image": image,
                    "command": args,
                    "ports": [{"containerPort": MASTER_PORT}],
                    "resources": {
                        "limits": {
                            "cpu": str(res.get("cpu", 2)),
                            "memory": f"{res.get('memory_mb', 4096)}Mi",
                        }
                    },
                }
            ],
        },
    }


def master_service_manifest(job: Dict) -> Dict:
    name = job["metadata"]["name"]
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": master_name(name),
            "labels": {"job-name": name},
            "ownerReferences": [owner_reference(job)],
        },
        "spec": {
            "selector": {"job-name": name, "role": "dlrover-master"},
            "ports": [{"port": MASTER_PORT, "targetPort": MASTER_PORT}],
        },
    }


def owner_reference(job: Dict) -> Dict:
    """Children carry an owner ref so cluster GC also covers them when
    the controller itself is down (reference controller SetControllerReference)."""
    return {
        "apiVersion": f"{ELASTICJOB_GROUP}/{ELASTICJOB_VERSION}",
        "kind": "ElasticJob",
        "name": job["metadata"]["name"],
        "uid": job["metadata"].get("uid", ""),
        "controller": True,
        "blockOwnerDeletion": True,
    }


class ElasticJobReconciler:
    def __init__(
        self,
        namespace: str = "default",
        api: Optional[K8sApi] = None,
        resync_interval_s: float = 30.0,
    ):
        self._namespace = namespace
        self._api = api or get_k8s_api()
        self._resync_interval_s = resync_interval_s
        self._stopped = threading.Event()
        self._threads = []

    # ---- control loop ------------------------------------------------------

    def start(self):
        for target in (self._watch_loop, self._resync_loop):
            t = threading.Thread(
                target=target, name=target.__name__, daemon=True
            )
            t.start()
            self._threads.append(t)
        logger.info(
            "elasticjob reconciler started (namespace=%s)", self._namespace
        )

    def stop(self):
        self._stopped.set()

    def join(self, timeout: float = 5.0):
        for t in self._threads:
            t.join(timeout)

    def _watch_loop(self):
        while not self._stopped.is_set():
            try:
                for event in self._api.watch_custom_objects(
                    self._namespace, ELASTICJOB_PLURAL
                ):
                    if self._stopped.is_set():
                        return
                    job = event.get("object") or {}
                    if event.get("type") == "DELETED":
                        self.gc_job(job["metadata"]["name"])
                    else:
                        self.reconcile(job)
            except Exception:
                logger.exception("elasticjob watch failed; retrying")
                time.sleep(1.0)

    def _resync_loop(self):
        """Level-triggered safety net: periodic full reconcile so a
        missed watch event cannot leave a job unmanaged."""
        while not self._stopped.wait(self._resync_interval_s):
            self.resync()

    def resync(self):
        for job in self._api.list_custom_objects(
            self._namespace, ELASTICJOB_PLURAL
        ):
            try:
                self.reconcile(job)
            except Exception:
                logger.exception(
                    "reconcile of %s failed", job["metadata"]["name"]
                )

    # ---- reconcile ---------------------------------------------------------

    def reconcile(self, job: Dict):
        name = job["metadata"]["name"]
        pods = {
            p["metadata"]["name"]: p
            for p in self._api.list_pods(
                self._namespace, f"job-name={name}"
            )
            if p.get("metadata", {}).get("labels", {}).get("job-name")
            == name
        }
        m_name = master_name(name)
        if m_name not in pods:
            logger.info("creating master pod for job %s", name)
            if not self._api.create_pod(
                self._namespace, master_pod_manifest(job, self._namespace)
            ):
                logger.error("master pod create failed for %s", name)
            pods = {
                p["metadata"]["name"]: p
                for p in self._api.list_pods(
                    self._namespace, f"job-name={name}"
                )
            }
        # The service is reconciled INDEPENDENTLY of the pod: a deleted
        # or failed-to-create service must be recreated on the next
        # pass, or workers can never resolve the master address.
        if self._api.get_service(self._namespace, m_name) is None:
            logger.info("creating master service for job %s", name)
            if not self._api.create_service(
                self._namespace, master_service_manifest(job)
            ):
                logger.error("master service create failed for %s", name)
        self._update_status(job, pods)

    def _update_status(self, job: Dict, pods: Dict[str, Dict]):
        name = job["metadata"]["name"]
        m_pod = pods.get(master_name(name))
        counts: Dict[str, Dict[str, int]] = {}
        for pod in pods.values():
            labels = pod.get("metadata", {}).get("labels", {})
            if labels.get("role") == "dlrover-master":
                continue
            role = labels.get("node-type", "worker")
            phase = pod.get("status", {}).get("phase", "Pending").lower()
            counts.setdefault(role, {})
            counts[role][phase] = counts[role].get(phase, 0) + 1

        phase = JobPhase.PENDING
        if m_pod is not None:
            master_phase = m_pod.get("status", {}).get("phase", "Pending")
            phase = {
                "Pending": JobPhase.PENDING,
                "Running": JobPhase.RUNNING,
                "Succeeded": JobPhase.SUCCEEDED,
                "Failed": JobPhase.FAILED,
            }.get(master_phase, JobPhase.PENDING)

        status = {"phase": phase, "replicaStatuses": counts}
        if job.get("status") != status:
            self._api.patch_custom_object_status(
                self._namespace, ELASTICJOB_PLURAL, name, status
            )

    # ---- garbage collection ------------------------------------------------

    def gc_job(self, job_name: str):
        """Delete everything the job owns (reference controller
        handleDeletedJob); owner refs double-cover this when the cluster
        GC runs."""
        logger.info("garbage-collecting job %s", job_name)
        for pod in self._api.list_pods(
            self._namespace, f"job-name={job_name}"
        ):
            pod_name = pod.get("metadata", {}).get("name", "")
            labels = pod.get("metadata", {}).get("labels", {})
            if pod_name and labels.get("job-name") == job_name:
                self._api.delete_pod(self._namespace, pod_name)
        self._api.delete_service(self._namespace, master_name(job_name))
