"""Out-of-cluster job submission client.

Parity: reference dlrover/python/client/platform/ray/ray_job_submitter.py
:1-185 — the thin library users call from OUTSIDE the cluster to submit
a job and watch it. The reference submits to Ray's job server; here the
cluster entry is the token-authenticated HTTP submission service
(:mod:`dlrover_tpu.unified.submission`, typically run next to the
operator or on the head node).

Usage::

    from dlrover_tpu.client import JobSubmitter

    sub = JobSubmitter("head-node:8910", token="...")
    sub.submit({
        "job_name": "ppo",
        "roles": [{"name": "trainer", "entrypoint": "my.train",
                   "total": 4, "per_group": 2}],
    })
    final = sub.wait("ppo")          # -> "SUCCEEDED" | "FAILED"

The config dict is the same DLJobConfig JSON shape
``python -m dlrover_tpu.unified.driver job.json`` reads; dataclass
instances (DLJobConfig) are serialized automatically.
"""

import dataclasses
import json
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Union

TERMINAL_STAGES = ("SUCCEEDED", "FAILED")


class SubmitError(RuntimeError):
    pass


def _to_payload(config: Union[dict, object]) -> dict:
    if isinstance(config, dict):
        return config
    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    raise TypeError(
        f"config must be a dict or DLJobConfig, got {type(config)}"
    )


class JobSubmitter:
    """HTTP client for the submission service (see module doc)."""

    def __init__(self, address: str, token: str,
                 timeout: float = 30.0):
        if "://" not in address:
            address = f"http://{address}"
        self._base = address.rstrip("/")
        self._token = token
        self._timeout = timeout

    def _call(self, method: str, path: str,
              payload: Optional[dict] = None) -> dict:
        body = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            f"{self._base}{path}",
            data=body,
            method=method,
            headers={
                "X-Submit-Token": self._token,
                "Content-Type": "application/json",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except (ValueError, OSError):
                detail = ""
            raise SubmitError(
                f"{method} {path}: HTTP {e.code} {detail}".strip()
            ) from None
        except urllib.error.URLError as e:
            raise SubmitError(
                f"{method} {path}: {e.reason}"
            ) from None

    # ---- API ---------------------------------------------------------------

    def submit(self, config: Union[dict, object]) -> str:
        """Submit a job; returns its name (raises SubmitError on
        rejection — bad config, duplicate running job, bad token)."""
        rsp = self._call("POST", "/api/v1/jobs", _to_payload(config))
        return rsp["job_name"]

    def status(self, job_name: str) -> Dict[str, str]:
        """{"job_name", "stage", "error"} for one job."""
        return self._call("GET", f"/api/v1/jobs/{job_name}")

    def list_jobs(self) -> Dict[str, str]:
        return self._call("GET", "/api/v1/jobs")["jobs"]

    def stop(self, job_name: str) -> Dict[str, str]:
        return self._call("POST", f"/api/v1/jobs/{job_name}/stop")

    def wait(self, job_name: str, timeout: float = 600.0,
             poll_s: float = 1.0) -> str:
        """Poll until the job reaches a terminal stage; returns it."""
        deadline = time.time() + timeout
        while True:
            stage = self.status(job_name)["stage"]
            if stage in TERMINAL_STAGES:
                return stage
            if time.time() > deadline:
                raise TimeoutError(
                    f"job {job_name!r} still {stage} after {timeout}s"
                )
            time.sleep(poll_s)
