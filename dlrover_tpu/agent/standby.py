"""Warm-standby worker process.

Restart latency on a failure is dominated by interpreter + framework
import time (this container's sitecustomize imports jax at startup:
~4s measured — the torch analogue in the reference's world is similar).
A standby is a pre-spawned interpreter that has already paid that cost
and blocks on stdin until the agent ADOPTS it as the next worker
incarnation: the agent writes one JSON line carrying the final
environment and argv (rendezvous outcome, restart count — values that
do not exist when the standby is spawned), and the standby becomes the
worker via runpy in-process. No TPU/JAX client is created while waiting
— importing jax registers backends but initializes nothing, so the
standby never contends for the chip with the live worker.

Spawned by ElasticAgent when ``WorkerSpec.warm_standby`` is set (see
agent/training.py); exercised end-to-end by bench_e2e.py.
"""

import json
import os
import runpy
import sys


def wait_and_exec():
    line = sys.stdin.readline()
    if not line:
        # Agent closed stdin without adopting (job ended): exit clean.
        sys.exit(0)
    go = json.loads(line)
    os.environ.update(go["env"])
    sys.argv = list(go["argv"])
    if go.get("module"):
        runpy.run_module(go["module"], run_name="__main__", alter_sys=True)
    else:
        runpy.run_path(go["argv"][0], run_name="__main__")


if __name__ == "__main__":
    wait_and_exec()
