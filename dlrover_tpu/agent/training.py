"""The elastic agent: per-node supervisor of JAX worker processes.

Parity: reference dlrover/python/elastic_agent/torch/training.py
(ElasticTrainingAgent:648, _invoke_run:1247, _initialize_workers:1073).
Re-designed as a plain process supervisor: torchelastic's WorkerGroup
machinery is replaced by direct subprocess management, because on TPU a
re-mesh requires restarting worker *processes* anyway
(``jax.distributed`` cannot re-initialize in-process).

Run states per monitor tick:
- all workers exited 0     -> exit barrier, report success, done
- any worker failed        -> breakpoint-save signal, restart-or-raise
- membership change wanted -> graceful stop, new rendezvous, restart
- otherwise                -> heartbeat (executing piggy-backed diagnosis
                              actions), resource report
"""

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import (
    MasterRendezvousHandler,
    RendezvousEvictedError,
    RendezvousOutcome,
    RendezvousTimeoutError,
)
from dlrover_tpu.common.constants import (
    DiagnosisActionType,
    GoodputPhase,
    JobConstant,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.env_utils import worker_env
from dlrover_tpu.common.log import logger


class RunResult(Enum):
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    RELAUNCH = "relaunch"  # ask the cluster layer for a new node


@dataclass
class WorkerSpec:
    entrypoint: str  # path to the training script, or "-m module"
    args: List[str] = field(default_factory=list)
    nproc_per_node: int = 1
    max_restarts: int = 3
    node_rank: int = 0
    node_unit: int = 1
    rdzv_name: str = RendezvousName.TRAINING
    join_timeout: float = 600.0
    monitor_interval: float = 1.0
    env: Dict[str, str] = field(default_factory=dict)
    redirect_output: Optional[str] = None  # dir for per-worker logs


@dataclass
class _Worker:
    local_rank: int
    process: subprocess.Popen
    log_file: Optional[object] = None


class ElasticAgent:
    """Supervises one node's worker processes across elastic restarts."""

    def __init__(
        self,
        spec: WorkerSpec,
        client: MasterClient,
        ckpt_saver=None,
        diagnosis_agent=None,
    ):
        self._spec = spec
        self._client = client
        if diagnosis_agent is None:
            from dlrover_tpu.agent.diagnosis_agent import DiagnosisAgent

            log_path = ""
            if spec.redirect_output:
                log_path = os.path.join(
                    spec.redirect_output, f"worker-{spec.node_rank}-0.log"
                )
            diagnosis_agent = DiagnosisAgent(
                master_client=client,
                node_id=spec.node_rank,
                log_path=log_path,
            )
        self._diagnosis_agent = diagnosis_agent
        self._rdzv = MasterRendezvousHandler(
            client,
            spec.node_rank,
            spec.nproc_per_node,
            rdzv_name=spec.rdzv_name,
            node_unit=spec.node_unit,
            join_timeout=spec.join_timeout,
        )
        self._workers: List[_Worker] = []
        self._restart_count = 0
        self._ckpt_saver = ckpt_saver
        self._last_heartbeat = 0.0
        self._last_resource_report = 0.0
        self._current_outcome: Optional[RendezvousOutcome] = None
        self._stopping = False

    # ---- worker lifecycle --------------------------------------------------

    def _initialize_workers(self) -> RendezvousOutcome:
        from dlrover_tpu.training_event import AgentEvents

        rdzv_start = time.time()
        with AgentEvents.rendezvous({"node_rank": self._spec.node_rank}):
            outcome = self._rdzv.next_rendezvous()
        self._client.report_goodput_phase(
            GoodputPhase.RENDEZVOUS, rdzv_start, time.time()
        )
        self._current_outcome = outcome
        if self._ckpt_saver is not None:
            self._ckpt_saver.set_world(outcome.world)
        self._start_workers(outcome)
        return outcome

    def _start_workers(self, outcome: RendezvousOutcome):
        from dlrover_tpu.training_event import AgentEvents

        spec = self._spec
        with AgentEvents.start_workers(self._restart_count) as span:
            self._start_workers_inner(outcome, spec)
            span.content["num_workers"] = len(self._workers)

    def _start_workers_inner(self, outcome: RendezvousOutcome, spec):
        self._workers = []
        # Workers must be able to import this framework even when the
        # launcher was started from a different cwd/PYTHONPATH.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        # Zero-cooperation profiling: when XLA capture is enabled, the
        # injection dir's sitecustomize arms the listener at interpreter
        # startup even if the train script never imports this framework
        # (reference xpu_timer's LD_PRELOAD contract). It chain-loads
        # any sitecustomize it shadows.
        inject_dir = os.path.join(
            pkg_root, "dlrover_tpu", "tpu_timer", "_inject"
        )
        for local_rank in range(spec.nproc_per_node):
            env = dict(os.environ)
            existing = env.get("PYTHONPATH", "")
            if pkg_root not in existing.split(os.pathsep):
                env["PYTHONPATH"] = (
                    f"{existing}{os.pathsep}{pkg_root}" if existing else pkg_root
                )
            env.update(spec.env)
            # Gate AFTER merging spec.env (the launcher may enable the
            # flag there).
            from dlrover_tpu.common.env_utils import env_bool

            if env_bool(env, "DLROVER_TPU_TIMER_XLA"):
                env["PYTHONPATH"] = (
                    f"{inject_dir}{os.pathsep}" + env["PYTHONPATH"]
                )
            env.update(
                worker_env(
                    coordinator=outcome.coordinator_address,
                    num_processes=outcome.num_processes,
                    process_id=outcome.process_id_base + local_rank,
                    local_rank=local_rank,
                    local_world_size=spec.nproc_per_node,
                    restart_count=self._restart_count,
                    rdzv_round=outcome.round,
                    node_ranks=list(outcome.world),
                    num_slices=outcome.num_slices,
                )
            )
            if spec.entrypoint.startswith("-m "):
                cmd = [
                    sys.executable,
                    "-m",
                    spec.entrypoint[3:].strip(),
                    *spec.args,
                ]
            else:
                cmd = [sys.executable, spec.entrypoint, *spec.args]
            log_file = None
            stdout = stderr = None
            if spec.redirect_output:
                os.makedirs(spec.redirect_output, exist_ok=True)
                path = os.path.join(
                    spec.redirect_output,
                    f"worker-{spec.node_rank}-{local_rank}.log",
                )
                log_file = open(path, "ab")
                stdout = stderr = log_file
            proc = subprocess.Popen(
                cmd,
                env=env,
                stdout=stdout,
                stderr=stderr,
                start_new_session=True,
            )
            self._workers.append(_Worker(local_rank, proc, log_file))
            logger.info(
                "started worker local_rank=%d pid=%d process_id=%d",
                local_rank,
                proc.pid,
                outcome.process_id_base + local_rank,
            )

    def _stop_workers(self, timeout: float = 15.0, post_mortem: bool = False):
        if post_mortem:
            # Failure/hang stop: SIGUSR2 makes workers dump all-thread
            # stacks into their logs (a worker wedged in a collective
            # tells us where), then a grace period lets faulthandler
            # finish writing before SIGTERM lands.
            dumped = False
            for w in self._workers:
                if w.process.poll() is None:
                    try:
                        os.kill(w.process.pid, signal.SIGUSR2)
                        dumped = True
                    except (ProcessLookupError, OSError):
                        pass
            if dumped:
                time.sleep(0.5)
        for w in self._workers:
            if w.process.poll() is None:
                try:
                    os.killpg(w.process.pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + timeout
        for w in self._workers:
            remaining = max(deadline - time.time(), 0.1)
            try:
                w.process.wait(remaining)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(w.process.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                w.process.wait()
        for w in self._workers:
            if w.log_file:
                w.log_file.close()
                w.log_file = None

    def _restart_workers(self, post_mortem: bool = False):
        restart_start = time.time()
        self._stop_workers(post_mortem=post_mortem)
        self._restart_count += 1
        self._initialize_workers()
        self._client.report_goodput_phase(
            GoodputPhase.RESTART, restart_start, time.time()
        )

    # ---- monitoring --------------------------------------------------------

    def _monitor_workers(self) -> Optional[str]:
        """Return "succeeded"|"failed"|None (still running)."""
        states = [w.process.poll() for w in self._workers]
        if all(s == 0 for s in states):
            return "succeeded"
        if any(s is not None and s != 0 for s in states):
            return "failed"
        return None

    def _failed_exit_codes(self) -> Dict[int, int]:
        return {
            w.local_rank: w.process.returncode
            for w in self._workers
            if w.process.poll() is not None and w.process.returncode != 0
        }

    def _membership_changed(self) -> bool:
        return self._rdzv.num_nodes_waiting() > 0

    def _heartbeat_and_actions(self) -> Optional[RunResult]:
        try:
            actions = self._client.report_heartbeat()
        except Exception:
            logger.warning("heartbeat failed", exc_info=True)
            return None
        for action in actions or []:
            atype = getattr(action, "action_type", None)
            if atype == DiagnosisActionType.RESTART_WORKER:
                # Diagnosis-driven restart usually means a hang: capture
                # stacks before tearing the workers down.
                logger.info("diagnosis action: restart workers in place")
                self._restart_workers(post_mortem=True)
            elif atype == DiagnosisActionType.RELAUNCH_WORKER:
                logger.info("diagnosis action: relaunch node")
                self._stop_workers()
                return RunResult.RELAUNCH
            elif atype == DiagnosisActionType.JOB_ABORT:
                logger.info("diagnosis action: abort job")
                self._stop_workers()
                return RunResult.FAILED
            elif atype == DiagnosisActionType.JOB_RESTART:
                logger.info("diagnosis action: job restart")
                self._restart_workers()
        return None

    # ---- failure handling --------------------------------------------------

    def _on_workers_failed(self) -> Optional[RunResult]:
        codes = self._failed_exit_codes()
        logger.warning("worker failure, exit codes %s", codes)
        if self._ckpt_saver is not None:
            try:
                self._ckpt_saver.save_shm_on_failure()
            except Exception:
                logger.exception("breakpoint checkpoint save failed")
        from dlrover_tpu.agent.diagnosis_agent import (
            FailureContext,
            WorkerAction,
        )

        ctx = FailureContext(
            exit_codes=codes,
            restart_count=self._restart_count,
            max_restarts=self._spec.max_restarts,
            # One offset-tracked read shared by diagnosis and the
            # reason classifier: the scan offset advances per read, so
            # two reads would leave the second one blind.
            log_tail=self._diagnosis_agent.consume_failure_evidence(),
        )
        decision = self._diagnosis_agent.diagnose_training_failure(ctx)
        reason = self._diagnosis_agent.failure_reason(ctx)
        from dlrover_tpu.common.constants import NodeExitReason
        from dlrover_tpu.training_event import AgentEvents

        if reason == NodeExitReason.OOM:
            # Restarting in place with the same config just OOMs again;
            # escalate so the master's optimizer can bump resources.
            decision = WorkerAction.RELAUNCH_NODE
        AgentEvents.worker_failure(codes, decision)
        try:
            self._client.report_failure(
                error_data=f"reason={reason} codes={codes}",
                node_rank=self._spec.node_rank,
                restart_count=self._restart_count,
                exit_code=next(iter(codes.values()), 1),
                level=TrainingExceptionLevel.NODE_ERROR
                if decision == WorkerAction.RELAUNCH_NODE
                else TrainingExceptionLevel.PROCESS_ERROR,
            )
        except Exception:
            logger.warning("failure report failed", exc_info=True)
        if decision == WorkerAction.RELAUNCH_NODE:
            return RunResult.RELAUNCH
        if decision == WorkerAction.FAIL_JOB:
            logger.error(
                "max restarts (%d) exhausted", self._spec.max_restarts
            )
            return RunResult.FAILED
        # Some workers may still be alive while siblings crashed; their
        # stacks are evidence for the failure diagnosis.
        self._restart_workers(post_mortem=True)
        return None

    # ---- main loop ---------------------------------------------------------

    def run(self) -> RunResult:
        self._diagnosis_agent.start()
        try:
            return self._run()
        except RendezvousEvictedError:
            logger.warning("evicted from rendezvous; requesting relaunch")
            self._stop_workers()
            return RunResult.RELAUNCH
        except RendezvousTimeoutError:
            logger.error("rendezvous timed out; requesting relaunch")
            self._stop_workers()
            try:
                self._client.report_failure(
                    "rendezvous timeout",
                    node_rank=self._spec.node_rank,
                    restart_count=self._restart_count,
                    level=TrainingExceptionLevel.RDZV_ERROR,
                )
            except Exception:
                pass
            return RunResult.RELAUNCH
        finally:
            self._diagnosis_agent.stop()

    def _run(self) -> RunResult:
        spec = self._spec
        self._initialize_workers()
        while True:
            time.sleep(spec.monitor_interval)
            state = self._monitor_workers()
            if state == "succeeded":
                self._exit_barrier()
                try:
                    self._client.report_succeeded()
                except Exception:
                    logger.warning("success report failed", exc_info=True)
                logger.info("all workers succeeded")
                return RunResult.SUCCEEDED
            if state == "failed":
                result = self._on_workers_failed()
                if result is not None:
                    return result
                continue
            # healthy: heartbeat + membership check
            now = time.time()
            if now - self._last_heartbeat > JobConstant.NODE_HEARTBEAT_INTERVAL:
                self._last_heartbeat = now
                result = self._heartbeat_and_actions()
                if result is not None:
                    return result
            if self._membership_changed():
                logger.info(
                    "membership change detected; gracefully re-meshing"
                )
                self._restart_workers()

    def _exit_barrier(self, timeout: float = 300.0):
        """All agents wait so slow savers/rank committers can finish.

        Reference: training.py exit_barrier via master KV store. Implemented
        with set+poll on per-node keys (idempotent under RPC retry, unlike a
        counter)."""
        outcome = self._current_outcome
        if outcome is None or len(outcome.world) <= 1:
            return
        key = f"exit-barrier/{outcome.round}/{self._spec.node_rank}"
        try:
            self._client.kv_store_set(key, b"1")
            peer_keys = [
                f"exit-barrier/{outcome.round}/{r}" for r in outcome.world
            ]
            deadline = time.time() + timeout
            while time.time() < deadline:
                values = self._client.kv_store_multi_get(peer_keys)
                if len(values) >= len(peer_keys):
                    return
                time.sleep(0.5)
            logger.warning("exit barrier timed out")
        except Exception:
            logger.warning("exit barrier failed", exc_info=True)

    def stop(self):
        self._stopping = True
        self._diagnosis_agent.stop()
        self._stop_workers()
