"""The elastic agent: per-node supervisor of JAX worker processes.

Parity: reference dlrover/python/elastic_agent/torch/training.py
(ElasticTrainingAgent:648, _invoke_run:1247, _initialize_workers:1073).
Re-designed as a plain process supervisor: torchelastic's WorkerGroup
machinery is replaced by direct subprocess management, because on TPU a
re-mesh requires restarting worker *processes* anyway
(``jax.distributed`` cannot re-initialize in-process).

Run states per monitor tick:
- all workers exited 0     -> exit barrier, report success, done
- any worker failed        -> breakpoint-save signal, restart-or-raise
- membership change wanted -> graceful stop, new rendezvous, restart
- otherwise                -> heartbeat (executing piggy-backed diagnosis
                              actions), resource report
"""

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import (
    MasterRendezvousHandler,
    RendezvousEvictedError,
    RendezvousOutcome,
    RendezvousTimeoutError,
)
from dlrover_tpu.common.constants import (
    DiagnosisActionType,
    GoodputPhase,
    JobConstant,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.env_utils import worker_env
from dlrover_tpu.common.log import logger


class RunResult(Enum):
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    RELAUNCH = "relaunch"  # ask the cluster layer for a new node


@dataclass
class WorkerSpec:
    entrypoint: str  # path to the training script, or "-m module"
    args: List[str] = field(default_factory=list)
    nproc_per_node: int = 1
    max_restarts: int = 3
    node_rank: int = 0
    node_unit: int = 1
    rdzv_name: str = RendezvousName.TRAINING
    join_timeout: float = 600.0
    monitor_interval: float = 1.0
    env: Dict[str, str] = field(default_factory=dict)
    redirect_output: Optional[str] = None  # dir for per-worker logs
    # Keep a pre-spawned interpreter (python + framework imports
    # already paid) and adopt it as the next incarnation on restart —
    # cuts restart latency by the ~4s import cost (agent/standby.py).
    # Honored for nproc_per_node == 1.
    warm_standby: bool = False


@dataclass
class _Worker:
    local_rank: int
    process: subprocess.Popen
    log_file: Optional[object] = None


class ElasticAgent:
    """Supervises one node's worker processes across elastic restarts."""

    def __init__(
        self,
        spec: WorkerSpec,
        client: MasterClient,
        ckpt_saver=None,
        diagnosis_agent=None,
    ):
        self._spec = spec
        self._client = client
        if diagnosis_agent is None:
            from dlrover_tpu.agent.diagnosis_agent import DiagnosisAgent

            log_path = ""
            if spec.redirect_output:
                log_path = os.path.join(
                    spec.redirect_output, f"worker-{spec.node_rank}-0.log"
                )
            diagnosis_agent = DiagnosisAgent(
                master_client=client,
                node_id=spec.node_rank,
                log_path=log_path,
            )
        self._diagnosis_agent = diagnosis_agent
        self._rdzv = MasterRendezvousHandler(
            client,
            spec.node_rank,
            spec.nproc_per_node,
            rdzv_name=spec.rdzv_name,
            node_unit=spec.node_unit,
            join_timeout=spec.join_timeout,
        )
        self._workers: List[_Worker] = []
        self._standby: Optional[subprocess.Popen] = None
        self._standby_log = None
        self._breakpoint_thread: Optional[threading.Thread] = None
        self._restart_count = 0
        self._ckpt_saver = ckpt_saver
        self._last_heartbeat = 0.0
        self._last_resource_report = 0.0
        self._current_outcome: Optional[RendezvousOutcome] = None
        self._stopping = False
        self._workers_started_at = 0.0
        from dlrover_tpu.observability.registry import default_registry

        registry = default_registry()
        self._restarts_counter = registry.counter(
            "agent_worker_restarts_total",
            "worker restarts performed by this agent",
        )
        self._failures_counter = registry.counter(
            "agent_worker_failures_total",
            "worker failures observed by this agent",
        )

    # ---- worker lifecycle --------------------------------------------------

    def _initialize_workers(self) -> RendezvousOutcome:
        from dlrover_tpu.training_event import AgentEvents

        rdzv_start = time.time()
        with AgentEvents.rendezvous({"node_rank": self._spec.node_rank}):
            outcome = self._rdzv.next_rendezvous()
        self._client.report_goodput_phase(
            GoodputPhase.RENDEZVOUS, rdzv_start, time.time()
        )
        self._current_outcome = outcome
        if self._ckpt_saver is not None:
            self._ckpt_saver.set_world(outcome.world)
        self._start_workers(outcome)
        return outcome

    def _start_workers(self, outcome: RendezvousOutcome):
        from dlrover_tpu.training_event import AgentEvents

        spec = self._spec
        with AgentEvents.start_workers(self._restart_count) as span:
            self._start_workers_inner(outcome, spec)
            span.content["num_workers"] = len(self._workers)

    def _base_worker_env(self, spec) -> Dict[str, str]:
        """Environment shared by every incarnation (and by standbys):
        everything except the rendezvous-outcome values."""
        # Workers must be able to import this framework even when the
        # launcher was started from a different cwd/PYTHONPATH.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                f"{existing}{os.pathsep}{pkg_root}" if existing else pkg_root
            )
        env.update(spec.env)
        # Gate AFTER merging spec.env (the launcher may enable the
        # flag there). Zero-cooperation profiling: when XLA capture is
        # enabled, the injection dir's sitecustomize arms the listener
        # at interpreter startup even if the train script never imports
        # this framework (reference xpu_timer's LD_PRELOAD contract).
        # It chain-loads any sitecustomize it shadows.
        from dlrover_tpu.common.env_utils import env_bool

        if env_bool(env, "DLROVER_TPU_TIMER_XLA"):
            inject_dir = os.path.join(
                pkg_root, "dlrover_tpu", "tpu_timer", "_inject"
            )
            env["PYTHONPATH"] = (
                f"{inject_dir}{os.pathsep}" + env["PYTHONPATH"]
            )
        return env

    def _outcome_env(
        self, outcome: RendezvousOutcome, local_rank: int, spec
    ) -> Dict[str, str]:
        return worker_env(
            coordinator=outcome.coordinator_address,
            num_processes=outcome.num_processes,
            process_id=outcome.process_id_base + local_rank,
            local_rank=local_rank,
            local_world_size=spec.nproc_per_node,
            restart_count=self._restart_count,
            rdzv_round=outcome.round,
            node_ranks=list(outcome.world),
            num_slices=outcome.num_slices,
        )

    def _worker_argv(self, spec) -> tuple:
        """(argv-after-python, module-or-None) for the entrypoint."""
        if spec.entrypoint.startswith("-m "):
            module = spec.entrypoint[3:].strip()
            return [module, *spec.args], module
        return [spec.entrypoint, *spec.args], None

    def _open_worker_log(self, spec, local_rank: int):
        if not spec.redirect_output:
            return None
        os.makedirs(spec.redirect_output, exist_ok=True)
        path = os.path.join(
            spec.redirect_output,
            f"worker-{spec.node_rank}-{local_rank}.log",
        )
        return open(path, "ab")

    def _start_workers_inner(self, outcome: RendezvousOutcome, spec):
        self._workers = []
        self._workers_started_at = time.time()
        for local_rank in range(spec.nproc_per_node):
            env = self._base_worker_env(spec)
            env.update(self._outcome_env(outcome, local_rank, spec))
            argv, module = self._worker_argv(spec)
            adopted = (
                local_rank == 0
                and self._adopt_standby(env, argv, module)
            )
            if adopted:
                proc, log_file = adopted
            else:
                if module is not None:
                    cmd = [sys.executable, "-m", *argv]
                else:
                    cmd = [sys.executable, *argv]
                log_file = self._open_worker_log(spec, local_rank)
                stdout = stderr = log_file
                proc = subprocess.Popen(
                    cmd,
                    env=env,
                    stdout=stdout,
                    stderr=stderr,
                    start_new_session=True,
                )
            self._workers.append(_Worker(local_rank, proc, log_file))
            logger.info(
                "started worker local_rank=%d pid=%d process_id=%d%s",
                local_rank,
                proc.pid,
                outcome.process_id_base + local_rank,
                " (adopted warm standby)" if adopted else "",
            )
        if spec.warm_standby and spec.nproc_per_node == 1:
            self._spawn_standby(spec)

    # ---- warm standby ------------------------------------------------------

    def _spawn_standby(self, spec):
        """Pre-spawn the NEXT incarnation's interpreter so a restart
        skips the ~4s python + framework import cost (agent/standby.py).
        The standby blocks on stdin; it never touches the accelerator
        until adopted."""
        if self._standby is not None and self._standby.poll() is None:
            return
        self._standby_log = self._open_worker_log(spec, 0)
        try:
            self._standby = subprocess.Popen(
                [sys.executable, "-m", "dlrover_tpu.agent.standby"],
                env=self._base_worker_env(spec),
                stdin=subprocess.PIPE,
                stdout=self._standby_log,
                stderr=self._standby_log,
                start_new_session=True,
            )
            logger.info("warm standby spawned pid=%d", self._standby.pid)
        except OSError:
            logger.warning("standby spawn failed", exc_info=True)
            self._standby = None

    def _adopt_standby(self, env, argv, module):
        """Hand the final env/argv to a live standby; returns
        (process, log_file) or None (no/dead standby -> cold spawn)."""
        standby, log_file = self._standby, self._standby_log
        self._standby = self._standby_log = None
        if standby is None:
            if log_file:  # spawn-failed leftovers must not leak the fd
                log_file.close()
            return None
        if standby.poll() is not None:
            if log_file:
                log_file.close()
            return None
        try:
            import json as json_mod

            line = json_mod.dumps(
                {"env": env, "argv": argv, "module": module}
            )
            standby.stdin.write(line.encode() + b"\n")
            standby.stdin.flush()
            standby.stdin.close()
        except (OSError, ValueError):
            logger.warning("standby adoption failed; cold spawn",
                           exc_info=True)
            try:
                standby.kill()
            except OSError:
                pass
            if log_file:
                log_file.close()
            return None
        return standby, log_file

    def _close_standby(self):
        standby, log_file = self._standby, self._standby_log
        self._standby = self._standby_log = None
        if standby is not None and standby.poll() is None:
            try:
                standby.stdin.close()  # EOF -> clean exit
                standby.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    standby.kill()
                except OSError:
                    pass
        if log_file:
            log_file.close()

    def _stop_workers(self, timeout: float = 15.0, post_mortem: bool = False):
        if post_mortem:
            # Failure/hang stop: SIGUSR2 makes workers dump all-thread
            # PYTHON stacks into their logs (a worker wedged in a
            # collective tells us where), then a grace period lets
            # faulthandler finish writing before SIGTERM lands. A
            # worker wedged inside libtpu/XLA C++ shows one opaque
            # Python line, so the agent ALSO captures native stacks
            # out-of-process (ptrace + libunwind, the reference's
            # gdb-orchestration role) and appends them to the same log.
            dumped = False
            for w in self._workers:
                if w.process.poll() is None:
                    try:
                        os.kill(w.process.pid, signal.SIGUSR2)
                        dumped = True
                    except (ProcessLookupError, OSError):
                        pass
            if dumped:
                time.sleep(0.5)
            self._capture_native_stacks()
        for w in self._workers:
            if w.process.poll() is None:
                try:
                    os.killpg(w.process.pid, signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + timeout
        for w in self._workers:
            remaining = max(deadline - time.time(), 0.1)
            try:
                w.process.wait(remaining)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(w.process.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                w.process.wait()
        for w in self._workers:
            if w.log_file:
                w.log_file.close()
                w.log_file = None

    def _capture_native_stacks(self, timeout: float = 12.0):
        """Append native (ptrace+libunwind) stacks of every live worker
        to its log, CONCURRENTLY and with a hard bound — this runs on
        the hang-recovery path, where the diagnostic must never become
        the delay (advisor r5: first-use sampler builds and serial
        20s/worker sampling could add minutes before SIGTERM; the
        sampler binary is prebuilt at agent start)."""
        try:
            from dlrover_tpu.tpu_timer.native_stack import (
                sample_native_stacks,
            )
        except Exception:  # noqa: BLE001 - diagnosis best-effort
            return

        def one(w):
            try:
                text = sample_native_stacks(
                    w.process.pid, timeout=timeout
                )
            except Exception:  # noqa: BLE001
                text = None
            if text and w.log_file:
                try:
                    w.log_file.write(text.encode())
                    w.log_file.flush()
                except (OSError, ValueError):
                    pass

        threads = [
            threading.Thread(target=one, args=(w,), daemon=True)
            for w in self._workers
            if w.process.poll() is None
        ]
        for t in threads:
            t.start()
        deadline = time.time() + timeout + 3.0
        for t in threads:
            t.join(timeout=max(deadline - time.time(), 0.1))

    def _restart_workers(self, post_mortem: bool = False):
        restart_start = time.time()
        self._stop_workers(post_mortem=post_mortem)
        self._restart_count += 1
        self._restarts_counter.inc()
        self._initialize_workers()
        self._client.report_goodput_phase(
            GoodputPhase.RESTART, restart_start, time.time()
        )

    # ---- monitoring --------------------------------------------------------

    def _monitor_workers(self) -> Optional[str]:
        """Return "succeeded"|"failed"|None (still running)."""
        states = [w.process.poll() for w in self._workers]
        if all(s == 0 for s in states):
            return "succeeded"
        if any(s is not None and s != 0 for s in states):
            return "failed"
        return None

    def _failed_exit_codes(self) -> Dict[int, int]:
        return {
            w.local_rank: w.process.returncode
            for w in self._workers
            if w.process.poll() is not None and w.process.returncode != 0
        }

    def _membership_changed(self) -> bool:
        return self._rdzv.num_nodes_waiting() > 0

    def _heartbeat_and_actions(self) -> Optional[RunResult]:
        try:
            actions = self._client.report_heartbeat()
        except Exception:
            logger.warning("heartbeat failed", exc_info=True)
            return None
        for action in actions or []:
            atype = getattr(action, "action_type", None)
            if atype == DiagnosisActionType.RESTART_WORKER:
                # Diagnosis-driven restart usually means a hang: capture
                # stacks before tearing the workers down.
                logger.info("diagnosis action: restart workers in place")
                self._restart_workers(post_mortem=True)
            elif atype == DiagnosisActionType.RELAUNCH_WORKER:
                logger.info("diagnosis action: relaunch node")
                self._stop_workers()
                return RunResult.RELAUNCH
            elif atype == DiagnosisActionType.JOB_ABORT:
                logger.info("diagnosis action: abort job")
                self._stop_workers()
                return RunResult.FAILED
            elif atype == DiagnosisActionType.JOB_RESTART:
                logger.info("diagnosis action: job restart")
                self._restart_workers()
        return None

    # ---- failure handling --------------------------------------------------

    def collect_flight_records(
        self, local_ranks=None, last_n: int = 64
    ) -> Dict[int, Dict]:
        """Fetch the flight-recorder crash dumps of this node's workers
        (the last N steps each dead worker managed to record). Dumps
        older than the current incarnation are skipped: a SIGKILLed
        worker writes nothing, and reporting the PREVIOUS incarnation's
        ring as this failure's postmortem would mislead diagnosis."""
        from dlrover_tpu.observability import flight_recorder

        if local_ranks is None:
            local_ranks = range(self._spec.nproc_per_node)
        # Cutoff AT the incarnation start: the previous incarnation
        # always dumps before _start_workers_inner stamps the new
        # start time, so its file's mtime lands before the cutoff.
        started = getattr(self, "_workers_started_at", 0.0)
        max_age = max(time.time() - started, 0.0) if started else None
        return flight_recorder.collect_dumps(
            self._spec.node_rank,
            local_ranks,
            max_age_s=max_age,
            last_n=last_n,
        )

    def _report_flight_records(self, codes: Dict[int, int]):
        """Forward dead workers' last-steps rings to the master's
        diagnosis store; best-effort — postmortem data must never delay
        the restart path."""
        try:
            dumps = self.collect_flight_records(local_ranks=codes.keys())
        except Exception:  # noqa: BLE001 - diagnosis best-effort
            logger.warning("flight record collection failed", exc_info=True)
            return
        from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

        for local_rank, dump in dumps.items():
            steps = dump.get("steps", [])
            if steps:
                logger.info(
                    "flight recorder (local_rank %d): last step %s",
                    local_rank,
                    steps[-1],
                )
            try:
                self._client.report_diagnosis_data(
                    DiagnosisDataType.FLIGHT_RECORDER,
                    {
                        "node_rank": self._spec.node_rank,
                        "local_rank": local_rank,
                        "steps": steps,
                    },
                )
            except Exception:  # noqa: BLE001
                logger.debug("flight record report failed", exc_info=True)
        # Hang-watchdog / SIGUSR1 stack dumps ride the same postmortem
        # path: a wedged-then-killed worker's blocked frames reach the
        # master's hang diagnostician as evidence.
        try:
            from dlrover_tpu.observability.hang_watchdog import (
                collect_hang_dumps,
            )

            started = getattr(self, "_workers_started_at", 0.0)
            max_age = max(time.time() - started, 0.0) if started else None
            hang_dumps = collect_hang_dumps(
                self._spec.node_rank, codes.keys(), max_age_s=max_age
            )
            for local_rank, dump in hang_dumps.items():
                self._client.report_diagnosis_data(
                    DiagnosisDataType.STACK_DUMP, dump
                )
        except Exception:  # noqa: BLE001 — postmortem best-effort
            logger.debug("hang dump report failed", exc_info=True)

    def _on_workers_failed(self) -> Optional[RunResult]:
        codes = self._failed_exit_codes()
        logger.warning("worker failure, exit codes %s", codes)
        self._failures_counter.inc()
        self._report_flight_records(codes)
        if self._ckpt_saver is not None:
            # Breakpoint save runs in the background: a same-host
            # restart restores MEMORY-FIRST from the shm image (owned
            # by this agent process, so it survives the worker), and
            # the storage persist only protects the node-loss case —
            # where minutes of latency are fine — so the restart
            # needn't wait the seconds a large state takes to persist.
            # The persist only READS shm (serialized against new saves
            # by the per-rank locks). A crash-looping worker must not
            # stack concurrent saves (save_shm_on_failure is not
            # self-reentrant): if the previous persist is still running
            # after the join grace, skip this round — the next failure
            # or cadence save covers it.
            prev = self._breakpoint_thread
            if prev is not None:
                prev.join(timeout=60.0)
            if prev is not None and prev.is_alive():
                logger.warning(
                    "previous breakpoint save still running; skipping"
                )
            else:
                def _breakpoint_save():
                    try:
                        self._ckpt_saver.save_shm_on_failure()
                    except Exception:
                        logger.exception(
                            "breakpoint checkpoint save failed"
                        )

                self._breakpoint_thread = threading.Thread(
                    target=_breakpoint_save, daemon=True,
                    name="breakpoint-save",
                )
                self._breakpoint_thread.start()
        from dlrover_tpu.agent.diagnosis_agent import (
            FailureContext,
            WorkerAction,
        )

        ctx = FailureContext(
            exit_codes=codes,
            restart_count=self._restart_count,
            max_restarts=self._spec.max_restarts,
            # One offset-tracked read shared by diagnosis and the
            # reason classifier: the scan offset advances per read, so
            # two reads would leave the second one blind.
            log_tail=self._diagnosis_agent.consume_failure_evidence(),
        )
        decision = self._diagnosis_agent.diagnose_training_failure(ctx)
        reason = self._diagnosis_agent.failure_reason(ctx)
        from dlrover_tpu.common.constants import NodeExitReason
        from dlrover_tpu.training_event import AgentEvents

        if reason == NodeExitReason.OOM:
            # Restarting in place with the same config just OOMs again;
            # escalate so the master's optimizer can bump resources.
            decision = WorkerAction.RELAUNCH_NODE
        AgentEvents.worker_failure(codes, decision)
        try:
            self._client.report_failure(
                error_data=f"reason={reason} codes={codes}",
                node_rank=self._spec.node_rank,
                restart_count=self._restart_count,
                exit_code=next(iter(codes.values()), 1),
                level=TrainingExceptionLevel.NODE_ERROR
                if decision == WorkerAction.RELAUNCH_NODE
                else TrainingExceptionLevel.PROCESS_ERROR,
            )
        except Exception:
            logger.warning("failure report failed", exc_info=True)
        if decision == WorkerAction.RELAUNCH_NODE:
            return RunResult.RELAUNCH
        if decision == WorkerAction.FAIL_JOB:
            logger.error(
                "max restarts (%d) exhausted", self._spec.max_restarts
            )
            return RunResult.FAILED
        # Some workers may still be alive while siblings crashed; their
        # stacks are evidence for the failure diagnosis.
        self._restart_workers(post_mortem=True)
        return None

    # ---- main loop ---------------------------------------------------------

    def run(self) -> RunResult:
        self._diagnosis_agent.start()
        # Prebuild the native stack sampler off the critical path: a
        # first-use g++ build during hang recovery would delay the
        # restart (see _capture_native_stacks).
        def _prebuild():
            try:
                from dlrover_tpu.tpu_timer.native_stack import (
                    ensure_built,
                )

                ensure_built()
            except Exception:  # noqa: BLE001 - diagnosis best-effort
                pass

        threading.Thread(target=_prebuild, daemon=True).start()
        try:
            return self._run()
        except RendezvousEvictedError:
            logger.warning("evicted from rendezvous; requesting relaunch")
            self._stop_workers()
            return RunResult.RELAUNCH
        except RendezvousTimeoutError:
            logger.error("rendezvous timed out; requesting relaunch")
            self._stop_workers()
            try:
                self._client.report_failure(
                    "rendezvous timeout",
                    node_rank=self._spec.node_rank,
                    restart_count=self._restart_count,
                    level=TrainingExceptionLevel.RDZV_ERROR,
                )
            except Exception:
                pass
            return RunResult.RELAUNCH
        finally:
            self._diagnosis_agent.stop()
            self._close_standby()

    def _run(self) -> RunResult:
        spec = self._spec
        self._initialize_workers()
        while True:
            time.sleep(spec.monitor_interval)
            state = self._monitor_workers()
            if state == "succeeded":
                self._exit_barrier()
                try:
                    self._client.report_succeeded()
                except Exception:
                    logger.warning("success report failed", exc_info=True)
                logger.info("all workers succeeded")
                return RunResult.SUCCEEDED
            if state == "failed":
                result = self._on_workers_failed()
                if result is not None:
                    return result
                continue
            # healthy: heartbeat + membership check
            now = time.time()
            if now - self._last_heartbeat > JobConstant.NODE_HEARTBEAT_INTERVAL:
                self._last_heartbeat = now
                result = self._heartbeat_and_actions()
                if result is not None:
                    return result
            if self._membership_changed():
                logger.info(
                    "membership change detected; gracefully re-meshing"
                )
                self._restart_workers()

    def _exit_barrier(self, timeout: float = 300.0):
        """All agents wait so slow savers/rank committers can finish.

        Reference: training.py exit_barrier via master KV store. Implemented
        with set+poll on per-node keys (idempotent under RPC retry, unlike a
        counter)."""
        outcome = self._current_outcome
        if outcome is None or len(outcome.world) <= 1:
            return
        key = f"exit-barrier/{outcome.round}/{self._spec.node_rank}"
        try:
            self._client.kv_store_set(key, b"1")
            peer_keys = [
                f"exit-barrier/{outcome.round}/{r}" for r in outcome.world
            ]
            deadline = time.time() + timeout
            while time.time() < deadline:
                values = self._client.kv_store_multi_get(peer_keys)
                if len(values) >= len(peer_keys):
                    return
                time.sleep(0.5)
            logger.warning("exit barrier timed out")
        except Exception:
            logger.warning("exit barrier failed", exc_info=True)

    def stop(self):
        self._stopping = True
        self._diagnosis_agent.stop()
        self._stop_workers()
        self._close_standby()
        if self._breakpoint_thread is not None:
            self._breakpoint_thread.join(timeout=60.0)
