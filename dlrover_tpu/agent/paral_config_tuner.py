"""Agent-side parallel-config tuner.

Parity: reference dlrover/python/elastic_agent/config/
paral_config_tuner.py:30 — polls the master's suggested ParallelConfig
and writes it to a JSON file the trainer watches; trainers that opt in
re-tune micro-batch/grad-accum (and rebuild their jitted step) when the
version changes.
"""

import json
import os
import threading
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger

CONFIG_FILE_ENV = "DLROVER_TPU_PARAL_CONFIG_FILE"


def default_config_path(job_name: str = "job") -> str:
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f"dlrover_tpu_paral_config_{job_name}.json"
    )


class ParalConfigTuner:
    def __init__(
        self,
        master_client,
        config_path: str = "",
        interval_s: float = 30.0,
    ):
        self._client = master_client
        self._path = config_path or default_config_path(
            os.getenv(NodeEnv.JOB_NAME, "job")
        )
        self._interval_s = interval_s
        # Start at 0: the master's "no suggestion yet" sentinel is a
        # default ParallelConfig with version=0 and must not be written.
        self._version = 0
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.environ[CONFIG_FILE_ENV] = self._path

    @property
    def config_path(self) -> str:
        return self._path

    def tune_once(self) -> bool:
        """Fetch the suggestion; write the file if the version advanced."""
        try:
            config = self._client.get_parallel_config()
        except Exception:
            logger.warning("parallel config fetch failed", exc_info=True)
            return False
        if config is None or config.version <= self._version:
            return False
        self._version = config.version
        payload = {
            "version": config.version,
            "micro_batch_size": config.micro_batch_size,
            "grad_accum_steps": config.grad_accum_steps,
            "remat_policy": config.remat_policy,
            "mesh_shape": config.mesh_shape,
        }
        tmp = f"{self._path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.rename(tmp, self._path)
        logger.info("parallel config v%d written to %s",
                    config.version, self._path)
        return True

    def start(self):
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paral-config-tuner", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        while not self._stopped.wait(self._interval_s):
            try:
                self.tune_once()
            except Exception:
                logger.warning("config tuning failed", exc_info=True)


def read_parallel_config(path: str = "") -> Optional[dict]:
    """Trainer-side helper: current suggestion or None.

    Zero-valued ``micro_batch_size``/``grad_accum_steps`` mean "no
    suggestion for this knob" (the master may know the mesh/remat answer
    before it knows the global batch); trainers must treat 0 as unset.
    """
    path = path or os.getenv(CONFIG_FILE_ENV, "")
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
