"""Node/ICI health probe executed as a worker process.

Parity: reference trainer/torch/node_check/nvidia_gpu.py:40-84 (matmul
rounds + allreduce) — TPU version: an MXU-shaped bf16 matmul on every
local device plus a psum across the probe group (ICI/DCN when the group
spans hosts). Writes elapsed seconds to the result file; any exception
leaves no result, which the agent reports as a failed probe.
"""

import os
import sys
import time


def _chaos_ranks(var: str) -> set:
    return {
        int(r)
        for r in os.getenv(var, "").split(",")
        if r.strip().lstrip("-").isdigit()
    }


def _my_node_rank() -> int:
    return int(os.getenv("DLROVER_TPU_CHECK_NODE_RANK", "-1"))


def main() -> int:
    result_file = sys.argv[1]
    matmul_size = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 30
    comm_mb = int(sys.argv[4]) if len(sys.argv) > 4 else 0

    from dlrover_tpu.trainer.runtime import init_distributed

    ctx = init_distributed()
    import jax
    import jax.numpy as jnp

    start = time.time()

    # MXU probe: bf16 GEMM chain, one per local device.
    @jax.jit
    def gemm_chain(x):
        for _ in range(8):
            x = jnp.dot(x, x, preferred_element_type=jnp.float32).astype(
                jnp.bfloat16
            )
            x = x / (jnp.max(jnp.abs(x)) + 1.0)
        return x

    for device in jax.local_devices():
        key = jax.random.PRNGKey(0)
        x = jax.device_put(
            jax.random.normal(
                key, (matmul_size, matmul_size), dtype=jnp.bfloat16
            ),
            device,
        )
        for _ in range(rounds // 8 or 1):
            x = gemm_chain(x)
        jax.block_until_ready(x)

    # Collective probe across the whole probe world (ICI within a slice,
    # DCN across slices). Uses psum over all devices via pmap-free jit
    # with a 1D mesh of every global device.
    if comm_mb > 0 and jax.device_count() > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devices = jax.devices()
        mesh = Mesh(devices, ("probe",))
        n = (comm_mb * 1024 * 1024 // 4 // len(devices)) * len(devices)
        arr = jnp.ones((n,), dtype=jnp.float32)
        sharded = jax.device_put(
            arr, NamedSharding(mesh, P("probe"))
        )

        @jax.jit
        def allreduce(x):
            # a reduction whose result every device needs: XLA emits an
            # all-reduce over the mesh
            return x + jnp.sum(x)

        out = allreduce(sharded)
        jax.block_until_ready(out)

    # Chaos/fault injection (operational chaos harness + e2e tests,
    # chaos.py): a rigged rank straggles (sleeps inside the timed
    # region) or fails its probe AFTER the collectives, so partners
    # complete cleanly and the master's bisection isolates exactly the
    # rigged node without waiting out collective timeouts.
    rank = _my_node_rank()
    if rank in _chaos_ranks("DLROVER_TPU_CHAOS_CHECK_SLOW_RANKS"):
        time.sleep(float(os.getenv("DLROVER_TPU_CHAOS_CHECK_SLOW_SECS", "3")))
    if rank in _chaos_ranks("DLROVER_TPU_CHAOS_CHECK_FAIL_RANKS"):
        return 1  # no result file: the agent reports a failed probe

    elapsed = time.time() - start
    tmp = result_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{elapsed:.6f}")
    os.replace(tmp, result_file)
    return 0


if __name__ == "__main__":
    sys.exit(main())
