"""Agent-side node resource monitor.

Parity: reference elastic_agent/monitor/resource.py (ResourceMonitor —
psutil/pynvml sampling -> report_used_resource) and monitor/training.py
(TorchTrainingMonitor). TPU utilization comes from the worker's own step
reports (and, when present, the native profiler's metrics endpoint) rather
than a NVML analogue.
"""

import os
import threading
from typing import Optional

import psutil

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.log import logger


class ResourceMonitor:
    def __init__(
        self,
        client: MasterClient,
        interval: float = 15.0,
    ):
        self._client = client
        self._interval = interval
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._process = psutil.Process(os.getpid())

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="resource-monitor"
            )
            self._thread.start()

    def stop(self):
        self._stopped.set()

    def _sample(self):
        cpu = psutil.cpu_percent(interval=None)
        mem = psutil.virtual_memory()
        used_mb = (mem.total - mem.available) / (1024 * 1024)
        return cpu, used_mb

    def _run(self):
        psutil.cpu_percent(interval=None)  # prime the sampler
        while not self._stopped.wait(self._interval):
            try:
                cpu, mem_mb = self._sample()
                self._client.report_used_resource(cpu, mem_mb)
            except Exception:
                logger.debug("resource sample failed", exc_info=True)
