"""Agent-side network/node check orchestration.

Parity: reference NodeCheckElasticAgent (elastic_agent/torch/training.py:
2055, node_health_check:2316, run_network_check:2410): up to two check
rounds — round 0 pairs nodes arbitrarily; a failing pair's members become
suspects; round 1 pairs each suspect with a known-healthy node so the
master can bisect the fault to a node. Straggler detection compares probe
times against the group median.
"""

import os
import subprocess
import sys
import tempfile
import time
from typing import Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler
from dlrover_tpu.common.constants import (
    NetworkCheckConstant,
    NodeEventType,
    RendezvousName,
)
from dlrover_tpu.common.env_utils import worker_env
from dlrover_tpu.common.log import logger

_PROBE_MODULE = "dlrover_tpu.agent.node_check_worker"


def _run_probe(
    outcome,
    node_rank: int,
    nproc_per_node: int,
    comm_perf: bool,
    timeout: float,
) -> Tuple[bool, float]:
    """Launch the probe process(es) for this node; returns (ok, elapsed)."""
    result_dir = tempfile.mkdtemp(prefix="dlrover_tpu_check_")
    procs = []
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for local_rank in range(nproc_per_node):
        result_file = os.path.join(result_dir, f"r{local_rank}")
        env = dict(os.environ)
        existing = env.get("PYTHONPATH", "")
        if pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                f"{existing}{os.pathsep}{pkg_root}" if existing else pkg_root
            )
        env.update(
            worker_env(
                coordinator=outcome.coordinator_address,
                num_processes=outcome.num_processes,
                process_id=outcome.process_id_base + local_rank,
                local_rank=local_rank,
                local_world_size=nproc_per_node,
                rdzv_round=outcome.round,
            )
        )
        env["DLROVER_TPU_CHECK_NODE_RANK"] = str(node_rank)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    _PROBE_MODULE,
                    result_file,
                    str(NetworkCheckConstant.MATMUL_SIZE),
                    str(NetworkCheckConstant.MATMUL_ROUNDS),
                    str(NetworkCheckConstant.ALLREDUCE_MB if comm_perf else 0),
                ],
                env=env,
            )
        )
    deadline = time.time() + timeout
    ok = True
    for p in procs:
        remaining = max(deadline - time.time(), 1.0)
        try:
            if p.wait(remaining) != 0:
                ok = False
        except subprocess.TimeoutExpired:
            p.kill()
            ok = False
    elapsed = 0.0
    for local_rank in range(nproc_per_node):
        path = os.path.join(result_dir, f"r{local_rank}")
        if os.path.exists(path):
            elapsed = max(elapsed, float(open(path).read().strip()))
        else:
            ok = False
    return ok, elapsed


def run_network_check(
    client: MasterClient,
    node_rank: int,
    nproc_per_node: int = 1,
    comm_perf: bool = False,
    timeout: float = NetworkCheckConstant.CHECK_TIMEOUT,
    node_unit: int = 1,
) -> bool:
    """Run the probe rounds; returns False if THIS node is faulty."""
    from dlrover_tpu.training_event import AgentEvents

    span = AgentEvents.node_check().begin()
    try:
        ok = _run_network_check(
            client, node_rank, nproc_per_node, comm_perf, timeout, node_unit
        )
    except Exception as e:
        span.fail(str(e))
        raise
    span.end(success=ok)
    return ok


def _run_network_check(
    client: MasterClient,
    node_rank: int,
    nproc_per_node: int = 1,
    comm_perf: bool = False,
    timeout: float = NetworkCheckConstant.CHECK_TIMEOUT,
    node_unit: int = 1,
) -> bool:
    # Up to 4 rounds: pair + bisect in the flat flow; the group-aware
    # flow adds intra/inter phases (rdzv_manager.py
    # GroupNetworkCheckRendezvousManager.MAX_PHASES).
    for attempt in range(4):
        handler = MasterRendezvousHandler(
            client,
            node_rank,
            nproc_per_node,
            rdzv_name=RendezvousName.NETWORK_CHECK,
            node_unit=node_unit,
            join_timeout=timeout,
        )
        outcome = handler.next_rendezvous()
        logger.info(
            "network check round %d: group=%d world=%s",
            outcome.round,
            outcome.group,
            sorted(outcome.world),
        )
        ok, elapsed = _run_probe(
            outcome, node_rank, nproc_per_node, comm_perf, timeout
        )
        client.report_network_check_result(node_rank, ok, elapsed)
        # Wait until the master has concluded the round we reported in.
        verdict = _poll_verdict(client, min_round=attempt, timeout=timeout)
        if verdict is None:
            logger.warning("network check result poll timed out")
            return ok
        faults, evaluated_round, needs_round2 = verdict
        if node_rank in faults:
            client.report_node_event(
                NodeEventType.NODE_CHECK_FAILED,
                reason="network-check",
                message=f"probe failed in round {evaluated_round}",
            )
            return False
        stragglers = client.check_straggler()
        if node_rank in stragglers:
            logger.warning("this node is a straggler (probe %.2fs)", elapsed)
            client.report_node_event(
                NodeEventType.STRAGGLER,
                reason="network-check",
                message=f"{elapsed:.2f}s",
            )
        if not needs_round2:
            return True
        # suspects exist: everyone joins the bisection round
        logger.info("suspects detected; joining verification round")
    return True


def _poll_verdict(client: MasterClient, min_round: int, timeout: float):
    """Poll until a round >= min_round has been evaluated. (A pending
    bisection round surfaces as evaluated_round==0 + needs_round2, which
    satisfies min_round=0; round-1 pollers must wait for the real round-1
    verdict, never round 0's empty one.)"""
    deadline = time.time() + timeout
    while time.time() < deadline:
        faults, evaluated_round, needs_round2 = client.check_fault_node()
        if evaluated_round >= min_round:
            return faults, evaluated_round, needs_round2
        time.sleep(0.5)
    return None
