"""Typed client of the master's get/report protocol.

Parity: reference dlrover/python/elastic_agent/master_client.py:51-778
(MasterClient with gRPC/HTTP transports, retry wrapper, singleton).
"""

import http.client
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.comm import Message
from dlrover_tpu.common.constants import JobConstant, NodeEnv
from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point
from dlrover_tpu.observability import tracing
from dlrover_tpu.rpc.transport import build_master_stub

# Bounded master-outage ride-through window (seconds). When > 0, a verb
# whose per-call retry budget exhausts on a connection-class error keeps
# re-trying under long jittered sleeps for up to this long — the window
# a restarting master (journal replay, scheduler reschedule) needs, kept
# deliberately distinct from retry_rpc's per-call budget.
OUTAGE_ENV = "DLROVER_TPU_MASTER_OUTAGE_S"
# Env ceiling for the per-call retry budget (overrides the default for
# every wrapped verb; an explicit retry= kwarg still wins).
MAX_RETRIES_ENV = "DLROVER_TPU_RPC_MAX_RETRIES"

# "Master unreachable", as opposed to "master answered with an error":
# socket/timeout failures are OSError subclasses, half-closed keep-alive
# connections surface as http.client exceptions. An HTTP-level error
# reply (RuntimeError from the stub) means the master is alive — outage
# mode must not mask it.
_OUTAGE_ERRORS = (OSError, http.client.HTTPException)


class RpcRetriesExhausted(RuntimeError):
    """Every retry attempt of one RPC verb failed (named in message)."""

    def __init__(self, verb: str, attempts: int, last_error: Exception):
        super().__init__(
            f"RPC {verb} failed after {attempts} attempts "
            f"(last error: {type(last_error).__name__}: {last_error})"
        )
        self.verb = verb
        self.attempts = attempts
        self.last_error = last_error


def _exhausted_counter():
    from dlrover_tpu.observability.registry import default_registry

    return default_registry().counter(
        "client_rpc_retries_exhausted_total",
        "client RPCs that failed every retry attempt, by verb",
        labelnames=("verb",),
    )


def _default_retries() -> int:
    env = os.getenv(MAX_RETRIES_ENV, "")
    if env:
        try:
            return max(int(env), 1)
        except ValueError:
            pass
    return JobConstant.MASTER_CLIENT_DEFAULT_RETRY


def _outage_window_s() -> float:
    try:
        return float(os.getenv(OUTAGE_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def retry_rpc(func):
    """Bounded, jittered exponential retry for idempotent control verbs.

    Applied only to verbs that are safe to re-send: gets, and reports
    whose master-side apply is a no-op the second time (done-reports pop
    the lease from ``doing``; a re-apply finds nothing — at-most-once
    effect). Non-idempotent mutations (``kv_store_add``) are deliberately
    NOT wrapped. The ±30% jitter keeps a fleet of workers whose RPCs all
    failed together (master restart) from re-synchronizing into retry
    stampedes.

    Exhaustion contract: the per-call budget (default
    ``MASTER_CLIENT_DEFAULT_RETRY``, env-tunable via
    ``DLROVER_TPU_RPC_MAX_RETRIES``, explicit ``retry=`` kwarg wins)
    raises :class:`RpcRetriesExhausted` naming the verb and ticks
    ``client_rpc_retries_exhausted_total{verb}`` — unless the failure is
    connection-class and ``DLROVER_TPU_MASTER_OUTAGE_S`` is set, in
    which case the client enters bounded outage mode: long jittered
    reconnect attempts until the window expires (master crash-restart
    ride-through, docs/DESIGN.md §37).

    Tracing: ONE client span covers every attempt — a retried RPC is
    the same logical operation re-sent, so the span's ``retry`` attr
    increments instead of minting sibling spans, and the server spans
    of all attempts parent to it (the at-most-once story stays visible
    as one wire operation).
    """

    def wrapper(self, *args, **kwargs):
        retry = max(kwargs.pop("retry", _default_retries()), 1)
        err = None
        with tracing.span(f"rpc.{func.__name__}", kind="client") as sp:
            for i in range(retry):
                if i > 0:
                    sp.inc_attr("retry")
                    backoff = min(2 ** (i - 1), 8)
                    time.sleep(backoff * (1.0 + random.uniform(-0.3, 0.3)))
                try:
                    return func(self, *args, **kwargs)
                except Exception as e:  # noqa: BLE001 — transports vary
                    err = e
            outage_s = _outage_window_s()
            if outage_s > 0 and isinstance(err, _OUTAGE_ERRORS):
                deadline = time.monotonic() + outage_s
                self._outage_begin(func.__name__, err)
                try:
                    while time.monotonic() < deadline:
                        sp.inc_attr("outage_retry")
                        remaining = deadline - time.monotonic()
                        time.sleep(
                            min(
                                1.0 + random.uniform(0.0, 2.0),
                                max(remaining, 0.05),
                            )
                        )
                        try:
                            result = func(self, *args, **kwargs)
                            self._outage_end(recovered=True)
                            return result
                        except _OUTAGE_ERRORS as e:
                            err = e
                        except Exception as e:  # noqa: BLE001
                            # Master is back but the verb itself errors:
                            # surface that, don't spin the window out.
                            err = e
                            break
                finally:
                    self._outage_end(recovered=False)
            sp.set_attr("error", type(err).__name__)
            # The raise happens OUTSIDE the with block, so __exit__
            # would close this span "ok" — end it as the failure it is
            # (end() is idempotent; __exit__'s end becomes a no-op).
            sp.end(status="error")
        _exhausted_counter().inc(verb=func.__name__)
        logger.warning(
            "RPC %s failed after %d tries: %s", func.__name__, retry, err
        )
        raise RpcRetriesExhausted(func.__name__, retry, err) from err

    return wrapper


class MasterClient:
    _instance: Optional["MasterClient"] = None
    _lock = threading.Lock()

    def __init__(
        self,
        master_addr: str,
        node_id: int = 0,
        node_type: str = "worker",
        kind: str = "grpc",
        timeout: float = JobConstant.MASTER_CLIENT_TIMEOUT_DEFAULT,
    ):
        self._addr = master_addr
        self._node_id = node_id
        self._node_type = node_type
        self._stub = build_master_stub(master_addr, kind=kind, timeout=timeout)
        # Epoch fencing (DESIGN.md §37): last master incarnation observed
        # in a response; -1 until a journal-backed master answers.
        self._epoch_lock = threading.Lock()
        self._master_epoch = -1
        self._epoch_listeners: List[Callable[[int, int], None]] = []
        self._in_outage = False

    # ---- plumbing ----------------------------------------------------------

    def _get(self, request: comm.BaseRequest, timeout: Optional[float] = None):
        fault_point("rpc.client.get", request=type(request).__name__)
        msg = Message(
            node_id=self._node_id,
            node_type=self._node_type,
            data=request.serialize(),
            # Active span's context (the retry_rpc span, or any caller
            # span) rides the envelope; None when tracing is disarmed.
            trace=tracing.current_carrier(),
        )
        resp = self._stub.get(msg, timeout=timeout)
        out = comm.BaseResponse.deserialize(resp.data)
        self._observe_epoch(out)
        return out

    def _report(self, request: comm.BaseRequest, timeout: Optional[float] = None):
        fault_point("rpc.client.report", request=type(request).__name__)
        msg = Message(
            node_id=self._node_id,
            node_type=self._node_type,
            data=request.serialize(),
            trace=tracing.current_carrier(),
        )
        resp = self._stub.report(msg, timeout=timeout)
        out = comm.BaseResponse.deserialize(resp.data)
        self._observe_epoch(out)
        return out

    # ---- epoch fencing & outage ride-through (DESIGN.md §37) ---------------

    @property
    def master_epoch(self) -> int:
        return self._master_epoch

    @property
    def in_outage(self) -> bool:
        return self._in_outage

    def add_epoch_listener(self, fn: Callable[[int, int], None]):
        """Register ``fn(old_epoch, new_epoch)`` — fired (on the RPC
        thread that noticed) when a response carries a master_epoch
        different from the last one observed. The FIRST observation only
        records the epoch: a fresh worker joining an old master is not a
        restart."""
        with self._epoch_lock:
            self._epoch_listeners.append(fn)

    def _observe_epoch(self, resp):
        epoch = getattr(resp, "master_epoch", -1)
        if not isinstance(epoch, int) or epoch < 0:
            return
        listeners = []
        with self._epoch_lock:
            prev = self._master_epoch
            if epoch != prev:
                self._master_epoch = epoch
                if prev >= 0:
                    listeners = list(self._epoch_listeners)
        for fn in listeners:
            # Listener RPCs (re-register, flush) re-enter _observe_epoch
            # with an unchanged epoch — no recursion.
            try:
                fn(prev, epoch)
            except Exception:  # noqa: BLE001 — listener bugs must not kill RPCs
                logger.warning(
                    "master-epoch listener %s failed", fn, exc_info=True
                )

    def _outage_begin(self, verb: str, err: Exception):
        if not self._in_outage:
            self._in_outage = True
            logger.warning(
                "master unreachable on %s (%s: %s); entering outage "
                "ride-through for up to %ss",
                verb,
                type(err).__name__,
                err,
                _outage_window_s(),
            )

    def _outage_end(self, recovered: bool):
        if self._in_outage:
            self._in_outage = False
            if recovered:
                logger.info("master reachable again; outage mode exited")

    def wait_master_ready(self, timeout: float = 120.0) -> bool:
        return self._stub.wait_ready(timeout)

    def close(self):
        self._stub.close()

    # ---- rendezvous --------------------------------------------------------

    @retry_rpc
    def join_rendezvous(
        self,
        node_rank: int,
        local_world_size: int,
        rdzv_name: str,
        node_unit: int = 1,
        node_ip: str = "",
        node_group: int = -1,
    ) -> int:
        resp = self._report(
            comm.JoinRendezvousRequest(
                node_id=self._node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
                node_unit=node_unit,
                node_ip=node_ip,
                node_group=node_group,
            )
        )
        return getattr(resp, "round", 0)

    @retry_rpc
    def get_comm_world(self, rdzv_name: str, node_rank: int):
        resp = self._get(
            comm.CommWorldRequest(node_id=node_rank, rdzv_name=rdzv_name)
        )
        rank_order = getattr(resp, "rank_order", None) or list(resp.world)
        node_groups = getattr(resp, "node_groups", None) or {}
        return resp.round, resp.group, resp.world, rank_order, node_groups

    @retry_rpc
    def num_nodes_waiting(self, rdzv_name: str) -> int:
        resp = self._get(comm.NumNodesWaitingRequest(rdzv_name=rdzv_name))
        return resp.waiting_num

    # ---- network check -----------------------------------------------------

    @retry_rpc
    def report_network_check_result(
        self, node_rank: int, succeeded: bool, elapsed: float
    ):
        return self._report(
            comm.NetworkCheckResultReport(
                node_id=self._node_id,
                node_rank=node_rank,
                succeeded=succeeded,
                result=elapsed,
            )
        )

    @retry_rpc
    def check_fault_node(self):
        """Returns (fault_nodes, evaluated_round, needs_round2)."""
        resp = self._get(comm.FaultNodeRequest())
        return resp.fault_nodes, resp.evaluated_round, resp.needs_round2

    @retry_rpc
    def check_straggler(self) -> List[int]:
        resp = self._get(comm.StragglerRequest())
        return resp.stragglers

    # ---- live rescale ------------------------------------------------------

    @retry_rpc
    def rescale_join(
        self,
        node_rank: int,
        local_world_size: int = 1,
        node_group: int = -1,
    ):
        """Announce this worker to the rescale plane (idempotent)."""
        return self._report(
            comm.RescaleJoinReport(
                node_id=self._node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                node_group=node_group,
            )
        )

    @retry_rpc
    def get_rescale_plan(self, node_rank: int, current_plan_id: int = -1):
        """Latest rescale plan newer than ``current_plan_id``, or None.
        Returns the raw RescalePlanResponse (plan_id == -1 -> no plan)."""
        resp = self._get(
            comm.RescalePlanRequest(
                node_id=self._node_id,
                node_rank=node_rank,
                current_plan_id=current_plan_id,
            )
        )
        if getattr(resp, "plan_id", -1) < 0:
            return None
        return resp

    @retry_rpc
    def report_rescale_ack(
        self, node_rank: int, plan_id: int, phase: str
    ):
        # Idempotent master-side (set add), so the retry wrapper is safe.
        return self._report(
            comm.RescaleAckReport(
                node_id=self._node_id,
                node_rank=node_rank,
                plan_id=plan_id,
                phase=phase,
            )
        )

    @retry_rpc
    def get_rescale_barrier(
        self, node_rank: int, plan_id: int, phase: str
    ):
        """(ready, expired, superseded, missing) of a plan's phase."""
        resp = self._get(
            comm.RescaleBarrierRequest(
                node_id=self._node_id,
                node_rank=node_rank,
                plan_id=plan_id,
                phase=phase,
            )
        )
        return (
            getattr(resp, "ready", False),
            getattr(resp, "expired", False),
            getattr(resp, "superseded", False),
            getattr(resp, "missing", []),
        )

    # ---- heartbeat / events ------------------------------------------------

    def report_heartbeat(self, timestamp: Optional[float] = None):
        resp = self._report(
            comm.HeartbeatReport(
                node_id=self._node_id, timestamp=timestamp or time.time()
            ),
        )
        return getattr(resp, "actions", [])

    @retry_rpc
    def report_failure(
        self,
        error_data: str,
        node_rank: int = 0,
        restart_count: int = 0,
        exit_code: int = 0,
        level: str = "process",
    ):
        return self._report(
            comm.NodeFailureReport(
                node_id=self._node_id,
                node_rank=node_rank,
                error_data=error_data,
                restart_count=restart_count,
                exit_code=exit_code,
                level=level,
            )
        )

    @retry_rpc
    def report_succeeded(self):
        return self._report(
            comm.SucceededRequest(
                node_id=self._node_id, node_type=self._node_type
            )
        )

    @retry_rpc
    def report_node_event(self, event_type: str, reason: str = "", message: str = ""):
        return self._report(
            comm.NodeEventReport(
                node_id=self._node_id,
                event_type=event_type,
                reason=reason,
                message=message,
            )
        )

    def report_diagnosis_data(self, data_type: str, payload: Dict):
        try:
            return self._report(
                comm.DiagnosisDataReport(
                    node_id=self._node_id,
                    data_type=data_type,
                    payload=payload,
                    timestamp=time.time(),
                )
            )
        except Exception:
            logger.debug("diagnosis data report failed", exc_info=True)

    # ---- perf / resources --------------------------------------------------

    def report_used_resource(
        self, cpu_percent: float, memory_mb: float, tpu_duty: float = 0.0,
        hbm_used_mb: float = 0.0,
    ):
        try:
            return self._report(
                comm.ResourceStats(
                    node_id=self._node_id,
                    cpu_percent=cpu_percent,
                    memory_mb=memory_mb,
                    tpu_duty_cycle=tpu_duty,
                    hbm_used_mb=hbm_used_mb,
                )
            )
        except Exception:
            logger.debug("resource report failed", exc_info=True)

    def report_global_step(
        self,
        step: int,
        elapsed_train_secs: float = 0.0,
        step_time_s: float = 0.0,
    ):
        try:
            return self._report(
                comm.GlobalStepReport(
                    node_id=self._node_id,
                    step=step,
                    timestamp=time.time(),
                    elapsed_train_secs=elapsed_train_secs,
                    step_time_s=step_time_s,
                )
            )
        except Exception:
            logger.debug("global step report failed", exc_info=True)

    def report_trace_spans(self, max_n: int = 256):
        """Push this process's finished spans to the master's trace
        aggregator, piggybacked on the existing diagnosis-data verb.
        Best-effort and disarmed-free: one tracer check, nothing else."""
        tracer = tracing.active_tracer()
        if tracer is None:
            return
        spans = tracer.drain_exports(max_n)
        if not spans:
            return
        from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

        self.report_diagnosis_data(
            DiagnosisDataType.TRACE_SPANS, {"spans": spans}
        )

    def report_goodput_phase(self, phase: str, start: float, end: float):
        try:
            return self._report(
                comm.GoodputPhaseReport(
                    node_id=self._node_id, phase=phase, start=start, end=end
                )
            )
        except Exception:
            logger.debug("goodput phase report failed", exc_info=True)

    # ---- kv store ----------------------------------------------------------

    @retry_rpc
    def kv_store_set(self, key: str, value: bytes):
        return self._report(comm.KVStoreSetRequest(key=key, value=value))

    @retry_rpc
    def kv_store_get(self, key: str) -> bytes:
        resp = self._get(comm.KVStoreGetRequest(key=key))
        return resp.value

    def kv_store_add(self, key: str, delta: int = 1) -> int:
        # Deliberately NOT retried: add is a non-idempotent mutation and a
        # lost response must not double-apply the increment. Callers that
        # need at-least-once semantics should use kv_store_set with a
        # caller-chosen unique key instead.
        resp = self._get(comm.KVStoreAddRequest(key=key, delta=delta))
        return resp.value

    @retry_rpc
    def kv_store_multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        resp = self._get(comm.KVStoreMultiGetRequest(keys=keys))
        return resp.values

    # ---- sync --------------------------------------------------------------

    @retry_rpc
    def join_sync(self, sync_name: str, node_rank: int):
        return self._report(
            comm.SyncJoinRequest(
                sync_name=sync_name, node_id=self._node_id, node_rank=node_rank
            )
        )

    @retry_rpc
    def sync_finished(self, sync_name: str):
        return self._report(comm.SyncFinishRequest(sync_name=sync_name))

    @retry_rpc
    def sync_barrier(self, sync_name: str) -> bool:
        resp = self._get(comm.SyncQueryRequest(sync_name=sync_name))
        return resp.done

    # ---- data sharding -----------------------------------------------------

    @retry_rpc
    def report_dataset_shard_params(self, params: comm.DatasetShardParams):
        return self._report(params)

    @retry_rpc
    def get_task(self, dataset_name: str) -> comm.ShardTask:
        return self._get(
            comm.TaskRequest(dataset_name=dataset_name, node_id=self._node_id)
        )

    @retry_rpc
    def get_tasks(self, dataset_name: str, count: int = 1):
        """Batched lease fetch: (tasks, wait). ``wait`` means peers hold
        the remaining shards in flight — poll again later. Falls back to
        a single :meth:`get_task` against masters that predate the
        batched verb (their servicer answers with a failed
        BaseResponse, not a MultiTaskResponse)."""
        resp = self._get(
            comm.MultiTaskRequest(
                dataset_name=dataset_name,
                node_id=self._node_id,
                count=count,
            )
        )
        tasks = getattr(resp, "tasks", None)
        if tasks is None:
            task = self.get_task(dataset_name)
            if task.task_id < 0:
                from dlrover_tpu.common.constants import TaskType

                return [], task.task_type == TaskType.WAIT
            return [task], False
        return tasks, bool(getattr(resp, "wait", False))

    @retry_rpc
    def report_task_done(
        self, dataset_name: str, task_id: int, success: bool = True
    ):
        return self._report(
            comm.TaskDoneReport(
                dataset_name=dataset_name,
                task_id=task_id,
                node_id=self._node_id,
                success=success,
            )
        )

    @retry_rpc
    def report_tasks_done_batch(
        self,
        dataset_name: str,
        done_ids: List[int],
        failed_ids: Optional[List[int]] = None,
    ):
        resp = self._report(
            comm.TaskDoneBatchReport(
                dataset_name=dataset_name,
                node_id=self._node_id,
                done_ids=list(done_ids),
                failed_ids=list(failed_ids or []),
            )
        )
        if not resp.success:
            # Master predates the batched verb: replay serially so no
            # done-report is silently dropped.
            for tid in done_ids:
                self.report_task_done(dataset_name, tid, True)
            for tid in failed_ids or []:
                self.report_task_done(dataset_name, tid, False)
        return resp

    @retry_rpc
    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._get(comm.ShardCheckpointRequest(dataset_name=dataset_name))
        return resp.checkpoint

    @retry_rpc
    def restore_shard_checkpoint(self, dataset_name: str, checkpoint: str):
        return self._report(
            comm.ShardCheckpointRestoreRequest(
                dataset_name=dataset_name, checkpoint=checkpoint
            )
        )

    # ---- checkpoint --------------------------------------------------------

    @retry_rpc
    def report_ckpt_step(self, step: int, committed: bool = False):
        return self._report(
            comm.CkptStepReport(
                node_id=self._node_id, step=step, committed=committed
            )
        )

    @retry_rpc
    def get_ckpt_latest_step(self) -> int:
        resp = self._get(comm.CkptLatestStepRequest())
        return resp.step

    # ---- pre-check / config ------------------------------------------------

    @retry_rpc
    def get_pre_check_result(self) -> str:
        resp = self._get(comm.PreCheckRequest(node_id=self._node_id))
        return resp.status

    @retry_rpc
    def get_elastic_run_config(self) -> Dict[str, str]:
        resp = self._get(comm.ElasticRunConfigRequest())
        return resp.configs

    @retry_rpc
    def get_parallel_config(self) -> comm.ParallelConfig:
        return self._get(comm.ParallelConfigRequest(node_id=self._node_id))

    @retry_rpc
    def get_job_detail(self) -> comm.JobDetailResponse:
        return self._get(comm.JobDetailRequest())

    # ---- cluster version (PS parity) ---------------------------------------

    @retry_rpc
    def get_cluster_version(self, version_type: str, task_type: str, task_id: int):
        resp = self._get(
            comm.ClusterVersionRequest(
                task_type=task_type, task_id=task_id, version_type=version_type
            )
        )
        return resp.version

    @retry_rpc
    def update_cluster_version(
        self, version_type: str, version: int, task_type: str, task_id: int
    ):
        return self._report(
            comm.ClusterVersionReport(
                task_type=task_type,
                task_id=task_id,
                version_type=version_type,
                version=version,
            )
        )

    # ---- singleton ---------------------------------------------------------

    @classmethod
    def singleton_instance(cls, *args, **kwargs) -> "MasterClient":
        with cls._lock:
            if cls._instance is None:
                if not args and "master_addr" not in kwargs:
                    addr = os.getenv(NodeEnv.MASTER_ADDR, "")
                    if not addr:
                        raise RuntimeError(
                            f"{NodeEnv.MASTER_ADDR} unset and no addr given"
                        )
                    node_id = int(os.getenv(NodeEnv.NODE_ID, 0))
                    cls._instance = cls(addr, node_id=node_id)
                else:
                    cls._instance = cls(*args, **kwargs)
            return cls._instance

    @classmethod
    def reset_singleton(cls):
        with cls._lock:
            cls._instance = None
