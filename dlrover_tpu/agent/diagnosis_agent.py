"""Node-side diagnosis: decide restart-vs-relaunch, report evidence.

Parity: reference dlrover/python/elastic_agent/diagnosis/
diagnosis_agent.py:67-303 (DiagnosisAgent.diagnose_training_failure,
periodic data reporting). The ElasticAgent consults this after a worker
failure: a software crash inside the restart budget restarts processes in
place (cheap, keeps the TPU host); hardware/driver faults or an exhausted
budget escalate to node relaunch; repeated identical crash signatures
short-circuit to relaunch early.
"""

import os
import re
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import (
    HARDWARE_LOG_MARKERS,
    OOM_LOG_MARKERS,
    ExitCode,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

# Log lines that indicate the TPU host itself is unhealthy; these make a
# same-host restart pointless (reference uses exit codes + log inference).
_HARDWARE_PATTERNS = [
    re.compile(p, re.IGNORECASE) for p in HARDWARE_LOG_MARKERS
]

# Evidence filter: generic error-ish lines PLUS the OOM/hardware
# markers — "RESOURCE_EXHAUSTED" or "uncorrectable ecc" must survive
# the filter even without the word "error" on the line.
_ERROR_LINE = re.compile(
    "|".join(
        (r"error|exception|traceback|fatal|abort",)
        + OOM_LOG_MARKERS
        + HARDWARE_LOG_MARKERS
    ),
    re.IGNORECASE,
)

# OOM signatures (shared with the master's classifier via
# common/constants.py): an in-place restart with the same config just
# OOMs again, so these escalate to relaunch and carry a reason hint the
# master turns into an OOM record for the optimizer's memory bump.
_OOM_PATTERNS = [
    re.compile(p, re.IGNORECASE) for p in OOM_LOG_MARKERS
]


class WorkerAction:
    RESTART_WORKER = "restart"
    RELAUNCH_NODE = "relaunch"
    FAIL_JOB = "fail"


@dataclass
class FailureContext:
    exit_codes: Dict[int, int]
    restart_count: int
    max_restarts: int
    log_tail: Optional[List[str]] = None


class DiagnosisAgent:
    def __init__(
        self,
        master_client=None,
        node_id: int = 0,
        log_path: str = "",
        report_interval_s: float = 60.0,
    ):
        self._client = master_client
        self._node_id = node_id
        self._log_path = log_path
        self._report_interval_s = report_interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_signature = ""
        self._same_signature_count = 0
        # Byte offset of log content already examined for hardware-fault
        # signatures; logs are appended across restarts, and a stale
        # hardware-ish line must not taint later software crashes.
        self._fault_scan_offset = 0

    # ---- failure diagnosis --------------------------------------------------

    def diagnose_training_failure(self, ctx: FailureContext) -> str:
        """Pick the recovery level for a worker failure."""
        if self._is_hardware_fault(ctx):
            logger.warning("hardware fault signature: relaunching node")
            return WorkerAction.RELAUNCH_NODE
        if ctx.restart_count >= ctx.max_restarts:
            # The budget is the hard stop: a deterministic crash must fail
            # the job, not churn through node relaunches.
            return WorkerAction.FAIL_JOB
        signature = str(sorted(ctx.exit_codes.items()))
        if signature == self._last_signature:
            self._same_signature_count += 1
        else:
            self._last_signature = signature
            self._same_signature_count = 1
        if self._same_signature_count >= 3:
            # Crashing identically 3x in a row on this host: stop burning
            # the restart budget here and try a fresh host.
            logger.warning(
                "repeated identical failure %s; relaunching node", signature
            )
            return WorkerAction.RELAUNCH_NODE
        return WorkerAction.RESTART_WORKER

    def consume_failure_evidence(self) -> List[str]:
        """Error lines appended since the last failure — read ONCE per
        failure and passed via FailureContext.log_tail so diagnosis and
        classification see the same evidence (a second read would find
        nothing: the scan offset advances)."""
        return self._consume_new_error_logs()

    def failure_reason(self, ctx: FailureContext) -> str:
        """Classify the failure for the master's exit-reason taxonomy.

        Returns a NodeExitReason value mined from exit codes and the
        worker log tail; the agent sends it as a ``reason=X`` hint in
        the failure report's error_data. Stale log lines from previous
        incarnations must not leak in — callers pass the offset-tracked
        lines from ``consume_failure_evidence``.
        """
        from dlrover_tpu.common.constants import ExitCode, NodeExitReason

        lines = (
            ctx.log_tail
            if ctx.log_tail is not None
            else self._consume_new_error_logs()
        )
        if any(p.search(ln) for ln in lines for p in _OOM_PATTERNS):
            return NodeExitReason.OOM
        if self._is_hardware_fault(ctx):
            return NodeExitReason.HARDWARE_ERROR
        codes = set(ctx.exit_codes.values())
        if ExitCode.KILLED in codes:
            return NodeExitReason.KILLED
        if ExitCode.TERMED in codes:
            return NodeExitReason.PREEMPTED
        return NodeExitReason.SOFTWARE_ERROR

    def _is_hardware_fault(self, ctx: FailureContext) -> bool:
        if any(
            c in (ExitCode.HARDWARE_ERROR, ExitCode.GPU_DRIVER_ERROR)
            for c in ctx.exit_codes.values()
        ):
            return True
        if ctx.log_tail is not None:
            lines = ctx.log_tail
        else:
            lines = self._consume_new_error_logs()
        return any(
            p.search(line) for line in lines for p in _HARDWARE_PATTERNS
        )

    def _consume_new_error_logs(self) -> List[str]:
        """Error lines appended since the previous failure diagnosis."""
        if not self._log_path or not os.path.exists(self._log_path):
            return []
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                start = max(self._fault_scan_offset, size - 256 * 1024)
                self._fault_scan_offset = size
                if start >= size:
                    return []
                f.seek(start)
                text = f.read().decode("utf-8", errors="replace")
        except OSError:
            return []
        return [ln for ln in text.splitlines() if _ERROR_LINE.search(ln)]

    # ---- evidence collection ------------------------------------------------

    def collect_error_logs(self, max_lines: int = 64) -> List[str]:
        """Tail the worker log for error-ish lines (reference
        training_log_collector)."""
        if not self._log_path or not os.path.exists(self._log_path):
            return []
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 256 * 1024))
                text = f.read().decode("utf-8", errors="replace")
        except OSError:
            return []
        lines = [ln for ln in text.splitlines() if _ERROR_LINE.search(ln)]
        return lines[-max_lines:]

    # ---- periodic reporting -------------------------------------------------

    def start(self):
        if self._client is None:
            return
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._report_loop, name="diagnosis-agent", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _report_loop(self):
        while not self._stopped.is_set():
            if self._stopped.wait(self._report_interval_s):
                return
            try:
                logs = self.collect_error_logs()
                if logs:
                    self._client.report_diagnosis_data(
                        DiagnosisDataType.TRAINING_LOG,
                        {"logs": logs, "node_rank": self._node_id},
                    )
            except Exception:
                logger.warning("diagnosis report failed", exc_info=True)
