"""Metrics-file training monitor: zero-code-change step reporting.

Parity: reference elastic_agent/monitor/training.py:75
(TorchTrainingMonitor) — a training loop that does NOT use this repo's
trainer library (and therefore never talks RPC) can still feed the
master's perf/goodput accounting by appending JSON lines to a metrics
file; the AGENT tails the file and reports global steps upstream.

Worker side (any framework, no imports from this repo required):

    with open(os.environ["DLROVER_TPU_METRICS_FILE"], "a") as f:
        f.write(json.dumps({"step": step, "ts": time.time()}) + "\\n")

or use the helper ``report_step`` below. Agent side: ``run.py`` starts
a TrainingMonitor when DLROVER_TPU_METRICS_FILE is set.
"""

import json
import os
import threading
import time
from typing import Optional

from dlrover_tpu.common.log import logger

METRICS_FILE_ENV = "DLROVER_TPU_METRICS_FILE"


def report_step(step: int, **extra):
    """Worker-side helper: append a step record to the metrics file
    (no-op when the env is absent, so library code can always call)."""
    path = os.getenv(METRICS_FILE_ENV, "")
    if not path:
        return
    record = {"step": int(step), "ts": time.time()}
    record.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


class TrainingMonitor:
    """Agent-resident tail loop over the metrics file; reports the
    newest global step to the master on an interval."""

    def __init__(
        self,
        client,
        metrics_path: str,
        interval: float = 15.0,
    ):
        self._client = client
        self._path = metrics_path
        self._interval = interval
        self._offset = 0  # BYTE offset (the file is read in binary)
        self._inode: Optional[int] = None
        self._last_reported = -1
        self._start_ts: Optional[float] = None
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # poll_once is called from the tail thread AND from shutdown
        # flushes; the offset bookkeeping must never run concurrently.
        self._poll_lock = threading.Lock()
        from dlrover_tpu.observability.registry import default_registry

        self._resets_counter = default_registry().counter(
            "training_monitor_tail_resets_total",
            "metrics-file truncations/rotations the tail loop recovered "
            "from",
        )

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="training-monitor"
            )
            self._thread.start()
            logger.info("training monitor tailing %s", self._path)

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 5)

    def _reset_tail(self):
        """Back to the top of the (new) file; a restarted worker may
        REPLAY earlier steps (resumed from its checkpoint) — the step
        watermark must reset with the offset or the master sees a
        frozen global step for the whole replayed range."""
        self._offset = 0
        self._last_reported = -1
        self._start_ts = None
        self._resets_counter.inc()

    def _read_new_records(self):
        try:
            stat = os.stat(self._path)
        except OSError:
            return []
        size = stat.st_size
        if self._inode is None:
            self._inode = stat.st_ino
        elif stat.st_ino != self._inode:
            # Rotated (rename + recreate): the new file can be LARGER
            # than the old offset, so a size check alone would silently
            # read from the middle of it forever.
            self._inode = stat.st_ino
            self._reset_tail()
        if size < self._offset:
            # Truncated in place.
            self._reset_tail()
        if size == self._offset:
            return []
        # Binary read: offsets are byte positions, immune to non-ASCII
        # JSON from third-party writers.
        with open(self._path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read()
            # Only consume complete lines; a mid-write tail stays for
            # the next poll.
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                return []
            self._offset += last_nl + 1
            chunk = chunk[: last_nl + 1]
        records = []
        for line in chunk.decode("utf-8", errors="replace").splitlines():
            try:
                records.append(json.loads(line))
            except ValueError:
                continue
        return records

    def poll_once(self) -> Optional[int]:
        """Read new records and report the newest step; returns it."""
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> Optional[int]:
        records = self._read_new_records()
        steps = [
            r["step"]
            for r in records
            if isinstance(r.get("step"), int)
        ]
        if not records:
            return None
        if self._start_ts is None:
            self._start_ts = records[0].get("ts", time.time())
        if not steps:
            return None
        newest = max(steps)
        if newest > self._last_reported:
            self._last_reported = newest
            elapsed = max(
                records[-1].get("ts", time.time()) - self._start_ts, 0.0
            )
            try:
                self._client.report_global_step(newest, elapsed)
            except Exception:
                logger.warning("step report failed", exc_info=True)
        return newest

    def _run(self):
        while not self._stopped.wait(self._interval):
            try:
                self.poll_once()
            except Exception:
                logger.warning("training monitor poll failed", exc_info=True)
