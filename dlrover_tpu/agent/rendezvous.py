"""Agent-side rendezvous: from master comm-world to JAX coordination.

Parity: reference elastic_agent MasterRendezvousHandler
(elastic_agent/torch/training.py:405-646). Where torchelastic assembles a
process group store, this produces the ``jax.distributed.initialize``
triple: the lowest-rank node in the completed world hosts the JAX
coordinator; its agent publishes ``host:port`` in the master KV store keyed
by rendezvous round, and every agent derives contiguous process ids from
the world layout.
"""

import os
import time
from dataclasses import dataclass
from typing import Dict

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.env_utils import find_free_port, get_hostname_ip
from dlrover_tpu.common.log import logger


class RendezvousTimeoutError(Exception):
    pass


class RendezvousEvictedError(Exception):
    """This node was not chosen into the completed world."""


@dataclass
class RendezvousOutcome:
    round: int
    group: int  # pair group during network check; 0 for training
    world: Dict[int, int]  # node_rank -> local_world_size
    coordinator_address: str
    num_processes: int
    process_id_base: int  # first global process id of this node
    node_world_size: int  # number of nodes in the world
    is_coordinator: bool
    # Slices (node groups) in this world — 1 when ungrouped. With the
    # manager's group-major world order, a dcn mesh axis of this size
    # maps one group per slice row (parallel/mesh.py). Derived from the
    # master's node_groups (explicit DLROVER_TPU_NODE_GROUP or
    # node_unit arithmetic — whichever grouped the rendezvous).
    num_slices: int = 1


class MasterRendezvousHandler:
    def __init__(
        self,
        client: MasterClient,
        node_rank: int,
        local_world_size: int = 1,
        rdzv_name: str = RendezvousName.TRAINING,
        node_unit: int = 1,
        join_timeout: float = 600.0,
        poll_interval: float = 0.5,
        coordinator_port: int = 0,
    ):
        self._client = client
        self._node_rank = node_rank
        self._local_world_size = local_world_size
        self._rdzv_name = rdzv_name
        self._node_unit = node_unit
        self._join_timeout = join_timeout
        self._poll_interval = poll_interval
        self._coordinator_port = coordinator_port
        _, self._node_ip = get_hostname_ip()
        # TPU slice/block index of this host. Explicit env wins; with a
        # node_unit (hosts per slice) configured, the block is derived
        # from the rank so deployments need no extra wiring.
        group_env = os.getenv("DLROVER_TPU_NODE_GROUP", "")
        if group_env.strip():
            self._node_group = int(group_env)
        elif node_unit > 1:
            self._node_group = node_rank // node_unit
        else:
            self._node_group = -1

    def _coordinator_key(self, rdzv_round: int, group: int) -> str:
        return f"rdzv/{self._rdzv_name}/{rdzv_round}/{group}/coordinator"

    def next_rendezvous(self) -> RendezvousOutcome:
        """Join, wait for the world, agree on the JAX coordinator."""
        self._client.join_rendezvous(
            self._node_rank,
            self._local_world_size,
            self._rdzv_name,
            node_unit=self._node_unit,
            node_ip=self._node_ip,
            node_group=self._node_group,
        )
        deadline = time.time() + self._join_timeout
        world: Dict[int, int] = {}
        rank_order: list = []
        node_groups: Dict[int, int] = {}
        rdzv_round = 0
        group = 0
        while time.time() < deadline:
            rdzv_round, group, world, rank_order, node_groups = (
                self._client.get_comm_world(
                    self._rdzv_name, self._node_rank
                )
            )
            if world:
                if self._node_rank in world:
                    break
                # A round completed without us: we were truncated out
                # (illegal topology count) — surface as eviction so the
                # caller can rejoin or exit.
                raise RendezvousEvictedError(
                    f"node {self._node_rank} not in world {sorted(world)}"
                )
            time.sleep(self._poll_interval)
        if not world or self._node_rank not in world:
            raise RendezvousTimeoutError(
                f"rendezvous {self._rdzv_name} timed out after "
                f"{self._join_timeout}s"
            )

        # The master chooses the world ORDER (possibly topology-aware:
        # slice-mates adjacent, DCN hops only at block boundaries) and
        # sends it as an EXPLICIT rank list; global process ids follow
        # that order, not numeric node rank. Relying on the world dict's
        # insertion order surviving the transport would be fragile.
        ranks = rank_order if rank_order else list(world)
        if set(ranks) != set(world):
            raise RuntimeError(
                f"rank_order {ranks} disagrees with world {sorted(world)}; "
                "master/agent protocol mismatch"
            )
        num_processes = sum(world.values())
        my_pos = ranks.index(self._node_rank)
        process_id_base = sum(world[r] for r in ranks[:my_pos])
        coordinator_rank = ranks[0]
        is_coordinator = coordinator_rank == self._node_rank
        key = self._coordinator_key(rdzv_round, group)
        if is_coordinator:
            port = self._coordinator_port or find_free_port()
            coordinator = f"{self._node_ip}:{port}"
            self._client.kv_store_set(key, coordinator.encode())
        else:
            coordinator = self._wait_coordinator(key, deadline)
        logger.info(
            "rdzv[%s] round %d: world=%s coordinator=%s procs=%d base=%d",
            self._rdzv_name,
            rdzv_round,
            world,
            coordinator,
            num_processes,
            process_id_base,
        )
        return RendezvousOutcome(
            round=rdzv_round,
            group=group,
            world=dict(world),
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id_base=process_id_base,
            node_world_size=len(world),
            is_coordinator=is_coordinator,
            num_slices=self._derive_num_slices(world, node_groups),
        )

    def _derive_num_slices(self, world, node_groups) -> int:
        """Distinct node groups in the world (explicit env grouping or
        node_unit arithmetic — the master reports whichever grouped the
        round); falls back to node_unit division for old masters.

        A dcn mesh row must hold exactly one slice, so the grouping only
        counts when it PARTITIONS the world into equal-sized groups with
        no ungrouped nodes — an uneven world (mid-failover, or one host
        missing its group env) would otherwise get a mesh whose
        "intra-slice" collectives silently cross DCN. Such worlds run as
        a single slice instead.
        """
        groups = {r: g for r, g in (node_groups or {}).items() if r in world}
        if groups and len(groups) == len(world):
            ids = list(groups.values())
            if min(ids) >= 0:
                counts = {}
                for g in ids:
                    counts[g] = counts.get(g, 0) + 1
                if len(set(counts.values())) == 1:
                    return len(counts)
                logger.warning(
                    "uneven node groups %s — running as one slice",
                    counts,
                )
                return 1
        if self._node_unit > 1 and len(world) % self._node_unit == 0:
            return len(world) // self._node_unit
        return 1

    def _wait_coordinator(self, key: str, deadline: float) -> str:
        while time.time() < deadline:
            value = self._client.kv_store_get(key)
            if value:
                return value.decode()
            time.sleep(self._poll_interval)
        raise RendezvousTimeoutError(
            f"coordinator address never published under {key}"
        )

    def num_nodes_waiting(self) -> int:
        try:
            return self._client.num_nodes_waiting(self._rdzv_name)
        except Exception:
            return 0
