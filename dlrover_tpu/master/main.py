"""Master process entry point.

Parity: reference dlrover/python/master/main.py. Run as
``python -m dlrover_tpu.master.main --platform local --node_num 2``.
"""

import os
import signal
import sys

from dlrover_tpu.common.log import logger
from dlrover_tpu.master.args import parse_master_args


def _install_sigterm(master):
    """SIGTERM = graceful shutdown (DESIGN.md §37): the run loop exits
    on the stop flag and stop() drains the server, runs journal
    flush+fsync hooks, and writes the clean-shutdown close record."""

    def _on_term(signum, frame):
        logger.info("SIGTERM received: requesting graceful master stop")
        req = getattr(master, "request_stop", None)
        (req or master.stop)()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not on the main thread (embedded use) — caller owns signals


def run(args) -> int:
    if args.platform == "local":
        from dlrover_tpu.master.local_master import LocalJobMaster

        batch_config = None
        if args.global_batch_size > 0 and args.micro_batch_per_device > 0:
            from dlrover_tpu.trainer.elastic.trainer import (
                ElasticBatchConfig,
            )

            batch_config = ElasticBatchConfig(
                global_batch_size=args.global_batch_size,
                micro_batch_per_device=args.micro_batch_per_device,
            )
        master = LocalJobMaster(
            port=args.port,
            job_name=args.job_name,
            node_num=args.node_num,
            max_relaunch_count=args.max_relaunch_count,
            transport=args.transport,
            batch_config=batch_config,
            devices_per_node=args.devices_per_node,
            autoscale_loop=getattr(args, "autoscale_loop", False),
            autoscale_dry_run=getattr(
                args, "autoscale_dry_run", False
            ),
            autoscale_interval_s=getattr(
                args, "autoscale_interval_s", 5.0
            ),
            autoscale_record=getattr(args, "autoscale_record", ""),
        )
    else:
        try:
            from dlrover_tpu.master.dist_master import DistributedJobMaster
        except ImportError as e:
            raise SystemExit(
                f"platform {args.platform!r} requires the distributed "
                f"master which is unavailable: {e}"
            )
        master = DistributedJobMaster.from_args(args)
    master.prepare()
    _install_sigterm(master)
    if args.port_file:
        # Publish the port before any blocking pre-check: agents need it
        # to reach the master, and the connection pre-check needs agents.
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(master.port))
        os.rename(tmp, args.port_file)
    if args.pre_check and hasattr(master, "pre_check"):
        if not master.pre_check():
            logger.error("pre-check failed; aborting job")
            master.stop()
            return 1
    return master.run()


def main(argv=None) -> int:
    args = parse_master_args(argv)
    logger.info("starting dlrover-tpu master: %s", vars(args))
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
