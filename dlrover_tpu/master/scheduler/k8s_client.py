"""Thin Kubernetes API wrapper used by the k8s scaler/watcher.

Parity: reference dlrover/python/scheduler/kubernetes.py (k8sClient
singleton). The ``kubernetes`` package is not a hard dependency: the
surface the scaler/watcher need is narrow (pods + custom objects), so it
is defined here as plain methods and backed either by the real client
(when installed, in-cluster or kubeconfig) or by an injected fake in
tests — the reference's mock_k8s_client pattern (tests/test_utils.py:321).
"""

import threading
from typing import Dict, Iterator, List, Optional

from dlrover_tpu.common.log import logger

ELASTICJOB_GROUP = "elastic.iml.github.io"
ELASTICJOB_VERSION = "v1alpha1"
SCALEPLAN_PLURAL = "scaleplans"
ELASTICJOB_PLURAL = "elasticjobs"


class K8sApi:
    """The narrow API surface; a fake implements exactly these methods."""

    def create_pod(self, namespace: str, pod_manifest: Dict) -> bool:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str) -> bool:
        raise NotImplementedError

    def list_pods(self, namespace: str, label_selector: str) -> List[Dict]:
        raise NotImplementedError

    def watch_pods(
        self, namespace: str, label_selector: str
    ) -> Iterator[Dict]:
        """Yield {"type": ADDED|MODIFIED|DELETED, "object": pod_dict}."""
        raise NotImplementedError

    def create_custom_object(
        self, namespace: str, plural: str, body: Dict
    ) -> bool:
        raise NotImplementedError

    def list_custom_objects(self, namespace: str, plural: str) -> List[Dict]:
        raise NotImplementedError

    def watch_custom_objects(
        self, namespace: str, plural: str
    ) -> Iterator[Dict]:
        """Yield {"type": ADDED|MODIFIED|DELETED, "object": cr_dict}."""
        raise NotImplementedError

    def patch_custom_object_status(
        self, namespace: str, plural: str, name: str, status: Dict
    ) -> bool:
        raise NotImplementedError

    def delete_custom_object(
        self, namespace: str, plural: str, name: str
    ) -> bool:
        raise NotImplementedError

    def create_service(self, namespace: str, manifest: Dict) -> bool:
        raise NotImplementedError

    def get_service(self, namespace: str, name: str) -> Optional[Dict]:
        raise NotImplementedError

    def delete_service(self, namespace: str, name: str) -> bool:
        raise NotImplementedError


class RealK8sApi(K8sApi):
    """Backed by the official kubernetes client (lazy import)."""

    def __init__(self):
        import kubernetes  # gated: raises if not installed

        try:
            kubernetes.config.load_incluster_config()
        except Exception:
            kubernetes.config.load_kube_config()
        self._core = kubernetes.client.CoreV1Api()
        self._custom = kubernetes.client.CustomObjectsApi()
        self._watch = kubernetes.watch

    def create_pod(self, namespace, pod_manifest):
        try:
            self._core.create_namespaced_pod(namespace, pod_manifest)
            return True
        except Exception:
            logger.exception("pod create failed")
            return False

    def delete_pod(self, namespace, name):
        try:
            self._core.delete_namespaced_pod(name, namespace)
            return True
        except Exception:
            logger.warning("pod delete failed: %s", name)
            return False

    def list_pods(self, namespace, label_selector):
        resp = self._core.list_namespaced_pod(
            namespace, label_selector=label_selector
        )
        return [
            self._core.api_client.sanitize_for_serialization(item)
            for item in resp.items
        ]

    def watch_pods(self, namespace, label_selector):
        w = self._watch.Watch()
        for event in w.stream(
            self._core.list_namespaced_pod,
            namespace,
            label_selector=label_selector,
        ):
            obj = self._core.api_client.sanitize_for_serialization(
                event["object"]
            )
            yield {"type": event["type"], "object": obj}

    def create_custom_object(self, namespace, plural, body):
        try:
            self._custom.create_namespaced_custom_object(
                ELASTICJOB_GROUP,
                ELASTICJOB_VERSION,
                namespace,
                plural,
                body,
            )
            return True
        except Exception:
            logger.exception("custom object create failed")
            return False

    def list_custom_objects(self, namespace, plural):
        try:
            resp = self._custom.list_namespaced_custom_object(
                ELASTICJOB_GROUP, ELASTICJOB_VERSION, namespace, plural
            )
            return resp.get("items", [])
        except Exception:
            logger.exception("custom object list failed")
            return []

    def watch_custom_objects(self, namespace, plural):
        w = self._watch.Watch()
        for event in w.stream(
            self._custom.list_namespaced_custom_object,
            ELASTICJOB_GROUP,
            ELASTICJOB_VERSION,
            namespace,
            plural,
        ):
            yield {"type": event["type"], "object": event["object"]}

    def patch_custom_object_status(self, namespace, plural, name, status):
        try:
            self._custom.patch_namespaced_custom_object_status(
                ELASTICJOB_GROUP,
                ELASTICJOB_VERSION,
                namespace,
                plural,
                name,
                {"status": status},
            )
            return True
        except Exception:
            logger.warning("status patch failed: %s", name)
            return False

    def delete_custom_object(self, namespace, plural, name):
        try:
            self._custom.delete_namespaced_custom_object(
                ELASTICJOB_GROUP,
                ELASTICJOB_VERSION,
                namespace,
                plural,
                name,
            )
            return True
        except Exception:
            logger.warning("custom object delete failed: %s", name)
            return False

    def create_service(self, namespace, manifest):
        try:
            self._core.create_namespaced_service(namespace, manifest)
            return True
        except Exception:
            logger.exception("service create failed")
            return False

    def get_service(self, namespace, name):
        try:
            svc = self._core.read_namespaced_service(name, namespace)
            return self._core.api_client.sanitize_for_serialization(svc)
        except Exception:
            return None

    def delete_service(self, namespace, name):
        try:
            self._core.delete_namespaced_service(name, namespace)
            return True
        except Exception:
            logger.warning("service delete failed: %s", name)
            return False


_api: Optional[K8sApi] = None
_api_lock = threading.Lock()


def get_k8s_api() -> K8sApi:
    global _api
    with _api_lock:
        if _api is None:
            _api = RealK8sApi()
        return _api


def set_k8s_api(api: Optional[K8sApi]):
    """Inject a fake (tests) or reset."""
    global _api
    with _api_lock:
        _api = api
