"""Master RPC servicer: dispatches the get/report protocol to managers.

Parity: reference dlrover/python/master/servicer.py (MasterServicer:89,
dispatch by message type :152-208/:438-500). Dispatch here is an explicit
type->handler table instead of method-name reflection, so the full RPC
surface is greppable.
"""

import base64
import time
from typing import Dict, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.comm import Message
from dlrover_tpu.common.constants import (
    PreCheckStatus,
    RendezvousName,
    TaskType,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.elastic_training.elastic_ps import ClusterVersionService
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.fault import fault_point
from dlrover_tpu.master.overload import OverloadGovernor
from dlrover_tpu.master.rpc_metrics import RpcTelemetry, clocks
from dlrover_tpu.observability import tracing
from dlrover_tpu.rpc.transport import MasterService


class MasterServicer(MasterService):
    def __init__(
        self,
        rdzv_managers: Dict[str, RendezvousManager],
        task_manager=None,
        job_manager=None,
        diagnosis_master=None,
        perf_monitor=None,
        sync_service: Optional[SyncService] = None,
        kv_store: Optional[KVStoreService] = None,
        job_metric_collector=None,
        elastic_ps_service: Optional[ClusterVersionService] = None,
        rescale_coordinator=None,
        trace_aggregator=None,
        overload_governor: Optional[OverloadGovernor] = None,
        journal=None,
    ):
        # Durable master journal (docs/DESIGN.md §37): when present,
        # every state transition that must survive a master crash is
        # appended BEFORE the reply leaves, and its master_epoch is
        # stamped into every response for worker-side fencing.
        self._journal = journal
        self._master_epoch = (
            journal.master_epoch if journal is not None else -1
        )
        self._dataset_params: Dict[str, dict] = {}
        self._journal_rdzv: Dict[str, dict] = {}
        if journal is not None and journal.recovered is not None:
            for name, replay in journal.recovered.datasets.items():
                self._dataset_params[name] = dict(replay.params)
            self._journal_rdzv = {
                name: dict(committed)
                for name, committed in journal.recovered.rdzv.items()
            }
            if getattr(journal, "_snapshot_fn", None) is None:
                journal._snapshot_fn = self.journal_snapshot
        self._rescale_coordinator = rescale_coordinator
        # Recent trace trees served at /api/traces: fed by workers
        # pushing drained spans over DiagnosisDataReport and by the
        # master's own armed tracer (the master wires its tracer's
        # on_finish to the aggregator at construction).
        self._trace_aggregator = trace_aggregator
        self._rdzv_managers = rdzv_managers
        self._task_manager = task_manager
        self._job_manager = job_manager
        self._diagnosis_master = diagnosis_master
        self._perf_monitor = perf_monitor
        self._sync_service = sync_service or SyncService()
        self._kv_store = kv_store or KVStoreService()
        # Per-job random secret for the agents' checkpoint-replica HTTP
        # exchange (flash_ckpt/replica.py): not derivable from job
        # metadata, though anyone who can reach the master's (itself
        # unauthenticated) KV RPC can still read it — operators wanting a
        # secret outside that trust domain set DLROVER_TPU_REPLICA_TOKEN.
        from dlrover_tpu.common.constants import CheckpointConstant

        if not self._kv_store.get(CheckpointConstant.REPLICA_TOKEN_KEY):
            import secrets

            token = secrets.token_hex(16).encode()
            self._kv_store.set(
                CheckpointConstant.REPLICA_TOKEN_KEY, token
            )
            # Journal the seed so the token survives a master restart —
            # agents that cached it mid-job must keep matching.
            self._journal_kv_set(CheckpointConstant.REPLICA_TOKEN_KEY, token)
        self._job_metric_collector = job_metric_collector
        self._elastic_ps_service = elastic_ps_service or ClusterVersionService()
        self._pre_check_status = PreCheckStatus.PASS
        self._elastic_run_config: Dict[str, str] = {}
        self._start_time = time.time()
        # node_id -> wall time of its last RPC; the connection pre-check
        # uses "has talked to the master at all" as the liveness signal
        # (agents poll wait_pre_check before their first heartbeat).
        self._node_last_contact: Dict[int, float] = {}

        self._get_handlers = {
            comm.CommWorldRequest: self._get_comm_world,
            comm.NumNodesWaitingRequest: self._num_nodes_waiting,
            comm.FaultNodeRequest: self._get_fault_nodes,
            comm.StragglerRequest: self._get_stragglers,
            comm.KVStoreGetRequest: self._kv_get,
            comm.KVStoreMultiGetRequest: self._kv_multi_get,
            comm.KVStoreAddRequest: self._kv_add,
            comm.SyncQueryRequest: self._sync_query,
            comm.TaskRequest: self._get_task,
            comm.MultiTaskRequest: self._get_tasks,
            comm.ShardCheckpointRequest: self._get_shard_checkpoint,
            comm.CkptLatestStepRequest: self._get_ckpt_latest_step,
            comm.PreCheckRequest: self._get_pre_check_result,
            comm.ParallelConfigRequest: self._get_parallel_config,
            comm.ElasticRunConfigRequest: self._get_elastic_run_config,
            comm.JobDetailRequest: self._get_job_detail,
            comm.ClusterVersionRequest: self._get_cluster_version,
            comm.RescalePlanRequest: self._get_rescale_plan,
            comm.RescaleBarrierRequest: self._get_rescale_barrier,
        }
        self._report_handlers = {
            comm.JoinRendezvousRequest: self._join_rendezvous,
            comm.NetworkReadyRequest: self._network_ready,
            comm.NetworkCheckResultReport: self._report_network_check,
            comm.HeartbeatReport: self._report_heartbeat,
            comm.NodeFailureReport: self._report_node_failure,
            comm.SucceededRequest: self._report_succeeded,
            comm.NodeEventReport: self._report_node_event,
            comm.ResourceStats: self._report_resource_stats,
            comm.GlobalStepReport: self._report_global_step,
            comm.GoodputPhaseReport: self._report_goodput_phase,
            comm.KVStoreSetRequest: self._kv_set,
            comm.SyncJoinRequest: self._sync_join,
            comm.SyncFinishRequest: self._sync_finish,
            comm.DatasetShardParams: self._report_dataset_params,
            comm.TaskDoneReport: self._report_task_done,
            comm.TaskDoneBatchReport: self._report_tasks_done_batch,
            comm.ShardCheckpointRestoreRequest: self._restore_shard_checkpoint,
            comm.CkptStepReport: self._report_ckpt_step,
            comm.DiagnosisDataReport: self._report_diagnosis_data,
            comm.ClusterVersionReport: self._report_cluster_version,
            comm.RescaleJoinReport: self._report_rescale_join,
            comm.RescaleAckReport: self._report_rescale_ack,
        }
        # §32 per-verb telemetry + overload accounting. The verb label
        # set is exactly the registered handler types (+ the "other"
        # collapse bucket), so exposition cardinality is bounded by
        # construction no matter what arrives on the wire.
        self._telemetry = RpcTelemetry(
            t.__name__
            for t in (
                list(self._get_handlers) + list(self._report_handlers)
            )
        )
        self._overload = overload_governor or OverloadGovernor()

    # ---- transport entry points -------------------------------------------

    def node_last_contact(self) -> Dict[int, float]:
        return dict(self._node_last_contact)

    @property
    def telemetry(self) -> RpcTelemetry:
        return self._telemetry

    @property
    def overload_governor(self) -> OverloadGovernor:
        return self._overload

    def get(self, message: Message) -> Message:
        reply, name = self._dispatch(message, self._get_handlers, "get")
        # AFTER the handler: any state mutation (lease moved to doing,
        # kv value read) already happened — dropping the reply here is
        # the "response lost on the wire" fault the client-side retry
        # and the master's timeout recovery must absorb.
        fault_point("rpc.get.drop_reply", request=name)
        return reply

    def report(self, message: Message) -> Message:
        reply, name = self._dispatch(
            message, self._report_handlers, "report"
        )
        # State already applied; a dropped reply makes the client
        # re-send — report handlers must stay safe to re-apply
        # (at-most-once effect), which the chaos soak asserts.
        fault_point("rpc.report.drop_reply", request=name)
        return reply

    def _dispatch(
        self, message: Message, handlers, kind: str
    ) -> "tuple":
        """One instrumented dispatch: deserialize → admission → handler
        → serialize, with the §32 split timed so lock contention shows
        up as handler time, and the server span covering the SAME
        window as ``master_rpc_seconds`` (the soak asserts they agree
        within 15%). Returns ``(reply, request_type_name)`` — the
        caller fires its drop_reply fault point (a literal site the
        taxonomy test greps for) before handing the reply to the
        transport."""
        self._node_last_contact[message.node_id] = time.time()
        wall0 = time.time()
        t0, cpu0 = clocks()
        request = (
            comm.BaseRequest.deserialize(message.data)
            if message.data
            else comm.BaseRequest()
        )
        t_deser = time.monotonic()
        name = type(request).__name__
        tm = self._telemetry
        verb = tm.verb(name)
        tm.begin(verb)
        error_kind = None
        dropped = False
        handler_s = None  # stays None when the handler never runs
        serialize_s = 0.0
        try:
            handler = handlers.get(type(request))
            shed_class = (
                self._overload.admit(name) if handler is not None else None
            )
            if handler is not None and shed_class is None:
                # Server span parented to the caller's envelope
                # context: the worker's client RPC span and this
                # handler span share one trace. Disarmed: one global
                # check, a no-op object. Back-dated to the
                # pre-deserialize clock and exited after serialize, so
                # span duration == master_rpc_seconds duration (the
                # soak's 15%-agreement invariant).
                span = tracing.server_span(
                    f"master.{name}",
                    getattr(message, "trace", None),
                    start_mono=t0,
                    start_wall=wall0,
                    node_id=message.node_id,
                )
            else:
                span = tracing.NOOP_SPAN
            with span:
                if handler is None:
                    error_kind = "no_handler"
                    response = comm.BaseResponse(
                        success=False,
                        reason=f"no {kind} handler for {type(request)}",
                    )
                elif shed_class is not None:
                    # Graceful degradation: answered, not handled. Only
                    # diagnostic/telemetry classes can reach here — the
                    # governor admits critical verbs unconditionally.
                    dropped = True
                    response = comm.BaseResponse(
                        success=False,
                        reason=f"overload: shed {shed_class} traffic",
                    )
                else:
                    th0 = time.monotonic()
                    try:
                        response = handler(message, request)
                    except Exception as e:
                        error_kind = type(e).__name__
                        raise
                    finally:
                        handler_s = time.monotonic() - th0
                        self._overload.observe(
                            handler_s, tm.inflight_now()
                        )
                ts0 = time.monotonic()
                if self._master_epoch >= 0:
                    # Epoch fencing (§37): every response carries the
                    # journal's monotone master_epoch so a worker can
                    # tell a restarted master from the one it knew.
                    try:
                        response.master_epoch = self._master_epoch
                    except (AttributeError, TypeError):
                        pass
                reply = Message(
                    node_id=message.node_id, data=response.serialize()
                )
                serialize_s = time.monotonic() - ts0
        finally:
            t_end, cpu_end = clocks()
            tm.end(
                verb,
                total_s=t_end - t0,
                deserialize_s=t_deser - t0,
                handler_s=handler_s,
                serialize_s=serialize_s,
                cpu_s=max(cpu_end - cpu0, 0.0),
                error_kind=error_kind,
                dropped=dropped,
            )
        return reply, name

    def control_plane_state(self) -> Dict:
        """The §32 saturation view behind ``/api/control_plane``:
        overload governor state, per-verb RPC telemetry, and every
        bounded buffer's occupancy + drop counters."""
        buffers: Dict[str, Dict] = {}
        if self._trace_aggregator is not None:
            buffers["trace_aggregator"] = self._trace_aggregator.stats()
        if self._perf_monitor is not None:
            stats = getattr(self._perf_monitor, "buffer_stats", None)
            if callable(stats):
                buffers["perf_phase_records"] = stats()
        if self._task_manager is not None:
            stats = getattr(self._task_manager, "queue_stats", None)
            if callable(stats):
                buffers["task_queues"] = stats()
        size = getattr(self._kv_store, "size", None)
        if callable(size):
            buffers["kv_store"] = {
                "occupancy": size(),
                "drops": 0,  # unbounded dict today; 0 by definition
            }
        if self._journal is not None:
            buffers["journal"] = self._journal.stats()
        return {
            "overload": self._overload.state(),
            "rpc": self._telemetry.summary(),
            "buffers": buffers,
            "nodes_seen": len(self._node_last_contact),
            "uptime_s": round(time.time() - self._start_time, 3),
        }

    # ---- journal hooks (docs/DESIGN.md §37) -------------------------------

    @property
    def master_epoch(self) -> int:
        return self._master_epoch

    def _journal_kv_set(self, key: str, value: bytes):
        if self._journal is not None:
            self._journal.append(
                "kv_set",
                key=key,
                val=base64.b64encode(value).decode("ascii"),
            )

    def _journal_dispatch(self, node_id: int, tasks):
        """One group commit covering every real lease in the batch; the
        WAL order is mutate → journal → reply, so both crash windows
        keep exactly-once (pre-journal: the worker never got the reply
        and the shard is regenerated; post-journal: the lease replays
        into ``doing`` and either the rider's done-report pops it or
        timeout recovery re-queues it)."""
        if self._journal is None:
            return
        recs = [
            {
                "kind": "dispatch",
                "ds": t.dataset_name,
                "tid": t.task_id,
                "node": node_id,
                "epoch": t.epoch,
                "start": t.start,
                "end": t.end,
                "idx": t.record_indices,
                "part": t.partition,
            }
            for t in tasks
            if t.task_id >= 0
        ]
        if recs:
            self._journal.append_many(recs)

    def journal_snapshot(self) -> dict:
        """Lease-preserving full-state snapshot for journal compaction
        (original task ids survive, so compaction never breaks the
        exactly-once law). Reads each component under its own lock; the
        coordinator counters are read lock-free (monotone ints)."""
        snap: Dict[str, object] = {
            "datasets": {},
            "kv": {},
            "ckpt_step": -1,
            "plan_seq": 0,
            "rdzv": {
                name: {
                    "round": committed.get("round", 0),
                    "world": {
                        str(r): n
                        for r, n in (committed.get("world") or {}).items()
                    },
                }
                for name, committed in self._journal_rdzv.items()
            },
            "sync": {},
        }
        if self._task_manager is not None:
            snapshots = getattr(
                self._task_manager, "journal_snapshots", None
            )
            if callable(snapshots):
                for name, per in snapshots().items():
                    entry = dict(per)
                    entry["params"] = self._dataset_params.get(name, {})
                    snap["datasets"][name] = entry
        dump = getattr(self._kv_store, "dump", None)
        if callable(dump):
            snap["kv"] = {
                k: base64.b64encode(v).decode("ascii")
                for k, v in dump().items()
            }
        coord = self._rescale_coordinator
        if coord is not None:
            snap["plan_seq"] = int(getattr(coord, "_plan_seq", 0))
            snap["ckpt_step"] = int(getattr(coord, "_committed_step", -1))
        sync_snap = getattr(self._sync_service, "journal_snapshot", None)
        if callable(sync_snap):
            snap["sync"] = sync_snap()
        return snap

    # ---- rendezvous --------------------------------------------------------

    def _join_rendezvous(self, msg, req: comm.JoinRendezvousRequest):
        mgr = self._rdzv_managers.get(req.rdzv_name)
        if mgr is None:
            return comm.BaseResponse(False, f"unknown rdzv {req.rdzv_name}")
        mgr.set_node_unit(req.node_unit)
        rdzv_round = mgr.join_rendezvous(
            req.node_id,
            req.node_rank,
            req.local_world_size,
            req.node_ip,
            req.node_group,
        )
        if self._job_manager is not None:
            self._job_manager.handle_node_joined(req.node_id, req.node_rank)
        return comm.JoinRendezvousResponse(round=rdzv_round)

    def _get_comm_world(self, msg, req: comm.CommWorldRequest):
        mgr = self._rdzv_managers.get(req.rdzv_name)
        if mgr is None:
            return comm.BaseResponse(False, f"unknown rdzv {req.rdzv_name}")
        atomic = getattr(mgr, "get_comm_world_and_groups", None)
        if atomic is not None:
            rdzv_round, group, world, node_groups = atomic(req.node_id)
        else:
            rdzv_round, group, world = mgr.get_comm_world(req.node_id)
            node_groups = {}
        rank_order = list(world)
        if self._journal is not None and world:
            last = self._journal_rdzv.get(req.rdzv_name, {})
            if last.get("round") != rdzv_round:
                committed = {"round": rdzv_round, "world": dict(world)}
                self._journal_rdzv[req.rdzv_name] = committed
                self._journal.append(
                    "rdzv",
                    name=req.rdzv_name,
                    round=rdzv_round,
                    world={str(r): n for r, n in world.items()},
                )
        return comm.CommWorld(
            round=rdzv_round,
            group=group,
            world=world,
            coordinator_rank=rank_order[0] if rank_order else -1,
            rank_order=rank_order,
            node_groups=node_groups,
        )

    def _num_nodes_waiting(self, msg, req: comm.NumNodesWaitingRequest):
        mgr = self._rdzv_managers.get(req.rdzv_name)
        waiting = mgr.num_nodes_waiting() if mgr else 0
        return comm.NumNodesWaitingResponse(waiting_num=waiting)

    # ---- live rescale ------------------------------------------------------

    def _report_rescale_join(self, msg, req: comm.RescaleJoinReport):
        if self._rescale_coordinator is None:
            return comm.BaseResponse(False, "no rescale coordinator")
        self._rescale_coordinator.note_worker_joined(
            req.node_rank,
            req.local_world_size,
            node_group=getattr(req, "node_group", -1),
        )
        return comm.BaseResponse(True)

    def _get_rescale_plan(self, msg, req: comm.RescalePlanRequest):
        if self._rescale_coordinator is None:
            return comm.RescalePlanResponse()
        plan = self._rescale_coordinator.get_plan(
            req.node_rank, req.current_plan_id
        )
        if plan is None:
            return comm.RescalePlanResponse()
        # Chaos site: the plan broadcast to THIS worker is dropped on
        # the wire (raise -> transport error client-side). The pull
        # protocol absorbs it: the worker's next poll re-fetches the
        # same versioned plan.
        fault_point(
            "rescale.plan.broadcast",
            plan_id=plan.plan_id,
            rank=req.node_rank,
        )
        return comm.RescalePlanResponse(
            plan_id=plan.plan_id,
            world=dict(plan.world),
            rank_order=list(plan.rank_order),
            restore_step=plan.restore_step,
            reason=plan.reason,
            created_at=plan.created_at,
            barrier_timeout_s=plan.barrier_timeout_s,
        )

    def _report_rescale_ack(self, msg, req: comm.RescaleAckReport):
        if self._rescale_coordinator is None:
            return comm.BaseResponse(False, "no rescale coordinator")
        ok = self._rescale_coordinator.ack(
            req.plan_id, req.node_rank, req.phase
        )
        return comm.BaseResponse(
            ok, "" if ok else "stale plan or unknown rank/phase"
        )

    def _get_rescale_barrier(self, msg, req: comm.RescaleBarrierRequest):
        if self._rescale_coordinator is None:
            return comm.RescaleBarrierResponse()
        ready, expired, superseded, missing = (
            self._rescale_coordinator.barrier_state(req.plan_id, req.phase)
        )
        return comm.RescaleBarrierResponse(
            ready=ready,
            expired=expired,
            superseded=superseded,
            missing=missing,
        )

    # ---- network check -----------------------------------------------------

    def _network_ready(self, msg, req):
        return comm.BaseResponse(True)

    def _report_network_check(self, msg, req: comm.NetworkCheckResultReport):
        mgr = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if isinstance(mgr, NetworkCheckRendezvousManager):
            mgr.report_network_check_result(
                req.node_rank, req.succeeded, req.result
            )
        return comm.BaseResponse(True)

    def _get_fault_nodes(self, msg, req):
        mgr = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if isinstance(mgr, NetworkCheckRendezvousManager):
            nodes, evaluated_round, needs_round2 = mgr.check_fault_node()
            return comm.FaultNodeResponse(
                fault_nodes=nodes,
                evaluated_round=evaluated_round,
                needs_round2=needs_round2,
            )
        return comm.FaultNodeResponse()

    def _get_stragglers(self, msg, req):
        mgr = self._rdzv_managers.get(RendezvousName.NETWORK_CHECK)
        if isinstance(mgr, NetworkCheckRendezvousManager):
            return comm.StragglerResponse(stragglers=mgr.check_straggler())
        return comm.StragglerResponse()

    # ---- heartbeat / diagnosis --------------------------------------------

    def _report_heartbeat(self, msg, req: comm.HeartbeatReport):
        actions = []
        if self._job_manager is not None:
            actions = self._job_manager.collect_node_heartbeat(
                req.node_id, req.timestamp
            )
        return comm.HeartbeatResponse(actions=actions or [])

    def _report_node_failure(self, msg, req: comm.NodeFailureReport):
        logger.warning(
            "node %d (rank %d) reported failure: %s exit=%d",
            req.node_id,
            req.node_rank,
            req.error_data,
            req.exit_code,
        )
        if self._job_manager is not None:
            self._job_manager.handle_node_failure(req)
        if (
            self._rescale_coordinator is not None
            and req.level == TrainingExceptionLevel.NODE_ERROR
        ):
            # A node-level failure means the rank is gone for good: fold
            # it out of the live set so the next plan excludes it.
            self._rescale_coordinator.note_worker_lost(req.node_rank)
        return comm.BaseResponse(True)

    def _report_succeeded(self, msg, req: comm.SucceededRequest):
        if self._job_manager is not None:
            self._job_manager.handle_node_succeeded(req.node_id)
        return comm.BaseResponse(True)

    def _report_node_event(self, msg, req: comm.NodeEventReport):
        if self._job_manager is not None:
            self._job_manager.handle_reported_node_event(req)
        return comm.BaseResponse(True)

    def _report_diagnosis_data(self, msg, req: comm.DiagnosisDataReport):
        from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

        if (
            req.data_type == DiagnosisDataType.TRACE_SPANS
            and self._trace_aggregator is not None
        ):
            # Worker span push (piggybacked on this existing verb):
            # feed /api/traces directly; the generic diagnosis store
            # still records the report below.
            self._trace_aggregator.ingest(req.payload.get("spans", ()))
        if self._diagnosis_master is not None:
            self._diagnosis_master.collect_diagnosis_data(req)
        return comm.BaseResponse(True)

    # ---- perf / resources --------------------------------------------------

    def _report_resource_stats(self, msg, req: comm.ResourceStats):
        if self._job_manager is not None:
            self._job_manager.update_node_resource_usage(req)
        return comm.BaseResponse(True)

    def _report_global_step(self, msg, req: comm.GlobalStepReport):
        if self._perf_monitor is not None:
            self._perf_monitor.collect_global_step(
                req.step,
                req.timestamp,
                req.elapsed_train_secs,
                node_id=req.node_id,
                step_time_s=getattr(req, "step_time_s", 0.0),
            )
        return comm.BaseResponse(True)

    def _report_goodput_phase(self, msg, req: comm.GoodputPhaseReport):
        if self._perf_monitor is not None:
            self._perf_monitor.collect_phase(
                req.node_id, req.phase, req.start, req.end
            )
        return comm.BaseResponse(True)

    # ---- kv store ----------------------------------------------------------

    def _kv_set(self, msg, req: comm.KVStoreSetRequest):
        # Journal BEFORE apply: a crash in between replays the set and
        # the client's retry re-applies it idempotently.
        self._journal_kv_set(req.key, req.value)
        self._kv_store.set(req.key, req.value)
        return comm.BaseResponse(True)

    def _kv_get(self, msg, req: comm.KVStoreGetRequest):
        return comm.KVStoreGetResponse(value=self._kv_store.get(req.key))

    def _kv_add(self, msg, req: comm.KVStoreAddRequest):
        # Apply-then-journal the RESULT (not the delta): kv_add is the
        # one deliberately unretried verb, so replaying the final value
        # can never double-count an increment (§37: a crash before the
        # journal write loses the add, and the client sees the error).
        value = self._kv_store.add(req.key, req.delta)
        self._journal_kv_set(req.key, str(value).encode())
        return comm.KVStoreAddResponse(value=value)

    def _kv_multi_get(self, msg, req: comm.KVStoreMultiGetRequest):
        return comm.KVStoreMultiGetResponse(
            values=self._kv_store.multi_get(req.keys)
        )

    # ---- sync --------------------------------------------------------------

    def _sync_join(self, msg, req: comm.SyncJoinRequest):
        if self._journal is not None:
            self._journal.append(
                "sync", name=req.sync_name, op="join", rank=req.node_rank
            )
        self._sync_service.join_sync(req.sync_name, req.node_rank)
        return comm.BaseResponse(True)

    def _sync_finish(self, msg, req: comm.SyncFinishRequest):
        if self._journal is not None:
            self._journal.append("sync", name=req.sync_name, op="finish")
        self._sync_service.sync_finished(req.sync_name)
        return comm.BaseResponse(True)

    def _sync_query(self, msg, req: comm.SyncQueryRequest):
        return comm.SyncQueryResponse(done=self._sync_service.query(req.sync_name))

    # ---- data sharding -----------------------------------------------------

    def _report_dataset_params(self, msg, req: comm.DatasetShardParams):
        if self._task_manager is not None:
            params = {
                f: getattr(req, f)
                for f in comm.DatasetShardParams.__dataclass_fields__
            }
            self._dataset_params[req.dataset_name] = params
            if (
                self._journal is not None
                and self._task_manager.get_dataset(req.dataset_name) is None
            ):
                self._journal.append("dataset", params=params)
            self._task_manager.new_dataset(req)
        return comm.BaseResponse(True)

    def _get_task(self, msg, req: comm.TaskRequest):
        if self._task_manager is None:
            return comm.ShardTask()
        task = self._task_manager.get_task(req.node_id, req.dataset_name)
        self._journal_dispatch(req.node_id, [task])
        return task

    def _get_tasks(self, msg, req: comm.MultiTaskRequest):
        if self._task_manager is None:
            return comm.MultiTaskResponse()
        tasks = self._task_manager.get_tasks(
            req.node_id, req.dataset_name, req.count
        )
        self._journal_dispatch(req.node_id, tasks)
        wait = bool(tasks) and tasks[0].task_type == TaskType.WAIT
        return comm.MultiTaskResponse(
            tasks=[] if wait else [t for t in tasks if t.task_id >= 0],
            wait=wait,
        )

    def _report_task_done(self, msg, req: comm.TaskDoneReport):
        if self._task_manager is not None:
            # Journal-first: losing an applied-but-unjournaled done
            # would re-queue a consumed shard on restart (double read);
            # replaying a journaled-but-unapplied done is idempotent.
            if self._journal is not None and req.task_id >= 0:
                self._journal.append(
                    "done",
                    ds=req.dataset_name,
                    node=req.node_id,
                    ok=[req.task_id] if req.success else [],
                    fail=[] if req.success else [req.task_id],
                )
            self._task_manager.report_task_done(
                req.dataset_name, req.task_id, req.node_id, req.success
            )
        return comm.BaseResponse(True)

    def _report_tasks_done_batch(self, msg, req: comm.TaskDoneBatchReport):
        if self._task_manager is not None:
            if self._journal is not None and (
                req.done_ids or req.failed_ids
            ):
                self._journal.append(
                    "done",
                    ds=req.dataset_name,
                    node=req.node_id,
                    ok=list(req.done_ids),
                    fail=list(req.failed_ids or []),
                )
            self._task_manager.report_tasks_done(
                req.dataset_name, req.node_id, req.done_ids, req.failed_ids
            )
        return comm.BaseResponse(True)

    def _get_shard_checkpoint(self, msg, req: comm.ShardCheckpointRequest):
        if self._task_manager is None:
            return comm.ShardCheckpointResponse(checkpoint="")
        ckpt = self._task_manager.get_shard_checkpoint(req.dataset_name)
        return comm.ShardCheckpointResponse(checkpoint=ckpt)

    def _restore_shard_checkpoint(
        self, msg, req: comm.ShardCheckpointRestoreRequest
    ):
        if self._task_manager is not None:
            if self._journal is not None and req.checkpoint:
                self._journal.append(
                    "shard_ckpt", ds=req.dataset_name, ckpt=req.checkpoint
                )
            self._task_manager.restore_shard_checkpoint(
                req.dataset_name, req.checkpoint
            )
        return comm.BaseResponse(True)

    # ---- checkpoint coordination ------------------------------------------

    def _report_ckpt_step(self, msg, req: comm.CkptStepReport):
        if self._job_manager is not None:
            self._job_manager.update_ckpt_step(req.node_id, req.step, req.committed)
        if self._rescale_coordinator is not None:
            # The coordinator tracks the committed frontier itself so a
            # rescale plan's restore_step works without a job manager
            # (soak harness, standalone masters).
            self._rescale_coordinator.note_ckpt_step(req.step, req.committed)
        if self._journal is not None and req.committed:
            # Only committed steps matter to a restarted master (the
            # monotone frontier a rescale plan's restore_step obeys).
            self._journal.append("ckpt_step", step=req.step)
        return comm.BaseResponse(True)

    def _get_ckpt_latest_step(self, msg, req):
        step = -1
        if self._job_manager is not None:
            step = self._job_manager.get_committed_ckpt_step()
        return comm.CkptLatestStepResponse(step=step)

    # ---- pre-check / config / detail --------------------------------------

    def set_pre_check_status(self, status: str):
        self._pre_check_status = status

    def _get_pre_check_result(self, msg, req):
        if self._diagnosis_master is not None:
            status = self._diagnosis_master.get_pre_check_status()
        else:
            status = self._pre_check_status
        return comm.PreCheckResponse(status=status)

    def set_elastic_run_config(self, config: Dict[str, str]):
        self._elastic_run_config = dict(config)

    def _get_elastic_run_config(self, msg, req):
        return comm.ElasticRunConfigResponse(configs=self._elastic_run_config)

    def _get_parallel_config(self, msg, req):
        if self._job_manager is not None:
            cfg = self._job_manager.get_parallel_config()
            if cfg is not None:
                return cfg
        return comm.ParallelConfig()

    def _get_job_detail(self, msg, req):
        if self._job_manager is not None:
            return self._job_manager.get_job_detail()
        return comm.JobDetailResponse()

    # ---- cluster version (PS parity) --------------------------------------

    def _get_cluster_version(self, msg, req: comm.ClusterVersionRequest):
        if req.version_type == ClusterVersionService.GLOBAL:
            v = self._elastic_ps_service.get_global_version()
        else:
            v = self._elastic_ps_service.get_node_version(
                req.task_type, req.task_id, req.version_type
            )
        return comm.ClusterVersionResponse(version=v)

    def _report_cluster_version(self, msg, req: comm.ClusterVersionReport):
        self._elastic_ps_service.update_node_version(
            req.task_type, req.task_id, req.version_type, req.version
        )
        return comm.BaseResponse(True)
