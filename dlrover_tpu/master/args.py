"""Master CLI args (parity: reference dlrover/python/master/args.py)."""

import argparse


def build_master_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="dlrover-tpu job master")
    parser.add_argument("--port", type=int, default=0, help="RPC port (0=auto)")
    parser.add_argument("--job_name", type=str, default="dlrover-tpu-job")
    parser.add_argument(
        "--platform",
        type=str,
        default="local",
        choices=["local", "sim", "k8s", "gke_tpu"],
        help="cluster backend",
    )
    parser.add_argument("--node_num", type=int, default=1)
    parser.add_argument("--max_relaunch_count", type=int, default=3)
    parser.add_argument("--namespace", type=str, default="default")
    parser.add_argument(
        "--transport", type=str, default="grpc", choices=["grpc", "http"]
    )
    parser.add_argument(
        "--port_file",
        type=str,
        default="",
        help="write the bound RPC port to this file (standalone bootstrap)",
    )
    parser.add_argument("--pre_check", action="store_true", default=False)
    parser.add_argument("--network_check", action="store_true", default=False)
    parser.add_argument(
        "--dashboard_port",
        type=int,
        default=-1,
        help="serve the web dashboard on this port (-1 = off, 0 = auto)",
    )
    parser.add_argument(
        "--brain_addr",
        type=str,
        default="",
        help="host:port of a brain service (cross-job stats + optimizer)",
    )
    parser.add_argument(
        "--metric_endpoints",
        type=str,
        default="",
        help="out-of-band metric scrape targets, 'node=host:port,...' "
        "(per-node tpu_timer daemons or any Prometheus exporter)",
    )
    parser.add_argument(
        "--topology_aware",
        action="store_true",
        default=False,
        help="order ranks by network topology (slice-mates adjacent)",
    )
    parser.add_argument(
        "--global_batch_size",
        type=int,
        default=0,
        help="job global batch (enables micro-batch/accum suggestions)",
    )
    parser.add_argument(
        "--micro_batch_per_device",
        type=int,
        default=0,
        help="per-device micro batch; with --global_batch_size, "
        "restricts rendezvous/rescale worlds to dp sizes where "
        "global_batch %% (micro * dp) == 0",
    )
    parser.add_argument(
        "--devices_per_node",
        type=int,
        default=4,
        help="TPU chips per worker host (mesh suggestions)",
    )
    parser.add_argument(
        "--node_unit",
        type=int,
        default=0,
        help="hosts per TPU slice block: drives complete-group "
        "rendezvous, slice-aware network check, and whole-block "
        "relaunch on hardware faults (0 = ungrouped)",
    )
    parser.add_argument(
        "--auto_scale",
        action="store_true",
        default=False,
        help="enable the throughput-driven worker auto-scaler",
    )
    parser.add_argument(
        "--autoscale_loop",
        action="store_true",
        default=False,
        help="run the closed-loop autoscaler (docs/DESIGN.md §30): "
        "watch goodput/straggler/queue/fault signals; actuate "
        "straggler eviction, ckpt cadence (Young/Daly from observed "
        "MTBF) and — with --autoscale_max_world — world resizes; "
        "fleet-sizing decisions actuate where a router runs "
        "in-process; decisions at /api/autoscaler",
    )
    parser.add_argument(
        "--autoscale_dry_run",
        action="store_true",
        default=False,
        help="autoscaler decides and ledgers but never actuates "
        "(advisory mode)",
    )
    parser.add_argument(
        "--autoscale_interval_s",
        type=float,
        default=5.0,
        help="autoscaler decision-loop cadence in seconds",
    )
    parser.add_argument(
        "--autoscale_max_world",
        type=int,
        default=0,
        help="unpin the autoscaler's backlog-driven world resize up to "
        "this many workers (0 = world pinned: only straggler eviction, "
        "ckpt cadence and the brain seed actuate); clamped to "
        "--legal_worker_counts when given",
    )
    parser.add_argument(
        "--autoscale_record",
        type=str,
        default="",
        help="durably record the autoscaler's signal/decision/outcome "
        "stream to this JSONL path (docs/DESIGN.md §34) for offline "
        "what-if policy replay (tools/whatif.py); also armed by "
        "DLROVER_TPU_AUTOSCALE_RECORD",
    )
    parser.add_argument(
        "--autoscale_ckpt_interval_s",
        type=float,
        default=60.0,
        help="starting flash-ckpt cadence the autoscaler retunes from "
        "observed MTBF (Young/Daly); published on the "
        "autoscaler_ckpt_interval_s gauge and /api/autoscaler",
    )
    parser.add_argument(
        "--legal_worker_counts",
        type=str,
        default="",
        help="comma-separated legal worker counts (mesh shapes), e.g. 1,2,4,8",
    )
    return parser


def parse_master_args(args=None):
    return build_master_parser().parse_args(args)
