"""Resource plans and optimizers.

Parity: reference dlrover/python/master/resource/job.py (PS/Allreduce
JobResourceOptimizer:569), local_optimizer.py (PSLocalOptimizer:66) and
brain_optimizer.py — re-scoped for TPU SPMD jobs: the tunable is the
worker (host) count within *legal mesh shapes*, plus host-memory bumps
after OOM kills. The Brain-service flavor is a stub hook: single-job
local heuristics cover the standalone deployment; a cluster brain can
implement ResourceOptimizer and be dropped in.
"""

import abc
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeExitReason, NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import NodeGroupResource


@dataclass
class ResourcePlan:
    """What the job's role groups should look like."""

    node_group_resources: Dict[str, NodeGroupResource] = field(
        default_factory=dict
    )
    comment: str = ""

    def empty(self) -> bool:
        return not self.node_group_resources


class ResourceOptimizer(abc.ABC):
    @abc.abstractmethod
    def generate_plan(self) -> ResourcePlan:
        """Return the desired resource plan; empty plan = no change."""


@dataclass
class _SpeedSample:
    worker_count: int
    speed: float  # steps/s observed at that count
    at: float


class AllreduceLocalOptimizer(ResourceOptimizer):
    """Throughput-aware worker-count tuner for SPMD (psum) training.

    Heuristics (mirroring the reference's local optimizer intent, TPU
    legality added):
    - only suggest counts from ``legal_counts`` (mesh-shape legality:
      e.g. powers of two, multiples of node_unit);
    - grow while marginal scaling efficiency stays above
      ``min_scaling_efficiency`` (measured from recorded speed samples);
    - after an OOM exit, bump host memory 50% instead of scaling;
    - never change the count twice within ``cooldown_s``.
    """

    def __init__(
        self,
        job_manager,
        perf_monitor,
        legal_counts: Optional[List[int]] = None,
        min_scaling_efficiency: float = 0.7,
        cooldown_s: float = 300.0,
    ):
        self._job_manager = job_manager
        self._perf_monitor = perf_monitor
        self._legal_counts = sorted(legal_counts) if legal_counts else None
        self._min_eff = min_scaling_efficiency
        self._cooldown_s = cooldown_s
        self._samples: List[_SpeedSample] = []
        self._last_change = 0.0
        # Node ids whose OOM has already been answered with a memory
        # bump: dead records keep exit_reason forever, and one OOM must
        # not compound the bump every round.
        self._oom_handled: set = set()

    # ---- observations -------------------------------------------------------

    def record_speed(self):
        speed = self._perf_monitor.running_speed()
        # Only RUNNING nodes train; PENDING nodes mid-scale-up would
        # book the old world's speed under the new count.
        count = len(self._job_manager.worker_manager.running_nodes())
        if speed > 0 and count > 0:
            self._samples.append(_SpeedSample(count, speed, time.time()))
            del self._samples[:-64]

    def _speed_at(self, count: int) -> float:
        speeds = [s.speed for s in self._samples if s.worker_count == count]
        return sum(speeds) / len(speeds) if speeds else 0.0

    # ---- plan ---------------------------------------------------------------

    def generate_plan(self) -> ResourcePlan:
        plan = ResourcePlan()
        now = time.time()
        if now - self._last_change < self._cooldown_s:
            return plan
        worker_manager = self._job_manager.worker_manager
        group = worker_manager.group_resource
        current = group.count

        oom_plan = self._oom_memory_plan(group)
        if oom_plan is not None:
            self._last_change = now
            return oom_plan

        target = self._next_count(current)
        if target == current:
            return plan
        new_group = NodeGroupResource(
            count=target, node_resource=group.node_resource
        )
        plan.node_group_resources[NodeType.WORKER] = new_group
        plan.comment = f"scale {current} -> {target}"
        self._last_change = now
        return plan

    def _oom_memory_plan(self, group) -> Optional[ResourcePlan]:
        ooms = [
            n
            for n in self._job_manager.worker_manager.nodes.values()
            if n.exit_reason == NodeExitReason.OOM
            and n.id not in self._oom_handled
        ]
        if not ooms:
            return None
        self._oom_handled.update(n.id for n in ooms)
        old = group.node_resource.memory_mb
        if old <= 0:
            return None  # unlimited/unspecified: nothing to bump
        group.node_resource.memory_mb = old * 1.5
        logger.info(
            "OOM observed on %d nodes: host memory %.0f -> %.0f MB",
            len(ooms),
            old,
            group.node_resource.memory_mb,
        )
        plan = ResourcePlan(comment="oom-memory-bump")
        plan.node_group_resources[NodeType.WORKER] = group
        return plan

    def _next_count(self, current: int) -> int:
        if not self._legal_counts:
            # Without an explicit legal-shape list there is no safe upper
            # bound to grow toward (TPU mesh shapes are physical): leave
            # the count alone; only OOM memory bumps apply.
            return current
        candidates = self._legal_counts
        cur_speed = self._speed_at(current)
        if cur_speed <= 0:
            return current  # no evidence yet

        # Retreat first: if we grew here and the measured efficiency vs
        # the next smaller legal count is poor, step back down.
        smaller = [c for c in candidates if c < current]
        if smaller:
            prev = max(smaller)
            prev_speed = self._speed_at(prev)
            if prev_speed > 0:
                eff = (cur_speed / prev_speed) / (current / prev)
                if eff < self._min_eff:
                    return prev
        bigger = [c for c in candidates if c > current]
        if not bigger:
            return current
        target = min(bigger)
        seen_target = self._speed_at(target)
        if seen_target > 0:
            # Already tried the bigger size: grow again only if it was
            # efficient back then.
            eff = (seen_target / cur_speed) / (target / current)
            if eff < self._min_eff:
                return current
        return target
