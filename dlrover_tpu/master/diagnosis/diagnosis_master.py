"""Master-side diagnosis orchestration.

Parity: reference dlrover/python/master/diagnosis/diagnosis_master.py:326
(DiagnosisMaster) — runs configured PreCheckOperators before training
(gating agents via the pre-check RPC), then observes the running job via
the DiagnosisManager's registered diagnosticians, and stores per-node
diagnosis data reported by agents.
"""

import threading
from collections import defaultdict, deque
from typing import Deque, Dict, List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import PreCheckStatus
from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.diagnosis_data import (
    DiagnosisData,
    build_diagnosis_data,
)
from dlrover_tpu.diagnosis.diagnosis_manager import DiagnosisManager
from dlrover_tpu.diagnosis.precheck import PreCheckOperator

_DATA_WINDOW = 256  # per-node ring of recent diagnosis reports


class DiagnosisMaster:
    def __init__(
        self,
        pre_check_operators: Optional[List[PreCheckOperator]] = None,
        manager: Optional[DiagnosisManager] = None,
    ):
        self._pre_check_operators = pre_check_operators or []
        self._manager = manager or DiagnosisManager()
        self._pre_check_status = (
            PreCheckStatus.CHECKING
            if self._pre_check_operators
            else PreCheckStatus.PASS
        )
        self._lock = threading.Lock()
        self._node_data: Dict[int, Deque[DiagnosisData]] = defaultdict(
            lambda: deque(maxlen=_DATA_WINDOW)
        )

    @property
    def manager(self) -> DiagnosisManager:
        return self._manager

    # ---- pre-check ---------------------------------------------------------

    def pre_check(self) -> bool:
        """Run all operators (each with its own retry loop); sets the
        status agents poll through the servicer."""
        for op in self._pre_check_operators:
            result = op.run_with_retries()
            if not result.passed:
                logger.error(
                    "pre-check %s failed: %s (nodes %s)",
                    op.name,
                    result.reason,
                    result.abnormal_nodes,
                )
                with self._lock:
                    self._pre_check_status = PreCheckStatus.FAIL
                return False
            logger.info("pre-check %s passed", op.name)
        with self._lock:
            self._pre_check_status = PreCheckStatus.PASS
        return True

    def get_pre_check_status(self) -> str:
        with self._lock:
            return self._pre_check_status

    # ---- runtime observation -----------------------------------------------

    def start_observing(self):
        self._manager.start()

    def stop_observing(self):
        self._manager.stop()

    # ---- agent-reported data ----------------------------------------------

    def collect_diagnosis_data(self, report: comm.DiagnosisDataReport):
        data = build_diagnosis_data(
            report.data_type,
            report.node_id,
            report.payload,
            report.timestamp,
        )
        if data is None:
            logger.warning(
                "unknown diagnosis data type %r dropped", report.data_type
            )
            return
        with self._lock:
            self._node_data[data.node_id].append(data)

    def node_data(self, node_id: int) -> List[DiagnosisData]:
        with self._lock:
            return list(self._node_data.get(node_id, ()))

    def recent_data(self, data_type: str, limit: int = 8) -> List[Dict]:
        """Newest-first reports of one type across all nodes, as plain
        dicts — the hang diagnostician's stack_dump_provider reads the
        relayed worker stack captures through this."""
        out: List[Dict] = []
        with self._lock:
            for node_id, ring in self._node_data.items():
                for data in ring:
                    if data.data_type == data_type:
                        record = dict(vars(data))
                        record["node_id"] = node_id
                        out.append(record)
        out.sort(key=lambda r: r.get("timestamp", 0.0), reverse=True)
        return out[:limit]
