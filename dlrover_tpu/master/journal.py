"""Durable master journal: the control plane's write-ahead log.

Everything the master must not forget across a crash is appended here as
schema-versioned JSONL *before* the reply leaves the servicer: shard
lease dispatch/done, dataset registration and shard-checkpoint state,
rendezvous world commits, kv-store writes, committed checkpoint steps
and rescale ``plan_id`` cuts. A restarted master replays the journal and
resumes with the same outstanding leases (original task ids, so a
riding-through worker's done-report still pops them), never re-dispatches
a done shard, never re-issues a stale ``plan_id`` and never forgets the
newest committed checkpoint (docs/DESIGN.md §37).

Durability discipline borrows from ``autoscaler/recorder.py``:

- fsync per *group commit*: concurrent appenders buffer under a mutex
  and one of them flushes+fsyncs the whole batch, so the lease path pays
  one fsync per commit group, not per record (the bench gate: journaled
  lease-path RPS within 15% of unjournaled).
- torn-tail tolerance: a SIGKILL mid-write leaves a partial final line;
  the loader counts and skips it, and reopening repairs the tail with a
  newline so new records never concatenate onto the torn one.
- rotation-with-snapshot compaction: when the segment outgrows
  ``max_bytes`` the live state is snapshotted into a sibling temp file,
  fsynced, then atomically ``os.replace``d over the journal (the old
  segment wins until the snapshot is fully durable); the previous
  segment is kept as ``<path>.1`` for forensics.
- future-schema refusal: a header with ``v`` above ``SCHEMA_VERSION``
  raises — an old master must not half-understand a new journal.

``master_epoch`` is persisted in every header and bumped on every
reopen; the servicer stamps it into every response so workers can fence
against a restarted master (see ``MasterClient``).
"""

import base64
import json
import os
import threading
import time
from collections import Counter as KindCounter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.log import logger
from dlrover_tpu.fault import fault_point

SCHEMA_VERSION = 1

# Forensic segments kept after compaction: <path>.1 (newest) .. <path>.N.
KEEP_SEGMENTS = 2

JOURNAL_ENV = "DLROVER_TPU_MASTER_JOURNAL"


def _b64(value: bytes) -> str:
    return base64.b64encode(value).decode("ascii")


def _unb64(value: str) -> bytes:
    return base64.b64decode(value.encode("ascii"))


# ---------------------------------------------------------------------------
# Replay state
# ---------------------------------------------------------------------------


@dataclass
class DatasetReplay:
    """Per-dataset shard accounting reconstructed from the journal."""

    params: dict
    epoch: int = 0
    completed: int = 0
    # tid -> dispatch record (the outstanding, dispatched-but-not-done
    # leases; these keep their ORIGINAL task ids on rehydration).
    outstanding: Dict[int, dict] = field(default_factory=dict)
    # (start, end, partition) ranges consumed in the current epoch.
    consumed: Set[Tuple[int, int, int]] = field(default_factory=set)
    # Record indices consumed in the current epoch (text datasets).
    consumed_idx: Set[int] = field(default_factory=set)
    has_indices: bool = False
    # Explicit todo list (from a snapshot or shard-checkpoint restore);
    # None means "derive the remainder from the splitter geometry".
    base_todo: Optional[List[list]] = None
    # Streaming splitter offsets from a snapshot/shard-checkpoint (the
    # offsets, not epochs, are streaming progress).
    splitter_ckpt: Optional[dict] = None
    max_tid: int = -1

    def _key(self, rec: dict) -> Tuple[int, int, int]:
        return (rec["start"], rec["end"], rec.get("part", 0))

    def apply_dispatch(self, rec: dict):
        if rec.get("epoch", 0) > self.epoch:
            # A new epoch began: the previous epoch's consumption no
            # longer constrains the fresh shard set.
            self.epoch = rec.get("epoch", 0)
            self.consumed.clear()
            self.consumed_idx.clear()
            self.base_todo = None
        tid = rec["tid"]
        self.max_tid = max(self.max_tid, tid)
        if tid in self.outstanding:
            return  # idempotent re-apply (snapshot/tail overlap)
        if rec.get("idx"):
            self.has_indices = True
        if self.base_todo is not None:
            key = self._key(rec)
            for i, entry in enumerate(self.base_todo):
                if (entry[0], entry[1], entry[3] if len(entry) > 3 else 0) \
                        == key:
                    del self.base_todo[i]
                    break
        self.outstanding[tid] = rec

    def apply_done(self, tid: int, ok: bool):
        rec = self.outstanding.pop(tid, None)
        if rec is None:
            return  # duplicate / stale report: idempotent
        if not ok:
            # Failed shard returns to the unconsumed pool; it will be
            # re-dispatched (same or regenerated id) later.
            return
        self.completed += 1
        if rec.get("epoch", 0) == self.epoch:
            self.consumed.add(self._key(rec))
            for i in rec.get("idx") or ():
                self.consumed_idx.add(i)

    def apply_shard_ckpt(self, ckpt: dict):
        self.epoch = ckpt.get("epoch", 0)
        self.completed = ckpt.get("completed", 0)
        if ckpt.get("streaming"):
            # Streaming undone entries are [partition, start, end].
            self.base_todo = [
                [s, e, None, p] for p, s, e in ckpt.get("undone_shards", [])
            ]
            self.splitter_ckpt = ckpt.get("splitter")
        else:
            self.base_todo = [list(e) for e in ckpt.get("undone_shards", [])]
        self.outstanding.clear()
        self.consumed.clear()
        self.consumed_idx.clear()


@dataclass
class JournalState:
    """Everything ``load_journal`` reconstructs from one journal chain."""

    path: str = ""
    schema_version: int = SCHEMA_VERSION
    master_epoch: int = 0
    compactions: int = 0
    records: int = 0
    corrupt_lines: int = 0
    clean_shutdown: bool = False
    kinds: KindCounter = field(default_factory=KindCounter)
    datasets: Dict[str, DatasetReplay] = field(default_factory=dict)
    kv: Dict[str, bytes] = field(default_factory=dict)
    ckpt_step: int = -1
    plan_seq: int = 0
    rdzv: Dict[str, dict] = field(default_factory=dict)
    sync_joins: Dict[str, List[int]] = field(default_factory=dict)
    sync_finished: List[str] = field(default_factory=list)

    def is_empty(self) -> bool:
        return self.records == 0


def _apply_snapshot(state: JournalState, snap: dict):
    state.datasets.clear()
    for name, ds in (snap.get("datasets") or {}).items():
        replay = DatasetReplay(params=dict(ds.get("params") or {}))
        replay.epoch = ds.get("epoch", 0)
        replay.completed = ds.get("completed", 0)
        replay.base_todo = [list(e) for e in ds.get("todo", [])]
        for tid, d in (ds.get("doing") or {}).items():
            rec = dict(d)
            rec["tid"] = int(tid)
            replay.outstanding[int(tid)] = rec
            if rec.get("idx"):
                replay.has_indices = True
        replay.max_tid = ds.get("next_tid", 0) - 1
        replay.splitter_ckpt = ds.get("splitter")
        state.datasets[name] = replay
    state.kv = {
        k: _unb64(v) for k, v in (snap.get("kv") or {}).items()
    }
    state.ckpt_step = snap.get("ckpt_step", -1)
    state.plan_seq = snap.get("plan_seq", 0)
    state.rdzv = {
        name: dict(w) for name, w in (snap.get("rdzv") or {}).items()
    }
    sync = snap.get("sync") or {}
    state.sync_joins = {
        name: list(ranks) for name, ranks in (sync.get("joins") or {}).items()
    }
    state.sync_finished = list(sync.get("finished") or [])


def _apply_record(state: JournalState, rec: dict):
    kind = rec.get("kind")
    state.kinds[kind] += 1
    state.clean_shutdown = kind == "close"
    if kind == "header":
        version = rec.get("v", 0)
        if version > SCHEMA_VERSION:
            raise ValueError(
                f"journal schema v{version} is newer than supported "
                f"v{SCHEMA_VERSION}: refusing to replay"
            )
        state.schema_version = version
        state.master_epoch = max(state.master_epoch, rec.get("epoch", 0))
        state.compactions = max(state.compactions, rec.get("compaction", 0))
    elif kind == "snapshot":
        _apply_snapshot(state, rec.get("state") or {})
    elif kind == "dataset":
        params = rec.get("params") or {}
        name = params.get("dataset_name", "")
        if name and name not in state.datasets:
            state.datasets[name] = DatasetReplay(params=params)
    elif kind == "dispatch":
        ds = state.datasets.get(rec.get("ds", ""))
        if ds is not None:
            ds.apply_dispatch(rec)
    elif kind == "done":
        ds = state.datasets.get(rec.get("ds", ""))
        if ds is not None:
            for tid in rec.get("ok") or ():
                ds.apply_done(tid, True)
            for tid in rec.get("fail") or ():
                ds.apply_done(tid, False)
    elif kind == "shard_ckpt":
        ds = state.datasets.get(rec.get("ds", ""))
        if ds is not None:
            ckpt = rec.get("ckpt")
            if isinstance(ckpt, str):
                ckpt = json.loads(ckpt)
            ds.apply_shard_ckpt(ckpt or {})
    elif kind == "kv_set":
        state.kv[rec["key"]] = _unb64(rec.get("val", ""))
    elif kind == "ckpt_step":
        state.ckpt_step = max(state.ckpt_step, rec.get("step", -1))
    elif kind == "plan_cut":
        state.plan_seq = max(state.plan_seq, rec.get("plan_id", 0))
    elif kind == "rdzv":
        state.rdzv[rec.get("name", "")] = {
            "round": rec.get("round", 0),
            "world": {int(r): n for r, n in (rec.get("world") or {}).items()},
        }
    elif kind == "sync":
        name = rec.get("name", "")
        if rec.get("op") == "finish":
            if name not in state.sync_finished:
                state.sync_finished.append(name)
        else:
            state.sync_joins.setdefault(name, [])
            rank = rec.get("rank", -1)
            if rank not in state.sync_joins[name]:
                state.sync_joins[name].append(rank)
    # Unknown kinds within a supported schema version are skipped (the
    # same forward-tolerance load_recording() gives signal records).


def load_journal(path: str) -> JournalState:
    """Replay one journal file into a :class:`JournalState`.

    Torn/corrupt lines are counted and skipped; a header newer than
    ``SCHEMA_VERSION`` raises ``ValueError`` (future-schema refusal).
    """
    state = JournalState(path=path)
    if not os.path.exists(path):
        return state
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                state.corrupt_lines += 1
                continue
            if not isinstance(rec, dict):
                state.corrupt_lines += 1
                continue
            _apply_record(state, rec)
            state.records += 1
    return state


# ---------------------------------------------------------------------------
# The journal writer
# ---------------------------------------------------------------------------


class MasterJournal:
    """Append-only group-commit JSONL WAL for master control state.

    ``append`` returns only after the record is durable (flushed and, by
    default, fsynced). Concurrent appenders share one fsync via group
    commit: each buffers its record under ``_mu`` and then contends on
    ``_commit_mu``; whichever thread wins writes *every* pending record
    and publishes the durable sequence number, so the losers return
    without touching the file.
    """

    def __init__(
        self,
        path: str,
        fsync: bool = True,
        max_bytes: int = 64 * 1024 * 1024,
        snapshot_fn: Optional[Callable[[], dict]] = None,
    ):
        self.path = path
        self._fsync = fsync
        self._max_bytes = max(int(max_bytes), 1 << 16)
        self._snapshot_fn = snapshot_fn
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # Future-schema refusal propagates; IO corruption does not stop
        # a master from starting with what it could read.
        self.recovered = load_journal(path)
        self.master_epoch = self.recovered.master_epoch + 1
        self._compactions = self.recovered.compactions
        self._mu = threading.Lock()
        self._commit_mu = threading.Lock()
        self._pending: List[dict] = []
        self._seq = 0
        self._durable_seq = 0
        self._records = 0
        self._groups = 0
        self._closed = False
        self._last_append = 0.0
        self._repair_torn_tail()
        self._f = open(path, "a", encoding="utf-8")
        self._write_header()

    # ---- durability core ---------------------------------------------------

    def _repair_torn_tail(self):
        """A SIGKILL mid-write leaves a partial final line; terminate it
        so appended records never concatenate onto the torn bytes (the
        loader still counts the torn line as corrupt, preserved for
        forensics)."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size == 0:
            return
        with open(self.path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                f.write(b"\n")
                f.flush()
                os.fsync(f.fileno())

    def _header_record(self) -> dict:
        return {
            "kind": "header",
            "v": SCHEMA_VERSION,
            "epoch": self.master_epoch,
            "compaction": self._compactions,
            "pid": os.getpid(),
            "wall": time.time(),
            "mono": time.monotonic(),
        }

    def _write_header(self):
        line = json.dumps(self._header_record()) + "\n"
        self._f.write(line)
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())

    def append(self, kind: str, **fields):
        rec = {"kind": kind}
        rec.update(fields)
        self._append_records([rec])
        fault_point("master.journal.write", kind=kind)

    def append_many(self, records: List[dict]):
        """Append a batch durably (one group commit for the caller's
        whole batch), then fire the write fault point once per record —
        so a crash schedule matched on ``kind=dispatch`` kills the
        master *after* the dispatch is durable and *before* the reply
        leaves (the exactly-once crash window the soak exercises)."""
        if not records:
            return
        self._append_records(records)
        for rec in records:
            fault_point("master.journal.write", kind=rec.get("kind", ""))

    def _append_records(self, records: List[dict]):
        with self._mu:
            if self._closed:
                return
            self._pending.extend(records)
            self._seq += len(records)
            my_seq = self._seq
        self._commit(my_seq)

    def _commit(self, upto: int):
        with self._commit_mu:
            with self._mu:
                if self._durable_seq >= upto or self._closed:
                    return
                batch = self._pending
                self._pending = []
                batch_seq = self._seq
            if batch:
                payload = "".join(
                    json.dumps(rec, default=str) + "\n" for rec in batch
                )
                self._f.write(payload)
                self._f.flush()
                if self._fsync:
                    os.fsync(self._f.fileno())
                self._groups += 1
                self._records += len(batch)
                self._last_append = time.time()
            with self._mu:
                self._durable_seq = max(self._durable_seq, batch_seq)
            if (
                self._snapshot_fn is not None
                and self._segment_bytes() > self._max_bytes
            ):
                try:
                    self._compact_locked(self._snapshot_fn())
                except Exception:
                    logger.exception("journal auto-compaction failed")

    def _segment_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # ---- compaction --------------------------------------------------------

    def compact(self, snapshot: Optional[dict] = None):
        """Snapshot-compact the live segment. The dance is crash-safe:
        the snapshot is written to a sibling temp file and fsynced
        BEFORE ``os.replace`` swaps it in — until that replace, the old
        segment is the journal (a crash mid-compaction loses nothing)."""
        if snapshot is None:
            if self._snapshot_fn is None:
                raise ValueError("compact() needs a snapshot or snapshot_fn")
            snapshot = self._snapshot_fn()
        with self._commit_mu:
            with self._mu:
                if self._closed:
                    return
                batch = self._pending
                self._pending = []
                batch_seq = self._seq
            if batch:
                self._f.write(
                    "".join(
                        json.dumps(rec, default=str) + "\n" for rec in batch
                    )
                )
                self._f.flush()
            with self._mu:
                self._durable_seq = max(self._durable_seq, batch_seq)
            self._compact_locked(snapshot)

    def _compact_locked(self, snapshot: dict):
        self._compactions += 1
        tmp = self.path + ".compact.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(self._header_record()) + "\n")
            f.write(
                json.dumps(
                    {
                        "kind": "snapshot",
                        "v": SCHEMA_VERSION,
                        "state": snapshot,
                    },
                    default=str,
                )
                + "\n"
            )
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        # Keep the replaced segments as a forensic chain (.1 newest).
        try:
            for i in range(KEEP_SEGMENTS, 1, -1):
                older = f"{self.path}.{i - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{self.path}.{i}")
            seg1 = self.path + ".1"
            if os.path.exists(seg1):
                os.remove(seg1)
            os.link(self.path, seg1)
        except OSError:
            pass  # forensics are best-effort; durability is not
        os.replace(tmp, self.path)
        try:
            dir_fd = os.open(os.path.dirname(os.path.abspath(self.path)),
                             os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except OSError:
            pass
        self._f = open(self.path, "a", encoding="utf-8")
        logger.info(
            "journal %s compacted (epoch=%d compaction=%d)",
            self.path, self.master_epoch, self._compactions,
        )

    # ---- lifecycle / introspection ----------------------------------------

    def flush(self):
        """Drain pending records to durable storage (graceful-shutdown
        hook: called by ``HttpMasterServer`` after the RPC drain)."""
        with self._mu:
            upto = self._seq
        self._commit(upto)

    def close(self):
        self.append("close")
        with self._commit_mu:
            with self._mu:
                if self._closed:
                    return
                self._closed = True
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
            except (OSError, ValueError):
                pass

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        return {
            "path": self.path,
            "schema_version": SCHEMA_VERSION,
            "master_epoch": self.master_epoch,
            "records_appended": self._records,
            "commit_groups": self._groups,
            "segment_bytes": self._segment_bytes(),
            "compactions": self._compactions,
            "recovered_records": self.recovered.records,
            "recovered_corrupt_lines": self.recovered.corrupt_lines,
            "fsync": self._fsync,
            "last_append_unix": self._last_append,
        }


# ---------------------------------------------------------------------------
# Rehydration: JournalState -> live master components
# ---------------------------------------------------------------------------


def _derived_todo(replay: DatasetReplay) -> List[list]:
    """Reconstruct the unconsumed, un-leased remainder of the current
    epoch for a dataset without an explicit todo list."""
    if replay.base_todo is not None:
        return [list(e) for e in replay.base_todo]
    if replay.epoch <= 0:
        # Nothing was ever dispatched: leave the manager fresh and the
        # splitter will generate epoch 1 on first demand.
        return []
    params = replay.params
    size = int(params.get("dataset_size", 0))
    shard = max(int(params.get("shard_size", 1)), 1)
    leased = {(r["start"], r["end"], r.get("part", 0))
              for r in replay.outstanding.values()}
    if replay.has_indices:
        # Text datasets: shards address POSITIONS into a (possibly
        # shuffled) permutation, record_indices carry the truth, and
        # unshuffled consumers rely on position == index. The
        # permutation died with the master, so keep the positional
        # complement (positions not consumed, not leased) and assign
        # the un-taken indices to those positions in order — the
        # identity permutation reproduces exactly; a shuffled one is
        # re-drawn validly (any assignment of remaining indices to
        # remaining positions is a correct remainder).
        taken: Set[int] = set(replay.consumed_idx)
        for rec in replay.outstanding.values():
            for i in rec.get("idx") or ():
                taken.add(i)
        remaining_idx = [i for i in range(size) if i not in taken]
        out = []
        cursor = 0
        for start in range(0, size, shard):
            end = min(start + shard, size)
            if (start, end, 0) in replay.consumed:
                continue
            if (start, end, 0) in leased:
                continue
            chunk = remaining_idx[cursor:cursor + (end - start)]
            cursor += end - start
            out.append([start, end, chunk, 0])
        return out
    out = []
    for start in range(0, size, shard):
        end = min(start + shard, size)
        if (start, end, 0) in replay.consumed:
            continue
        if (start, end, 0) in leased:
            continue
        out.append([start, end, None, 0])
    return out


def _streaming_splitter_ckpt(replay: DatasetReplay, todo: List[list]) -> dict:
    """Rebuild streaming splitter offsets from journaled carves: every
    dispatched or still-queued shard has already advanced its partition's
    offset past its end."""
    params = replay.params
    offsets: Dict[int, int] = {
        p: 0 for p in range(max(int(params.get("num_partitions", 1) or 1), 1))
    }
    carved = 0
    for start, end, part in replay.consumed:
        offsets[part] = max(offsets.get(part, 0), end)
    for rec in replay.outstanding.values():
        part = rec.get("part", 0)
        offsets[part] = max(offsets.get(part, 0), rec.get("end", 0))
    for entry in todo:
        part = entry[3] if len(entry) > 3 else 0
        offsets[part] = max(offsets.get(part, 0), entry[1])
    carved = sum(offsets.values())
    size = int(params.get("dataset_size", -1))
    remaining = -1 if size < 0 else max(size - carved, 0)
    return {
        "partition_offsets": {str(p): o for p, o in offsets.items()},
        "remaining": remaining,
        "shard_size": max(int(params.get("shard_size", 1) or 1), 1),
    }


def _restore_task_manager(state: JournalState, task_manager) -> dict:
    from dlrover_tpu.common import comm

    summary = {}
    for name, replay in state.datasets.items():
        params_fields = {
            k: v for k, v in replay.params.items()
            if k in comm.DatasetShardParams.__dataclass_fields__
        }
        task_manager.new_dataset(comm.DatasetShardParams(**params_fields))
        mgr = task_manager.get_dataset(name)
        if mgr is None:
            continue
        doing = {
            tid: (
                rec.get("node", -1),
                rec.get("epoch", 0),
                rec.get("start", 0),
                rec.get("end", 0),
                rec.get("idx"),
                rec.get("part", 0),
            )
            for tid, rec in replay.outstanding.items()
        }
        rehydrate = getattr(mgr, "rehydrate", None)
        if rehydrate is None:
            logger.warning(
                "dataset %s: manager %s has no rehydrate(); skipping",
                name, type(mgr).__name__,
            )
            continue
        todo = _derived_todo(replay)
        kwargs = dict(
            dataset_name=name,
            epoch=replay.epoch,
            completed=replay.completed,
            todo_shards=todo,
            doing=doing,
            next_task_id=replay.max_tid + 1,
        )
        storage = str(replay.params.get("storage_type") or "").lower()
        if storage in ("stream", "streaming", "kafka", "sls"):
            kwargs["splitter_ckpt"] = (
                replay.splitter_ckpt
                or _streaming_splitter_ckpt(replay, todo)
            )
        rehydrate(**kwargs)
        summary[name] = {
            "todo": len(todo),
            "doing": len(doing),
            "completed": replay.completed,
            "epoch": replay.epoch,
        }
    return summary


def restore_master_state(
    state: Optional[JournalState],
    task_manager=None,
    kv_store=None,
    rescale_coordinator=None,
    sync_service=None,
    rdzv_managers=None,
    job_manager=None,
) -> dict:
    """Rehydrate live master components from a replayed journal.

    Exactly-once law: outstanding leases land back in ``doing`` with
    their ORIGINAL task ids (a riding-through worker's done-report pops
    them; a dead worker's leases re-queue via the normal timeout path),
    and done shards are excluded from the rebuilt todo so they are never
    re-dispatched.
    """
    if state is None or state.is_empty():
        return {}
    fault_point("master.restart", epoch=state.master_epoch)
    summary: dict = {"master_epoch": state.master_epoch}
    if task_manager is not None:
        summary["datasets"] = _restore_task_manager(state, task_manager)
    if kv_store is not None and state.kv:
        for key, value in state.kv.items():
            kv_store.set(key, value)
        summary["kv_keys"] = len(state.kv)
    if rescale_coordinator is not None:
        restore = getattr(
            rescale_coordinator, "restore_journal_state", None
        )
        if restore is not None:
            restore(state.plan_seq, state.ckpt_step)
            summary["plan_seq"] = state.plan_seq
            summary["ckpt_step"] = state.ckpt_step
    if job_manager is not None and state.ckpt_step >= 0:
        # The client-visible get_ckpt_latest_step verb reads the job
        # context, not the rescale coordinator — feed it too so a
        # restarted master never answers -1 for a step it committed.
        update = getattr(job_manager, "update_ckpt_step", None)
        if update is not None:
            update(-1, state.ckpt_step, committed=True)
    if sync_service is not None and (
        state.sync_joins or state.sync_finished
    ):
        restore = getattr(sync_service, "restore_journal_state", None)
        if restore is not None:
            restore(state.sync_joins, state.sync_finished)
            summary["syncs"] = len(state.sync_joins)
    for name, committed in (state.rdzv or {}).items():
        mgr = (rdzv_managers or {}).get(name)
        restore = getattr(mgr, "restore_committed_world", None)
        if restore is not None:
            restore(committed.get("round", 0), committed.get("world", {}))
            summary.setdefault("rdzv", {})[name] = committed.get("round", 0)
    logger.info("master state rehydrated from journal: %s", summary)
    return summary


def journal_path_from_env() -> Optional[str]:
    return os.getenv(JOURNAL_ENV) or None
