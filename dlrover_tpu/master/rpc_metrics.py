"""Per-verb RPC telemetry for the master control plane (DESIGN.md §32).

Every ``get``/``report`` the servicer dispatches lands in four
bounded-cardinality metric families:

- ``master_rpc_seconds{verb}`` — end-to-end dispatch latency histogram
  (deserialize + admission + handler + serialize), with p50/p95/p99
  precomputed at /metrics by the prom exposition;
- ``master_rpc_inflight{verb}`` + ``master_rpc_inflight_high_water`` —
  concurrent dispatches right now, and the worst depth ever seen;
- ``master_rpc_errors_total{verb,kind}`` — handler exceptions by
  exception class, plus the ``no_handler`` protocol error;
- ``master_rpc_dropped_total{verb}`` — requests answered without
  running their handler (overload shed, see ``master/overload.py``).

plus the handler-internal split ``master_rpc_phase_seconds{phase}``
(``deserialize`` / ``handler`` / ``serialize``) — aggregated across
verbs so the family stays three children — which is how lock
contention shows up: a slow verb whose ``handler`` phase dominates is
waiting on a manager lock, not on pickle.

**Cardinality is bounded by construction**: the ``verb`` label only
ever takes values from the servicer's registered handler tables plus
one ``other`` bucket (:data:`OTHER_VERB`); an attacker (or a newer
client) sending unknown request types cannot grow the exposition. The
documented family cap is :data:`MAX_VERB_LABELS` label values.

``master_rpc_cpu_seconds_total`` accumulates *thread* CPU spent inside
dispatch — the load harness divides it by the RPC count for the
"master CPU per 1k RPCs/s" bench number without needing the master in
its own process.
"""

import threading
import time
from typing import Dict, Iterable, List, Optional

from dlrover_tpu.observability.registry import default_registry

OTHER_VERB = "other"

# Documented cap on distinct ``verb`` label values (registered handler
# types + the collapse bucket). The servicer registers ~40 verbs today;
# the test suite asserts the exposition stays under this bound even
# when flooded with unknown request types.
MAX_VERB_LABELS = 64

# Control-plane handlers run in the tens-of-microseconds to
# tens-of-milliseconds band; the registry defaults start at 5ms and
# would flatten every healthy verb into the first bucket.
RPC_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

PHASE_DESERIALIZE = "deserialize"
PHASE_HANDLER = "handler"
PHASE_SERIALIZE = "serialize"


class RpcTelemetry:
    """One per servicer; all methods thread-safe and cheap (the HTTP
    transport dispatches from a thread per connection)."""

    def __init__(self, known_verbs: Iterable[str],
                 registry=None):
        self._known = frozenset(str(v) for v in known_verbs)
        if len(self._known) + 1 > MAX_VERB_LABELS:
            raise ValueError(
                f"{len(self._known)} registered verbs exceed the "
                f"documented {MAX_VERB_LABELS}-label cardinality cap"
            )
        reg = registry or default_registry()
        self.seconds = reg.histogram(
            "master_rpc_seconds",
            "end-to-end master RPC dispatch latency per verb",
            labelnames=("verb",),
            buckets=RPC_BUCKETS,
        )
        self.phase_seconds = reg.histogram(
            "master_rpc_phase_seconds",
            "dispatch split: deserialize / handler / serialize",
            labelnames=("phase",),
            buckets=RPC_BUCKETS,
        )
        self.inflight = reg.gauge(
            "master_rpc_inflight",
            "RPCs currently being dispatched, per verb",
            labelnames=("verb",),
        )
        self.inflight_high_water = reg.gauge(
            "master_rpc_inflight_high_water",
            "worst concurrent-dispatch depth seen since start",
        )
        self.errors = reg.counter(
            "master_rpc_errors_total",
            "handler failures per verb and exception kind",
            labelnames=("verb", "kind"),
        )
        self.dropped = reg.counter(
            "master_rpc_dropped_total",
            "requests answered without running their handler "
            "(overload shed)",
            labelnames=("verb",),
        )
        self.cpu_seconds = reg.counter(
            "master_rpc_cpu_seconds_total",
            "thread CPU seconds spent inside RPC dispatch",
        )
        self._lock = threading.Lock()
        self._inflight_total = 0
        self._high_water = 0
        self._rpcs_total = 0

    # ---- verb normalization ------------------------------------------------

    def verb(self, request_type_name: str) -> str:
        """Collapse unknown request types into ``other`` so the label
        set stays bounded no matter what arrives on the wire."""
        return (
            request_type_name
            if request_type_name in self._known
            else OTHER_VERB
        )

    # ---- dispatch lifecycle ------------------------------------------------

    def begin(self, verb: str) -> None:
        self.inflight.inc(verb=verb)
        with self._lock:
            self._inflight_total += 1
            if self._inflight_total > self._high_water:
                self._high_water = self._inflight_total
                self.inflight_high_water.set(self._high_water)

    def end(
        self,
        verb: str,
        total_s: float,
        deserialize_s: float = 0.0,
        handler_s: Optional[float] = None,
        serialize_s: float = 0.0,
        cpu_s: float = 0.0,
        error_kind: Optional[str] = None,
        dropped: bool = False,
    ) -> None:
        """``handler_s=None`` means the handler never ran (shed /
        no-handler): no handler-phase sample, so an overload episode's
        flood of shed replies cannot drag the handler split toward
        zero and mask real handler slowness. A shed (``dropped``) RPC
        is likewise excluded from ``master_rpc_seconds`` entirely —
        its microsecond fast-path would collapse the verb's quantiles
        toward zero exactly while its traffic is being dropped; the
        dropped counter is its record."""
        self.inflight.dec(verb=verb)
        with self._lock:
            self._inflight_total = max(self._inflight_total - 1, 0)
            self._rpcs_total += 1
        if not dropped:
            self.seconds.observe(max(total_s, 0.0), verb=verb)
        self.phase_seconds.observe(
            max(deserialize_s, 0.0), phase=PHASE_DESERIALIZE
        )
        if handler_s is not None:
            self.phase_seconds.observe(
                max(handler_s, 0.0), phase=PHASE_HANDLER
            )
        self.phase_seconds.observe(
            max(serialize_s, 0.0), phase=PHASE_SERIALIZE
        )
        if cpu_s > 0:
            self.cpu_seconds.inc(cpu_s)
        if error_kind is not None:
            self.errors.inc(verb=verb, kind=str(error_kind)[:64])
        if dropped:
            self.dropped.inc(verb=verb)

    # ---- read side ---------------------------------------------------------

    def inflight_now(self) -> int:
        with self._lock:
            return self._inflight_total

    def rpcs_total(self) -> int:
        with self._lock:
            return self._rpcs_total

    def high_water(self) -> int:
        with self._lock:
            return self._high_water

    def cpu_seconds_total(self) -> float:
        return self.cpu_seconds.value()

    def verb_names(self) -> List[str]:
        return sorted(self._known) + [OTHER_VERB]

    def summary(self) -> Dict:
        """Per-verb latency/volume table for ``/api/control_plane`` and
        the load harness (only verbs that have actually been seen)."""
        verbs: Dict[str, Dict] = {}
        for name, labels, value in self.seconds.samples():
            if not name.endswith("_count"):
                continue
            verb = labels.get("verb", "")
            if value <= 0:
                continue
            verbs[verb] = {
                "count": int(value),
                "mean_s": self.seconds.sum(verb=verb) / value,
                "p50_s": self.seconds.quantile(0.5, verb=verb),
                "p95_s": self.seconds.quantile(0.95, verb=verb),
                "p99_s": self.seconds.quantile(0.99, verb=verb),
                "errors": _label_total(self.errors, "verb", verb),
                "dropped": self.dropped.value(verb=verb),
                "inflight": self.inflight.value(verb=verb),
            }
        # A verb that has ONLY ever been shed has no latency samples
        # but must still surface — its drop count IS its story.
        for _name, labels, value in self.dropped.samples():
            verb = labels.get("verb", "")
            if value > 0 and verb not in verbs:
                verbs[verb] = {
                    "count": 0, "mean_s": None, "p50_s": None,
                    "p95_s": None, "p99_s": None,
                    "errors": _label_total(self.errors, "verb", verb),
                    "dropped": value,
                    "inflight": self.inflight.value(verb=verb),
                }
        return {
            "rpcs_total": self.rpcs_total(),
            "inflight": self.inflight_now(),
            "inflight_high_water": self.high_water(),
            "cpu_seconds_total": round(self.cpu_seconds_total(), 6),
            "verb_cap": MAX_VERB_LABELS,
            "verbs": verbs,
        }


def _label_total(counter, label: str, value: str) -> float:
    total = 0.0
    for _name, labels, v in counter.samples():
        if labels.get(label) == value:
            total += v
    return total


_MONO = time.monotonic
_THREAD_TIME = getattr(time, "thread_time", time.monotonic)


def clocks() -> tuple:
    """(monotonic, thread_cpu) sampled together — the servicer's
    dispatch timer."""
    return _MONO(), _THREAD_TIME()
