"""Standalone (single-host) job master.

Parity: reference dlrover/python/master/local_master.py:41 (LocalJobMaster)
— spawned by the run CLI in standalone mode so the full master protocol
(rendezvous, KV store, data sharding, diagnosis) is available without a
cluster.
"""

import threading
import time

from dlrover_tpu.common.constants import JobConstant
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    create_rdzv_managers,
)
from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
from dlrover_tpu.master.node.job_context import get_job_context
from dlrover_tpu.master.node.local_job_manager import LocalJobManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.rpc.transport import create_master_server


class LocalJobMaster:
    def __init__(
        self,
        port: int = 0,
        job_name: str = "local-job",
        node_num: int = 1,
        max_relaunch_count: int = 3,
        transport: str = "grpc",
        batch_config=None,
        devices_per_node: int = 1,
        autoscale_loop: bool = False,
        autoscale_dry_run: bool = False,
        autoscale_interval_s: float = 5.0,
        autoscale_record: str = "",
        journal_path: str = "",
    ):
        self.job_name = job_name
        self._job_context = get_job_context()
        self.job_manager = LocalJobManager(job_name, max_relaunch_count)
        self.rdzv_managers = create_rdzv_managers()
        self.perf_monitor = PerfMonitor()
        self.task_manager = TaskManager(perf_monitor=self.perf_monitor)
        self.diagnosis_master = self._build_diagnosis_master()
        from dlrover_tpu.master.elastic_training.rescale_coordinator import (
            RescaleCoordinator,
            wire_batch_legality,
        )

        self.rescale_coordinator = RescaleCoordinator(
            bootstrap_min=node_num
        )
        # Durable control-plane journal (DESIGN.md §37). Restore order
        # matters: kv/sync/task state is rehydrated BEFORE the servicer
        # is constructed so its replica-token seed check sees the
        # restored token instead of journaling a fresh (wrong) one.
        from dlrover_tpu.master.elastic_training.kv_store import (
            KVStoreService,
        )
        from dlrover_tpu.master.elastic_training.sync_service import (
            SyncService,
        )
        from dlrover_tpu.master.journal import (
            MasterJournal,
            journal_path_from_env,
            restore_master_state,
        )

        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.journal = None
        jpath = journal_path or journal_path_from_env()
        if jpath:
            self.journal = MasterJournal(jpath)
            restore_master_state(
                self.journal.recovered,
                task_manager=self.task_manager,
                kv_store=self.kv_store,
                rescale_coordinator=self.rescale_coordinator,
                sync_service=self.sync_service,
                rdzv_managers=self.rdzv_managers,
                job_manager=self.job_manager,
            )
            # Plan cuts are journaled as they happen so a restarted
            # master never re-issues a stale plan_id.
            self.rescale_coordinator.on_plan_cut = (
                lambda plan: self.journal.append(
                    "plan_cut", plan_id=plan.plan_id
                )
            )
        if batch_config is not None:
            # Rendezvous and rescale plans only form worlds the trainer's
            # batch config can actually train at (global_batch divisible
            # by micro * dp) — a 3-of-4-survivors world must be truncated,
            # not crash grad_accum_for().
            # Legality must use the REAL dp = nodes * devices_per_node;
            # defaulting to 1 here would admit worlds whose actual dp
            # fails grad_accum_for() on arrival.
            wire_batch_legality(
                self.rdzv_managers,
                self.rescale_coordinator,
                batch_config,
                local_world_size=devices_per_node,
            )
        from dlrover_tpu.observability import tracing as tracing_lib

        self.trace_aggregator = tracing_lib.TraceAggregator()
        _tracer = (
            tracing_lib.active_tracer()
            or tracing_lib.arm_from_env(service="master")
        )
        if _tracer is not None:
            # Master's own server spans feed /api/traces directly.
            _tracer.set_on_finish(self.trace_aggregator.ingest_one)
        self.servicer = MasterServicer(
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            diagnosis_master=self.diagnosis_master,
            perf_monitor=self.perf_monitor,
            sync_service=self.sync_service,
            kv_store=self.kv_store,
            rescale_coordinator=self.rescale_coordinator,
            trace_aggregator=self.trace_aggregator,
            journal=self.journal,
        )
        self._server = create_master_server(port, self.servicer, transport)
        if self.journal is not None and hasattr(
            self._server, "add_shutdown_hook"
        ):
            self._server.add_shutdown_hook(self.journal.close)
        self.port = self._server.port
        self._node_num = node_num
        self._stopped = threading.Event()
        # §30 autoscaler, standalone flavor: full signal plane
        # (straggler scores, shard queues, fleet load, fault history)
        # + the rescale coordinator's eviction actuation. The local
        # master has no cluster scaler, so world-resize decisions stay
        # advisory — visible in the ledger and metrics, acted on by
        # the operator.
        self.autoscaler = None
        self.fault_history = None
        self.ckpt_cadence = None
        if autoscale_loop:
            from dlrover_tpu.autoscaler import (
                AutoScaler,
                CadenceController,
                EVICT_STRAGGLER,
                FaultHistory,
                SET_CKPT_INTERVAL,
                SignalBus,
                SignalRecorder,
                control_plane_source,
                data_source,
                fault_source,
                fleet_source,
                perf_source,
            )

            self.fault_history = FaultHistory()
            # The cadence knob: the "ckpt" source makes the Young/Daly
            # rule live once an MTBF is observed; a standalone trainer
            # polls master.ckpt_cadence.interval_s() (or the gauge).
            self.ckpt_cadence = CadenceController(60.0)
            bus = (
                SignalBus()
                .add_source("perf", perf_source(self.perf_monitor))
                .add_source("data", data_source(self.task_manager))
                .add_source("fleet", fleet_source())
                .add_source("fault", fault_source(self.fault_history))
                .add_source("ckpt", self.ckpt_cadence.as_source())
                # §32 master saturation signal.
                .add_source("control_plane", control_plane_source(
                    self.servicer.control_plane_state
                ))
            )

            def evict(decision):
                rank = int(decision.target)
                if not self.rescale_coordinator.evict_worker(rank):
                    raise ValueError(
                        f"rank {decision.target} not in the live set"
                    )
                # Fresh EWMA for the seat's next occupant.
                self.perf_monitor.reset_rank(rank)

            self.autoscaler = AutoScaler(
                bus,
                actuators={
                    EVICT_STRAGGLER: evict,
                    SET_CKPT_INTERVAL: self.ckpt_cadence.apply,
                },
                interval_s=autoscale_interval_s,
                dry_run=autoscale_dry_run,
                job_name=job_name,
                # §34: durable signal/decision/outcome recording for
                # offline what-if replay (env arming still applies when
                # the flag is unset — AutoScaler falls back to it).
                recorder=(
                    SignalRecorder(autoscale_record)
                    if autoscale_record else None
                ),
            )

    def _build_diagnosis_master(self):
        from dlrover_tpu.diagnosis.diagnosis_manager import DiagnosisManager
        from dlrover_tpu.diagnosis.diagnosticians.training_hang import (
            TrainingHangDiagnostician,
        )
        from dlrover_tpu.master.diagnosis.diagnosis_master import (
            DiagnosisMaster,
        )

        from dlrover_tpu.diagnosis.diagnosticians.node_failure import (
            NodeFailureDiagnostician,
        )

        manager = DiagnosisManager()
        dm = DiagnosisMaster(manager=manager)
        from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

        manager.register(TrainingHangDiagnostician(
            self.perf_monitor,
            # Late-bound: workers' relayed stack dumps let the hang
            # escalation name the blocked frame.
            stack_dump_provider=lambda: dm.recent_data(
                DiagnosisDataType.STACK_DUMP
            ),
        ))
        manager.register(NodeFailureDiagnostician())
        return dm

    def prepare(self):
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                min_nodes=self._node_num,
                max_nodes=self._node_num,
                waiting_timeout=5.0,
            )
        self._server.start()
        self.job_manager.start()
        self.task_manager.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        self.diagnosis_master.start_observing()
        logger.info(
            "local master [%s] serving on port %d", self.job_name, self.port
        )

    def run(self) -> int:
        """Supervision loop; returns exit code."""
        try:
            while not self._stopped.is_set():
                time.sleep(JobConstant.MASTER_RUN_LOOP_INTERVAL)
                if self.job_manager.all_workers_exited():
                    if self.job_manager.all_workers_succeeded():
                        logger.info("all workers succeeded; master exiting")
                        return 0
                    logger.error("workers failed; master exiting")
                    return 1
                rc = self._execute_master_actions()
                if rc is not None:
                    return rc
            return 0
        finally:
            self.stop()

    def _execute_master_actions(self):
        """Consume job-level diagnosis actions (hang -> restart/abort),
        mirroring DistributedJobMaster._diagnose_loop for standalone."""
        from dlrover_tpu.common.constants import DiagnosisActionType

        while True:
            action = self._job_context.next_master_action()
            if action is None:
                return None
            if action.action_type == DiagnosisActionType.JOB_RESTART:
                logger.warning(
                    "diagnosis: restarting workers (%s)", action.reason
                )
                self.job_manager.restart_worker_processes(action.reason)
            elif action.action_type == DiagnosisActionType.JOB_ABORT:
                logger.error("diagnosis: aborting job (%s)", action.reason)
                return 1

    def stop(self):
        self._stopped.set()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.diagnosis_master.stop_observing()
        self.task_manager.stop()
        self.job_manager.stop()
        # Prefer the draining stop: finish in-flight RPCs, run shutdown
        # hooks (journal flush+fsync+close record), sever keep-alives.
        graceful = getattr(self._server, "graceful_stop", None)
        if graceful is not None:
            graceful()
        else:
            self._server.stop()
        if self.journal is not None and not self.journal.closed:
            self.journal.close()

    def request_stop(self):
        self._stopped.set()
