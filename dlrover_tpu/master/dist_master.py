"""Distributed job master: one per job, owns all managers.

Parity: reference dlrover/python/master/dist_master.py:101-457
(DistributedJobMaster.prepare/run/pre_check) — the supervision loop ticks
every few seconds checking: workers all exited, training hang, pending
timeout; a parallel diagnose thread executes job-level DiagnosisActions
(JobRestartAction/JobAbortionAction/NodeAction, reference :236-263).
"""

import threading
import time
from typing import Optional

from dlrover_tpu.common.constants import (
    DiagnosisActionType,
    JobConstant,
    JobExitReason,
    NodeType,
    RendezvousName,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    create_rdzv_managers,
)
from dlrover_tpu.master.monitor.perf_monitor import PerfMonitor
from dlrover_tpu.master.node.dist_job_manager import DistributedJobManager
from dlrover_tpu.master.node.event_callback import (
    AllReduceNodeHandlingCallback,
    TaskRescheduleCallback,
)
from dlrover_tpu.master.node.job_context import get_job_context
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.rpc.transport import create_master_server


def _parse_metric_endpoints(raw: str):
    """"0=host:port,1=host:port" -> {0: "host:port", ...} (CLI form of
    the metric monitor's endpoint map; programmatic callers pass a dict
    or a callable instead). Malformed input fails with a message that
    names the flag, not a bare traceback during master startup."""
    if not raw:
        return None
    out = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            node, addr = part.split("=", 1)
            out[int(node)] = addr
        except ValueError:
            raise SystemExit(
                f"--metric_endpoints: bad entry {part!r} "
                "(expected 'node_id=host:port,...')"
            )
    return out or None



class DistributedJobMaster:
    def __init__(
        self,
        port: int,
        job_name: str,
        node_num: int,
        scaler,
        watcher,
        max_relaunch_count: int = 3,
        transport: str = "grpc",
        node_resource: Optional[NodeResource] = None,
        diagnosis_master=None,
        heartbeat_timeout_s: float = 600.0,
        pending_timeout_s: float = 900.0,
        with_diagnosis: bool = True,
        pre_check: bool = False,
        auto_scale: bool = False,
        legal_worker_counts=None,
        dashboard_port: int = -1,
        global_batch_size: int = 0,
        micro_batch_per_device: int = 0,
        devices_per_node: int = 4,
        brain_addr: str = "",
        topology_aware: bool = False,
        node_group_size: int = 0,
        metric_endpoints=None,
        autoscale_loop: bool = False,
        autoscale_dry_run: bool = False,
        autoscale_interval_s: float = 5.0,
        autoscale_max_world: int = 0,
        autoscale_ckpt_interval_s: float = 60.0,
        autoscale_record: str = "",
        journal_path: str = "",
    ):
        self.job_name = job_name
        self._job_context = get_job_context()
        self.perf_monitor = PerfMonitor()
        self.task_manager = TaskManager(perf_monitor=self.perf_monitor)
        self.rdzv_managers = create_rdzv_managers()
        node_groups = {
            NodeType.WORKER: NodeGroupResource(
                count=node_num,
                node_resource=node_resource or NodeResource(),
            )
        }
        self.job_manager = DistributedJobManager(
            job_name=job_name,
            node_groups=node_groups,
            scaler=scaler,
            watcher=watcher,
            max_relaunch_count=max_relaunch_count,
            heartbeat_timeout_s=heartbeat_timeout_s,
            pending_timeout_s=pending_timeout_s,
            node_group_size=node_group_size,
        )
        self.job_manager.add_node_event_callback(
            AllReduceNodeHandlingCallback(self)
        )
        self.job_manager.add_node_event_callback(
            TaskRescheduleCallback(self.task_manager)
        )
        # Out-of-band cluster metric monitor (common/metric.py): scrape
        # the per-node tpu_timer daemons (or any Prometheus exporter)
        # into a windowed context the hang diagnostician corroborates
        # against. ``metric_endpoints``: {node_id: "host:port"} or a
        # zero-arg callable re-resolving them (elastic clusters).
        self.metric_monitor = None
        if metric_endpoints:
            from dlrover_tpu.common.metric import JobMetricMonitor

            self.metric_monitor = JobMetricMonitor(metric_endpoints)
        if diagnosis_master is None and with_diagnosis:
            diagnosis_master = self._build_diagnosis_master(pre_check)
        self.diagnosis_master = diagnosis_master
        from dlrover_tpu.master.hyperparams.simple_strategy_generator import (
            SimpleStrategyGenerator,
        )

        self.job_manager.set_strategy_generator(
            SimpleStrategyGenerator(
                self.job_manager,
                global_batch_size=global_batch_size,
                devices_per_node=devices_per_node,
            )
        )
        from dlrover_tpu.master.elastic_training.rescale_coordinator import (
            RescaleCoordinator,
            wire_batch_legality,
        )

        self.rescale_coordinator = RescaleCoordinator(
            node_unit=max(node_group_size, 1),
            bootstrap_min=node_num,
        )
        if global_batch_size > 0 and micro_batch_per_device > 0:
            # Rendezvous and rescale plans only form worlds whose dp
            # size divides the global batch — otherwise a partial-
            # survivor world would crash grad_accum_for() on arrival.
            from dlrover_tpu.trainer.elastic.trainer import (
                ElasticBatchConfig,
            )

            wire_batch_legality(
                self.rdzv_managers,
                self.rescale_coordinator,
                ElasticBatchConfig(
                    global_batch_size=global_batch_size,
                    micro_batch_per_device=micro_batch_per_device,
                ),
                local_world_size=devices_per_node,
            )
        # Recent trace trees (workers push span summaries over the
        # diagnosis-data verb; /api/traces serves them).
        from dlrover_tpu.observability import tracing as tracing_lib

        self.trace_aggregator = tracing_lib.TraceAggregator()
        # Master-side spans (servicer server spans) reach /api/traces
        # too when the master traces — armed explicitly or via the
        # DLROVER_TPU_TRACE_FILE env rigging.
        _tracer = (
            tracing_lib.active_tracer()
            or tracing_lib.arm_from_env(service="master")
        )
        if _tracer is not None:
            _tracer.set_on_finish(self.trace_aggregator.ingest_one)
        # Durable control-plane journal (DESIGN.md §37). Rehydrate
        # BEFORE the servicer is built: the servicer's replica-token
        # seed check must see the restored token, not mint a new one.
        from dlrover_tpu.master.elastic_training.kv_store import (
            KVStoreService,
        )
        from dlrover_tpu.master.elastic_training.sync_service import (
            SyncService,
        )
        from dlrover_tpu.master.journal import (
            MasterJournal,
            journal_path_from_env,
            restore_master_state,
        )

        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.journal = None
        jpath = journal_path or journal_path_from_env()
        if jpath:
            self.journal = MasterJournal(jpath)
            restore_master_state(
                self.journal.recovered,
                task_manager=self.task_manager,
                kv_store=self.kv_store,
                rescale_coordinator=self.rescale_coordinator,
                sync_service=self.sync_service,
                rdzv_managers=self.rdzv_managers,
                job_manager=self.job_manager,
            )
            self.rescale_coordinator.on_plan_cut = (
                lambda plan: self.journal.append(
                    "plan_cut", plan_id=plan.plan_id
                )
            )
        self.servicer = MasterServicer(
            rdzv_managers=self.rdzv_managers,
            task_manager=self.task_manager,
            job_manager=self.job_manager,
            diagnosis_master=diagnosis_master,
            perf_monitor=self.perf_monitor,
            sync_service=self.sync_service,
            kv_store=self.kv_store,
            rescale_coordinator=self.rescale_coordinator,
            trace_aggregator=self.trace_aggregator,
            journal=self.journal,
        )
        self._server = create_master_server(port, self.servicer, transport)
        if self.journal is not None and hasattr(
            self._server, "add_shutdown_hook"
        ):
            self._server.add_shutdown_hook(self.journal.close)
        self.port = self._server.port
        self._node_num = node_num
        self._stopped = threading.Event()
        self.exit_reason = ""

        if topology_aware:
            from dlrover_tpu.master.elastic_training.net_topology import (
                DpTopologySorter,
            )

            training_rdzv = self.rdzv_managers.get(RendezvousName.TRAINING)
            if training_rdzv is not None and hasattr(
                training_rdzv, "set_topology_sorter"
            ):
                training_rdzv.set_topology_sorter(DpTopologySorter())

        from dlrover_tpu.master.stats.job_collector import JobMetricCollector

        stats_reporter = None
        if brain_addr:
            from dlrover_tpu.brain.client import BrainStatsReporter

            stats_reporter = BrainStatsReporter(brain_addr, job_name)
        self.metric_collector = JobMetricCollector(
            job_name,
            self.job_manager,
            self.perf_monitor,
            reporter=stats_reporter,
        )
        # §30 closed-loop autoscaler (self.autoscaler — distinct from
        # the legacy throughput-driven self.auto_scaler below): observe
        # the live signal plane, decide through deterministic rules,
        # actuate world changes via the proven execute_plan path +
        # rescale-coordinator evictions.
        self.autoscaler = None
        self.fault_history = None
        if (auto_scale and autoscale_loop and autoscale_max_world > 0):
            # Two independent world controllers issuing conflicting
            # targets would oscillate the rendezvous window; refuse the
            # combination instead of racing.
            raise ValueError(
                "--auto_scale and --autoscale_loop with "
                "--autoscale_max_world both drive the worker count; "
                "pick one world controller"
            )
        if autoscale_loop:
            self._build_autoscaler(
                scaler, autoscale_dry_run, autoscale_interval_s,
                brain_addr,
                max_world=autoscale_max_world,
                legal_worker_counts=legal_worker_counts,
                ckpt_interval_s=autoscale_ckpt_interval_s,
                record_path=autoscale_record,
            )
        self.dashboard = None
        if dashboard_port >= 0:
            from dlrover_tpu.master.dashboard import DashboardServer

            self.dashboard = DashboardServer(
                self.job_manager,
                self.perf_monitor,
                dashboard_port,
                rdzv_managers=self.rdzv_managers,
                task_manager=self.task_manager,
                # /metrics also exposes the out-of-band daemon
                # aggregates when the metric monitor is on.
                metric_context=(
                    self.metric_monitor.context
                    if self.metric_monitor is not None
                    else None
                ),
                trace_aggregator=self.trace_aggregator,
                autoscaler=self.autoscaler,
                # §32: /api/control_plane — overload governor state,
                # per-verb RPC telemetry, bounded-buffer occupancy.
                control_plane=self.servicer.control_plane_state,
            )
        self.auto_scaler = None
        if auto_scale:
            from dlrover_tpu.master.node.job_auto_scaler import (
                AllreduceTrainingAutoScaler,
            )

            if brain_addr:
                from dlrover_tpu.brain.client import BrainResourceOptimizer

                optimizer = BrainResourceOptimizer(brain_addr, job_name)
            else:
                from dlrover_tpu.master.resource.optimizer import (
                    AllreduceLocalOptimizer,
                )

                optimizer = AllreduceLocalOptimizer(
                    self.job_manager,
                    self.perf_monitor,
                    legal_counts=legal_worker_counts,
                )
            self.auto_scaler = AllreduceTrainingAutoScaler(
                self.job_manager,
                scaler,
                optimizer,
                rdzv_managers=self.rdzv_managers,
            )

    def _build_autoscaler(self, scaler, dry_run: bool, interval_s: float,
                          brain_addr: str, max_world: int = 0,
                          legal_worker_counts=None,
                          ckpt_interval_s: float = 60.0,
                          record_path: str = ""):
        from dlrover_tpu.autoscaler import (
            AutoScaler,
            BrainPrior,
            CadenceController,
            EVICT_STRAGGLER,
            FaultHistory,
            GROW_WORLD,
            PolicyConfig,
            RulePolicy,
            SEED_WORLD,
            SET_CKPT_INTERVAL,
            SHRINK_WORLD,
            SignalBus,
            SignalRecorder,
            control_plane_source,
            data_source,
            fault_source,
            fleet_source,
            perf_source,
        )
        from dlrover_tpu.master.node.event_callback import (
            NodeEventCallback,
        )
        from dlrover_tpu.master.node.job_auto_scaler import (
            AllreduceTrainingAutoScaler,
        )
        from dlrover_tpu.master.resource.optimizer import ResourcePlan

        self.fault_history = FaultHistory()
        history = self.fault_history

        class _FaultFeed(NodeEventCallback):
            """Node deaths feed the observed-MTBF tracker."""

            def on_node_started(self, node):
                pass

            def on_node_succeeded(self, node):
                pass

            def on_node_deleted(self, node):
                pass

            def on_node_failed(self, node):
                history.record_failure()

        self.job_manager.add_node_event_callback(_FaultFeed())
        # The cadence knob: SET_CKPT_INTERVAL actuates it, the "ckpt"
        # source feeds the policy the interval it is steering (without
        # the source the Young/Daly rule can never fire), and trainers
        # poll it as self.ckpt_cadence.interval_s().
        self.ckpt_cadence = CadenceController(ckpt_interval_s)
        bus = (
            SignalBus()
            .add_source("perf", perf_source(self.perf_monitor))
            .add_source("data", data_source(self.task_manager))
            .add_source("fleet", fleet_source())
            .add_source("fault", fault_source(history))
            .add_source("ckpt", self.ckpt_cadence.as_source())
            # §32: the master's own saturation — a policy can refuse
            # scale-up when the control plane, not the accelerators,
            # is the binding constraint.
            .add_source("control_plane", control_plane_source(
                self.servicer.control_plane_state
            ))
            .add_source("world", lambda: {
                "size": len(
                    self.job_manager.worker_manager.alive_nodes()
                ),
            })
        )
        # World moves are opt-in (max_world > 0 unpins the backlog
        # rules). With a legal-counts list the cap is clamped to the
        # largest legal shape AND every grow/shrink targets the next
        # legal count (policy._next_world) — the loop can never order
        # a world the rendezvous would refuse to form.
        if max_world > 0 and legal_worker_counts:
            legal_caps = [
                c for c in legal_worker_counts if c <= max_world
            ]
            max_world = max(legal_caps) if legal_caps else 0
        policy = RulePolicy(PolicyConfig(
            max_world=max_world,
            legal_world_counts=(
                list(legal_worker_counts) if legal_worker_counts
                else None
            ),
        ))
        # World moves reuse the proven execute_plan path (group resize
        # through the scaler + rendezvous window update); its optimizer
        # is never consulted — the §30 policy IS the optimizer here.
        executor = AllreduceTrainingAutoScaler(
            self.job_manager, scaler, optimizer=None,
            rdzv_managers=self.rdzv_managers,
        )

        def set_world(decision):
            plan = ResourcePlan(comment=decision.reason[:120])
            plan.node_group_resources[NodeType.WORKER] = (
                NodeGroupResource(count=int(decision.target))
            )
            executor.execute_plan(plan)

        def evict(decision):
            # The coordinator cuts the scale-down plan; the job
            # manager's normal relaunch machinery replaces the seat.
            rank = int(decision.target)
            if not self.rescale_coordinator.evict_worker(rank):
                raise ValueError(
                    f"rank {decision.target} not in the live set"
                )
            # The replacement must not inherit the evictee's slow
            # step-time EWMA (an evict loop on a healthy worker).
            self.perf_monitor.reset_rank(rank)

        self.autoscaler = AutoScaler(
            bus,
            policy=policy,
            actuators={
                EVICT_STRAGGLER: evict,
                GROW_WORLD: set_world,
                SHRINK_WORLD: set_world,
                SEED_WORLD: set_world,
                # The cadence lands on the controller; workers with no
                # push channel read the recommendation off the
                # autoscaler_ckpt_interval_s gauge + /api/autoscaler.
                SET_CKPT_INTERVAL: self.ckpt_cadence.apply,
            },
            interval_s=interval_s,
            dry_run=dry_run,
            brain_prior=(
                BrainPrior(brain_addr, self.job_name)
                if brain_addr else None
            ),
            job_name=self.job_name,
            # §34: durable signal/decision/outcome recording for
            # offline what-if replay; env arming still applies when
            # the flag is unset.
            recorder=(
                SignalRecorder(record_path) if record_path else None
            ),
        )

    def _build_diagnosis_master(self, pre_check: bool):
        from dlrover_tpu.diagnosis.diagnosis_manager import DiagnosisManager
        from dlrover_tpu.diagnosis.diagnosticians.node_failure import (
            NodeFailureDiagnostician,
            NodeInconsistencyDiagnostician,
        )
        from dlrover_tpu.diagnosis.diagnosticians.training_hang import (
            TrainingHangDiagnostician,
        )
        from dlrover_tpu.diagnosis.precheck import (
            ConnectionPreCheckOperator,
            SchedulingPreCheckOperator,
        )
        from dlrover_tpu.master.diagnosis.diagnosis_master import (
            DiagnosisMaster,
        )

        manager = DiagnosisManager()
        operators = []
        if pre_check:
            operators = [
                SchedulingPreCheckOperator(self.job_manager),
                # Lazy: the servicer exists by the time pre_check() runs.
                ConnectionPreCheckOperator(
                    lambda: self.servicer.node_last_contact()
                ),
            ]
        dm = DiagnosisMaster(
            pre_check_operators=operators, manager=manager
        )
        from dlrover_tpu.diagnosis.diagnosis_data import DiagnosisDataType

        manager.register(
            TrainingHangDiagnostician(
                self.perf_monitor,
                self.job_manager,
                metric_context=(
                    self.metric_monitor.context
                    if self.metric_monitor is not None
                    else None
                ),
                # Late-bound: workers' relayed stack dumps let the hang
                # escalation name the blocked frame.
                stack_dump_provider=lambda: dm.recent_data(
                    DiagnosisDataType.STACK_DUMP
                ),
            )
        )
        manager.register(NodeFailureDiagnostician())
        manager.register(NodeInconsistencyDiagnostician())
        return dm

    @classmethod
    def from_args(cls, args) -> "DistributedJobMaster":
        """Build the master for a CLI platform choice (reference
        master/main.py + scheduler/factory.py new_job_args)."""
        if args.platform == "sim":
            from dlrover_tpu.testing.sim_cluster import (
                SimCluster,
                SimNodeWatcher,
                SimScaler,
            )

            cluster = SimCluster()
            scaler = SimScaler(args.job_name, cluster)
            watcher = SimNodeWatcher(args.job_name, cluster)
        elif args.platform in ("k8s", "gke_tpu"):
            try:
                from dlrover_tpu.master.scaler.pod_scaler import PodScaler
                from dlrover_tpu.master.watcher.k8s_watcher import PodWatcher

                scaler = PodScaler(args.job_name, args.namespace)
                watcher = PodWatcher(args.job_name, args.namespace)
            except ImportError as e:
                raise SystemExit(
                    f"platform {args.platform!r} needs the kubernetes "
                    f"python client installed on the master: {e}"
                )
        else:
            raise ValueError(f"unknown platform {args.platform!r}")
        legal_counts = None
        raw_counts = getattr(args, "legal_worker_counts", "")
        if raw_counts:
            legal_counts = [int(c) for c in raw_counts.split(",") if c]
        return cls(
            port=args.port,
            job_name=args.job_name,
            node_num=args.node_num,
            scaler=scaler,
            watcher=watcher,
            max_relaunch_count=args.max_relaunch_count,
            transport=args.transport,
            pre_check=getattr(args, "pre_check", False),
            auto_scale=getattr(args, "auto_scale", False),
            legal_worker_counts=legal_counts,
            dashboard_port=getattr(args, "dashboard_port", -1),
            global_batch_size=getattr(args, "global_batch_size", 0),
            micro_batch_per_device=getattr(
                args, "micro_batch_per_device", 0
            ),
            devices_per_node=getattr(args, "devices_per_node", 4),
            brain_addr=getattr(args, "brain_addr", ""),
            metric_endpoints=_parse_metric_endpoints(
                getattr(args, "metric_endpoints", "")
            ),
            node_group_size=getattr(args, "node_unit", 0),
            topology_aware=getattr(args, "topology_aware", False),
            autoscale_loop=getattr(args, "autoscale_loop", False),
            autoscale_dry_run=getattr(args, "autoscale_dry_run", False),
            autoscale_interval_s=getattr(
                args, "autoscale_interval_s", 5.0
            ),
            autoscale_max_world=getattr(
                args, "autoscale_max_world", 0
            ),
            autoscale_ckpt_interval_s=getattr(
                args, "autoscale_ckpt_interval_s", 60.0
            ),
            autoscale_record=getattr(args, "autoscale_record", ""),
        )

    # ---- lifecycle ---------------------------------------------------------

    def prepare(self):
        for mgr in self.rdzv_managers.values():
            mgr.update_rdzv_params(
                min_nodes=self._node_num,
                max_nodes=self._node_num,
                waiting_timeout=30.0,
            )
        self._server.start()
        # Late-bind the master address into worker env injection: the RPC
        # port is only known after the server starts.
        from dlrover_tpu.common.env_utils import get_hostname_ip

        self.job_manager.set_master_addr(
            f"{get_hostname_ip()[1]}:{self.port}"
        )
        self.job_manager.start()
        self.task_manager.start()
        self.metric_collector.start()
        if self.metric_monitor is not None:
            self.metric_monitor.start()
        if self.dashboard is not None:
            self.dashboard.start()
        if self.auto_scaler is not None:
            self.auto_scaler.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        if self.diagnosis_master is not None:
            self.diagnosis_master.start_observing()
        logger.info(
            "distributed master [%s] serving on port %d (%d workers)",
            self.job_name,
            self.port,
            self._node_num,
        )

    def pre_check(self) -> bool:
        if self.diagnosis_master is None:
            return True
        return self.diagnosis_master.pre_check()

    def run(self) -> int:
        diag_thread = threading.Thread(
            target=self._diagnose_loop, name="master-diagnose", daemon=True
        )
        diag_thread.start()
        try:
            while not self._stopped.is_set():
                time.sleep(JobConstant.MASTER_RUN_LOOP_INTERVAL)
                if self.job_manager.all_workers_exited():
                    if self.job_manager.all_workers_succeeded():
                        self.exit_reason = JobExitReason.SUCCEEDED
                        logger.info("all workers succeeded; master exiting")
                        return 0
                    self.exit_reason = JobExitReason.WORKER_ERROR
                    logger.error("workers failed; master exiting")
                    return 1
                if self.job_manager.pending_timed_out():
                    self.exit_reason = JobExitReason.UNKNOWN
                    logger.error("workers pending too long; aborting job")
                    return 1
                if self.task_manager.finished():
                    logger.info("all data shards consumed; job finishing")
                    self.exit_reason = JobExitReason.SUCCEEDED
                    return 0
            return 0 if self.exit_reason == JobExitReason.SUCCEEDED else 1
        finally:
            self.stop()

    def _diagnose_loop(self):
        """Execute master-level diagnosis actions (reference
        dist_master.py:236 _diagnose_job)."""
        while not self._stopped.is_set():
            time.sleep(1.0)
            action = self._job_context.next_master_action()
            if action is None:
                continue
            from dlrover_tpu.training_event import MasterEvents

            MasterEvents.diagnosis_action(action.action_type, action.reason)
            if action.action_type == DiagnosisActionType.JOB_RESTART:
                logger.warning("diagnosis: restarting workers (%s)",
                               action.reason)
                self.job_manager.restart_worker_processes(action.reason)
            elif action.action_type == DiagnosisActionType.JOB_ABORT:
                logger.error("diagnosis: aborting job (%s)", action.reason)
                self.exit_reason = JobExitReason.HANG_ERROR
                self._stopped.set()

    def stop(self):
        self._stopped.set()
        self.metric_collector.report_completion(
            success=self.exit_reason == JobExitReason.SUCCEEDED,
            exit_reason=self.exit_reason,
            failure_count=self._job_context.failure_count,
        )
        self.metric_collector.stop()
        if self.metric_monitor is not None:
            self.metric_monitor.stop()
        if self.dashboard is not None:
            self.dashboard.stop()
        if self.auto_scaler is not None:
            self.auto_scaler.stop()
        if self.autoscaler is not None:
            # Reports the achieved goodput back to the brain (the §30
            # prior's learning half) before the loop goes down.
            self.autoscaler.stop(
                success=self.exit_reason == JobExitReason.SUCCEEDED
            )
        if self.diagnosis_master is not None:
            self.diagnosis_master.stop_observing()
        self.task_manager.stop()
        self.job_manager.stop()
        graceful = getattr(self._server, "graceful_stop", None)
        if graceful is not None:
            graceful()
        else:
            self._server.stop()
        if self.journal is not None and not self.journal.closed:
            self.journal.close()

    def request_stop(self):
        self._stopped.set()
