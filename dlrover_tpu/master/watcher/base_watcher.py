"""Node watcher abstraction: observe the cluster backend as NodeEvents.

Parity: reference dlrover/python/master/watcher/base_watcher.py — the job
manager consumes ``watch()`` as a (blocking) event stream and calls
``list()`` on startup to reconcile pre-existing nodes.
"""

import abc
from typing import Iterator, List

from dlrover_tpu.common.node import Node, NodeEvent


class NodeWatcher(abc.ABC):
    def __init__(self, job_name: str):
        self._job_name = job_name

    @abc.abstractmethod
    def watch(self) -> Iterator[NodeEvent]:
        """Blocking stream of node change events; returns on stop()."""

    @abc.abstractmethod
    def list(self) -> List[Node]:
        """Snapshot of currently existing nodes of this job."""

    def stop(self):
        pass
