"""Kubernetes Pod watcher: pod events -> NodeEvents.

Parity: reference dlrover/python/master/watcher/k8s_watcher.py:274
(PodWatcher) — maps pod phases and container termination details onto
the node status flow, including the exit reasons the relaunch policy
keys on (OOMKilled, preemption, TPU-host faults).
"""

import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import (
    ExitCode,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeEvent, NodeResource
from dlrover_tpu.master.scheduler.k8s_client import K8sApi, get_k8s_api
from dlrover_tpu.master.watcher.base_watcher import NodeWatcher

_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.UNKNOWN,
}


def _termination_exit_reason(pod: Dict) -> str:
    """Derive the relaunch-policy exit reason from container state +
    pod conditions (reference k8s_watcher _get_pod_exit_reason)."""
    status = pod.get("status", {})
    reason = status.get("reason", "")
    if reason in ("Preempted", "Evicted", "Shutdown"):
        return NodeExitReason.PREEMPTED
    for cs in status.get("containerStatuses", []) or []:
        term = (cs.get("state", {}) or {}).get("terminated")
        if not term:
            term = (cs.get("lastState", {}) or {}).get("terminated")
        if not term:
            continue
        if term.get("reason") == "OOMKilled":
            return NodeExitReason.OOM
        code = term.get("exitCode", 0)
        if code in (ExitCode.HARDWARE_ERROR, ExitCode.GPU_DRIVER_ERROR):
            return NodeExitReason.HARDWARE_ERROR
        if code == ExitCode.NODE_CHECK_FAILED:
            return NodeExitReason.HARDWARE_ERROR
        if code in (ExitCode.KILLED, ExitCode.TERMED):
            return NodeExitReason.KILLED
        if code != 0:
            return NodeExitReason.FATAL_ERROR
    return ""


def pod_to_node(pod: Dict) -> Optional[Node]:
    meta = pod.get("metadata", {})
    labels = meta.get("labels", {}) or {}
    if labels.get("app") != "dlrover-tpu":
        return None
    try:
        node_id = int(labels.get("node-id", "-1"))
        rank = int(labels.get("rank-index", node_id))
    except ValueError:
        return None
    if node_id < 0:
        return None
    status = pod.get("status", {})
    node = Node(
        node_type=labels.get("node-type", NodeType.WORKER),
        node_id=node_id,
        rank_index=rank,
        name=meta.get("name", ""),
        host_name=pod.get("spec", {}).get("nodeName", ""),
        host_ip=status.get("podIP", "") or status.get("hostIP", ""),
        status=_PHASE_TO_STATUS.get(
            status.get("phase", ""), NodeStatus.UNKNOWN
        ),
        config_resource=NodeResource(),
    )
    node.exit_reason = _termination_exit_reason(pod)
    return node


class PodWatcher(NodeWatcher):
    def __init__(
        self,
        job_name: str,
        namespace: str = "default",
        api: Optional[K8sApi] = None,
    ):
        super().__init__(job_name)
        self._namespace = namespace
        self._api = api or get_k8s_api()
        self._label_selector = f"app=dlrover-tpu,job-name={job_name}"
        self._stopped = False

    def watch(self):
        while not self._stopped:
            try:
                for raw in self._api.watch_pods(
                    self._namespace, self._label_selector
                ):
                    if self._stopped:
                        return
                    node = pod_to_node(raw.get("object", {}))
                    if node is None:
                        continue
                    yield NodeEvent(raw.get("type", "MODIFIED"), node)
            except GeneratorExit:
                raise
            except Exception:
                if self._stopped:
                    return
                logger.exception("pod watch stream broke; re-watching")
                time.sleep(2.0)  # don't hot-loop a broken API server

    def list(self) -> List[Node]:
        nodes = []
        for pod in self._api.list_pods(
            self._namespace, self._label_selector
        ):
            node = pod_to_node(pod)
            if node is not None:
                nodes.append(node)
        return nodes

    def stop(self):
        self._stopped = True
