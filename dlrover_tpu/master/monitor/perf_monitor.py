"""Job-level performance monitor: step throughput and goodput accounting.

Parity: reference dlrover/python/master/monitor/perf_monitor.py:45
(PerfMonitor: global step speed, straggler-ish stats). Extended with an
explicit goodput ledger — wall time attributed to train/ckpt/restart/
rendezvous phases — because goodput-under-faults is this framework's
north-star metric.
"""

import threading
import time
from collections import defaultdict, deque
from typing import Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import GoodputPhase
from dlrover_tpu.observability.registry import default_registry


class PerfMonitor:
    # §34 lost-time cause taxonomy: every non-train wall second is
    # attributed to the decision/fault that cost it, or lands in the
    # single residual bucket "unattributed". The /api/goodput view and
    # the soak's ≥90%-attribution invariant read these names verbatim.
    CAUSES = ("ckpt", "rescale", "straggler", "hang", "shed")
    UNATTRIBUTED = "unattributed"
    # Phases whose cause is implied when the reporter passes none.
    _PHASE_CAUSE = {
        GoodputPhase.CKPT: "ckpt",
        GoodputPhase.RESTART: "rescale",
        GoodputPhase.RENDEZVOUS: "rescale",
    }

    def __init__(self, speed_window: int = 30, max_phase_records: int = 4096):
        self._lock = threading.Lock()
        self._start_time = time.time()
        self._global_step = 0
        self._last_step_report: Optional[Tuple[int, float]] = None
        self._speed_records: Deque[float] = deque(maxlen=speed_window)
        self._total_train_secs = 0.0
        # phase -> node_id -> seconds; goodput is averaged per node so a
        # multi-node job cannot saturate the metric at 1.0.
        self._phase_secs: Dict[str, Dict[int, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        # cause -> node_id -> lost seconds (non-train intervals only).
        self._cause_secs: Dict[str, Dict[int, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        # Raw (node, phase, start, end) intervals, bounded: the timeline
        # merger needs the intervals themselves, not just the sums.
        # Evictions are counted — after one, the records can no longer
        # reproduce goodput() exactly and consumers must know.
        self._phase_records: Deque[Dict] = deque(maxlen=max_phase_records)
        self._phase_records_dropped = 0
        self._max_phase_end = 0.0
        self._init_time = time.time()
        # Per-rank step-time EWMAs -> the straggler score (§29): skew of
        # one rank's step wall time against the fleet median. Fed by the
        # step_time_s piggyback on GlobalStepReport.
        self._rank_step_ewma: Dict[int, float] = {}
        self._rank_step_reports: Dict[int, int] = {}
        # §32: the gauge path is O(1) per report. A running median
        # ESTIMATOR (sign-step with a multiplicative delta, FAME-style)
        # tracks the fleet median incrementally; only the reporting
        # rank's gauge is refreshed per report; an exact resync runs
        # every ~R reports so estimator drift is bounded (amortized
        # O(log R) per report). straggler_report() stays an exact
        # recompute — its output is the contract.
        self._median_est = 0.0
        self._median_delta = 0.0
        self._reports_since_sync = 0
        registry = default_registry()
        self._phase_secs_counter = registry.counter(
            "dlrover_goodput_phase_seconds_total",
            "wall seconds attributed to each goodput phase",
            labelnames=("name",),
        )
        self._step_reports_counter = registry.counter(
            "dlrover_step_reports_total",
            "global-step reports received by the master",
        )
        self._straggler_gauge = registry.gauge(
            "dlrover_straggler_score",
            "per-rank step-time skew vs the fleet median (1.0 = median)",
            labelnames=("rank",),
        )
        self._lost_secs_counter = registry.counter(
            "dlrover_goodput_lost_seconds_total",
            "non-train wall seconds by attributed cause (§34 taxonomy)",
            labelnames=("cause",),
        )

    # ---- step speed --------------------------------------------------------

    def collect_global_step(
        self,
        step: int,
        timestamp: float,
        elapsed_train_secs: float = 0.0,
        node_id: int = -1,
        step_time_s: float = 0.0,
    ):
        with self._lock:
            if self._last_step_report is not None:
                prev_step, prev_ts = self._last_step_report
                dt = timestamp - prev_ts
                dstep = step - prev_step
                if dt > 0 and dstep > 0:
                    self._speed_records.append(dstep / dt)
            self._last_step_report = (step, timestamp)
            self._global_step = max(self._global_step, step)
            if elapsed_train_secs > 0:
                self._total_train_secs += elapsed_train_secs
            if node_id >= 0 and step_time_s > 0:
                prev = self._rank_step_ewma.get(node_id)
                ewma = (
                    step_time_s if prev is None
                    else 0.3 * step_time_s + 0.7 * prev
                )
                self._rank_step_ewma[node_id] = ewma
                self._rank_step_reports[node_id] = (
                    self._rank_step_reports.get(node_id, 0) + 1
                )
                gauge_score, resync = self._incremental_median_locked(ewma)
        self._step_reports_counter.inc()
        if node_id >= 0 and step_time_s > 0:
            # O(1) per report: only THIS rank's gauge moves, scored
            # against the running median estimate — the old path
            # recomputed the full O(R log R) report per gauge window.
            self._straggler_gauge.set(gauge_score, rank=str(node_id))
            if resync:
                # Amortized exact resync (~every R reports): bounds
                # estimator drift at O(log R) amortized per report.
                self._update_straggler_gauges()

    def _incremental_median_locked(self, ewma: float):
        """FAME-style running median: step the estimate toward each new
        observation by a delta that halves when the observation lands
        within delta of the estimate. O(1); called under ``_lock``.
        Returns (score-for-this-rank, exact-resync-due)."""
        if self._median_est <= 0.0:
            self._median_est = ewma
            self._median_delta = max(ewma / 2.0, 1e-9)
        else:
            if ewma > self._median_est:
                self._median_est += self._median_delta
            elif ewma < self._median_est:
                self._median_est -= self._median_delta
            if abs(ewma - self._median_est) < self._median_delta:
                self._median_delta = max(
                    self._median_delta / 2.0, self._median_est * 1e-3
                )
        self._reports_since_sync += 1
        resync = self._reports_since_sync >= max(
            len(self._rank_step_ewma), 32
        )
        if resync:
            self._reports_since_sync = 0
        return ewma / max(self._median_est, 1e-9), resync

    # ---- straggler score ---------------------------------------------------

    STRAGGLER_THRESHOLD = 1.5
    STRAGGLER_MIN_REPORTS = 3

    def straggler_report(
        self,
        threshold: Optional[float] = None,
        min_reports: Optional[int] = None,
    ) -> Dict:
        """Per-rank step-time skew: ``score = rank EWMA / fleet
        median``; a rank is flagged once its score clears ``threshold``
        over at least ``min_reports`` reports (one slow step must not
        page anyone). Live view behind ``/api/stragglers`` and the
        ``dlrover_straggler_score`` gauge."""
        threshold = (
            threshold if threshold is not None else self.STRAGGLER_THRESHOLD
        )
        min_reports = (
            min_reports if min_reports is not None
            else self.STRAGGLER_MIN_REPORTS
        )
        with self._lock:
            ewmas = dict(self._rank_step_ewma)
            reports = dict(self._rank_step_reports)
        if not ewmas:
            return {
                "ranks": {}, "stragglers": [],
                "median_step_time_s": 0.0, "threshold": threshold,
            }
        ordered = sorted(ewmas.values())
        mid = len(ordered) // 2
        median = (
            ordered[mid] if len(ordered) % 2
            else 0.5 * (ordered[mid - 1] + ordered[mid])
        )
        ranks = {}
        stragglers = []
        for rank, ewma in sorted(ewmas.items()):
            score = ewma / max(median, 1e-9)
            flagged = (
                len(ewmas) >= 2
                and score >= threshold
                and reports.get(rank, 0) >= min_reports
            )
            ranks[rank] = {
                "step_time_ewma_s": round(ewma, 6),
                "score": round(score, 4),
                "reports": reports.get(rank, 0),
                "flagged": flagged,
            }
            if flagged:
                stragglers.append(rank)
        return {
            "ranks": ranks,
            "stragglers": stragglers,
            "median_step_time_s": round(median, 6),
            "threshold": threshold,
        }

    def _update_straggler_gauges(self):
        """Exact gauge resync from a full straggler_report() — no
        longer on the per-report hot path (§32 replaced the old
        throttled full recompute with the O(1) incremental estimator);
        runs amortized every ~R reports, on explicit demand, and keeps
        the estimator honest by re-anchoring it to the true median."""
        report = self.straggler_report()
        for rank, info in report["ranks"].items():
            self._straggler_gauge.set(info["score"], rank=str(rank))
        median = report["median_step_time_s"]
        if median > 0:
            with self._lock:
                self._median_est = median
                self._median_delta = max(
                    self._median_delta, median * 1e-3
                )

    def reset_rank(self, rank: int):
        """Forget one rank's step-time history — called when the seat's
        OCCUPANT changes (straggler evicted, node replaced): the
        replacement must not inherit its predecessor's slow EWMA and
        report count, or a 3x-median ghost score re-flags a healthy
        worker for several reports (an evict loop at real step
        times)."""
        with self._lock:
            self._rank_step_ewma.pop(rank, None)
            self._rank_step_reports.pop(rank, None)
        self._straggler_gauge.set(0.0, rank=str(rank))

    @property
    def global_step(self) -> int:
        with self._lock:
            return self._global_step

    def running_speed(self) -> float:
        """Steps/sec over the sliding window."""
        with self._lock:
            if not self._speed_records:
                return 0.0
            return sum(self._speed_records) / len(self._speed_records)

    def step_stagnated(self, timeout_secs: float) -> bool:
        """True if no step progress has been reported for timeout_secs —
        the cheap hang signal used by the hang diagnostician."""
        with self._lock:
            if self._last_step_report is None:
                return False
            return (time.time() - self._last_step_report[1]) > timeout_secs

    # ---- goodput ledger ----------------------------------------------------

    def collect_phase(self, node_id: int, phase: str, start: float,
                      end: float, cause: Optional[str] = None):
        """Attribute one wall interval. Non-train intervals also carry
        a lost-time ``cause`` from the §34 taxonomy (:attr:`CAUSES`):
        explicit when the reporter knows who to blame (the autoscaler's
        eviction pause is ``straggler``, an overload shed is ``shed``),
        implied from the phase otherwise (ckpt→ckpt, restart→rescale),
        and ``unattributed`` as the only residual bucket."""
        if end <= start:
            return
        record = {
            "node_id": node_id,
            "phase": phase,
            "start": start,
            "end": end,
        }
        if phase == GoodputPhase.TRAIN:
            cause = None
        else:
            cause = cause or self._PHASE_CAUSE.get(
                phase, self.UNATTRIBUTED
            )
            if cause not in self.CAUSES:
                cause = self.UNATTRIBUTED
            record["cause"] = cause
        with self._lock:
            self._phase_secs[phase][node_id] += end - start
            if cause is not None:
                self._cause_secs[cause][node_id] += end - start
            if len(self._phase_records) == self._phase_records.maxlen:
                self._phase_records_dropped += 1
            self._phase_records.append(record)
            self._max_phase_end = max(self._max_phase_end, end)
        self._phase_secs_counter.inc(end - start, name=phase)
        if cause is not None:
            self._lost_secs_counter.inc(end - start, cause=cause)

    def goodput(self) -> float:
        """Fraction of wall time spent in productive training, averaged
        over reporting nodes."""
        with self._lock:
            wall = max(self._max_phase_end - self._init_time, 1e-9)
            per_node = self._phase_secs.get(GoodputPhase.TRAIN, {})
            if not per_node:
                return 0.0
            ratios = [min(t / wall, 1.0) for t in per_node.values()]
            return sum(ratios) / len(ratios)

    def goodput_basis(self) -> Dict:
        """How :meth:`goodput` is computed — previously only a code
        comment. Consumers (dashboards, the autoscaler, SREs reading
        /api/perf) need the averaging mode and node count to interpret
        the number: a 1-node 0.9 and a 64-node 0.9 are different
        claims."""
        with self._lock:
            per_node = self._phase_secs.get(GoodputPhase.TRAIN, {})
            return {
                "averaging": "per_node_train_fraction_mean",
                "nodes_reporting": len(per_node),
                "wall_s": round(
                    max(self._max_phase_end - self._init_time, 0.0), 6
                ),
                "wall_origin": "init_time_to_max_phase_end",
                "records_dropped": self._phase_records_dropped,
            }

    def goodput_attribution(self) -> Dict:
        """Per-cause accounting of the non-train wall time (§34): for
        the same node set and wall basis as :meth:`goodput`, how many
        lost seconds each cause explains, and what fraction of the
        lost time is attributed at all. ``unattributed`` is the only
        residual bucket — it covers both intervals reported without a
        cause and wall time nobody reported a phase for."""
        with self._lock:
            wall = max(self._max_phase_end - self._init_time, 1e-9)
            train_nodes = self._phase_secs.get(GoodputPhase.TRAIN, {})
            nodes = set(train_nodes)
            for per_node in self._cause_secs.values():
                nodes.update(per_node)
            if not nodes:
                return {
                    "wall_s": 0.0, "train_frac": 0.0, "lost_frac": 0.0,
                    "causes": {}, "unattributed_frac": 0.0,
                    "attributed_frac": 0.0, "nodes": 0,
                }
            n = len(nodes)
            train_frac = sum(
                min(train_nodes.get(node, 0.0) / wall, 1.0)
                for node in nodes
            ) / n
            causes: Dict[str, Dict[str, float]] = {}
            explained = 0.0
            for cause in (*self.CAUSES, self.UNATTRIBUTED):
                per_node = self._cause_secs.get(cause, {})
                secs = sum(per_node.get(node, 0.0) for node in nodes) / n
                frac = min(secs / wall, 1.0)
                causes[cause] = {
                    "seconds": round(secs, 6),
                    "frac": round(frac, 6),
                }
                if cause != self.UNATTRIBUTED:
                    explained += frac
        lost_frac = max(1.0 - train_frac, 0.0)
        explained = min(explained, lost_frac)
        # The residual bucket covers BOTH cause-less reports and
        # never-reported wall time; rewrite seconds and frac together
        # so the two fields of the dict cannot disagree (the reported
        # cause-less seconds alone would understate the residual).
        residual_frac = max(lost_frac - explained, 0.0)
        causes[self.UNATTRIBUTED] = {
            "seconds": round(residual_frac * wall, 6),
            "frac": round(residual_frac, 6),
        }
        return {
            "wall_s": round(wall, 6),
            "train_frac": round(train_frac, 6),
            "lost_frac": round(lost_frac, 6),
            "causes": causes,
            "unattributed_frac": causes[self.UNATTRIBUTED]["frac"],
            "attributed_frac": round(
                explained / lost_frac if lost_frac > 1e-9 else 1.0, 6
            ),
            "nodes": n,
        }

    def phase_breakdown(self, as_fractions: bool = False) -> Dict[str, float]:
        with self._lock:
            totals = {
                phase: sum(nodes.values())
                for phase, nodes in self._phase_secs.items()
            }
        if not as_fractions:
            return totals
        grand = sum(totals.values())
        if grand <= 0:
            return {phase: 0.0 for phase in totals}
        return {phase: secs / grand for phase, secs in totals.items()}

    def buffer_stats(self) -> Dict:
        """§32 bounded-buffer accounting for /api/control_plane: the
        phase-record ring's occupancy + drops without copying the
        records themselves (phase_records() copies; this is the cheap
        saturation view)."""
        with self._lock:
            return {
                "occupancy": len(self._phase_records),
                "capacity": self._phase_records.maxlen,
                "drops": self._phase_records_dropped,
                "ranks_tracked": len(self._rank_step_ewma),
            }

    def phase_records(self) -> Dict:
        """The raw goodput ledger for the timeline merger: the recorded
        (node, phase, start, end) intervals plus the accounting origin,
        so ``trace_merge.reconstruct_goodput`` can reproduce
        :meth:`goodput` exactly — as long as ``records_dropped`` is 0;
        past the ring bound the reconstruction is partial and the merge
        tool downgrades its goodput cross-check to a warning."""
        with self._lock:
            return {
                "init_time": self._init_time,
                "max_phase_end": self._max_phase_end,
                "records_dropped": self._phase_records_dropped,
                "records": [dict(r) for r in self._phase_records],
            }

    def reset(self):
        with self._lock:
            self._global_step = 0
            self._last_step_report = None
            self._speed_records.clear()
            self._phase_secs.clear()
            self._cause_secs.clear()
            self._phase_records.clear()
            self._phase_records_dropped = 0
            self._init_time = time.time()
            self._max_phase_end = 0.0
            self._rank_step_ewma.clear()
            self._rank_step_reports.clear()
            self._median_est = 0.0
            self._median_delta = 0.0
            self._reports_since_sync = 0
