"""Master web dashboard: job/node/rendezvous/data state over HTTP.

Parity: reference dlrover/dashboard (tornado app wired at
master/main.py:100-107, jobs/nodes UI) — rebuilt on the stdlib HTTP
server: JSON APIs (/api/job, /api/perf, /api/nodes, /api/rdzv,
/api/datasets) plus a single self-contained HTML page rendering the
node table (status, exit history, heartbeat age, slice block), the
rendezvous state, dataset progress, and training perf.
"""

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dlrover_tpu.common.log import logger

_PAGE = """<!DOCTYPE html>
<html><head><title>dlrover-tpu</title>
<style>
body{font-family:monospace;margin:2em;background:#fafafa}
table{border-collapse:collapse;margin-bottom:1.2em}
td,th{border:1px solid #999;padding:4px 10px}
h1{font-size:1.3em}h2{font-size:1.05em;margin-bottom:.3em}
.Running{color:green}.Failed,.Breakdown{color:red}
.Pending,.Initial{color:#b8860b}.Succeeded{color:blue}
</style></head><body>
<h1>dlrover-tpu job <span id="job"></span></h1>
<p>stage: <b id="stage"></b> | step: <b id="step"></b> |
speed: <b id="speed"></b> steps/s | goodput: <b id="goodput"></b>%</p>
<h2>nodes</h2>
<table id="nodes"><tr><th>id</th><th>role</th><th>rank</th><th>block</th>
<th>status</th><th>relaunches</th><th>exit history</th>
<th>heartbeat</th><th>host</th></tr></table>
<h2>rendezvous</h2>
<table id="rdzv"><tr><th>name</th><th>round</th><th>waiting</th>
<th>world</th></tr></table>
<h2>datasets</h2>
<table id="data"><tr><th>name</th><th>todo</th><th>doing</th>
<th>completed</th><th>records done</th></tr></table>
<script>
async function j(u){return await (await fetch(u)).json();}
function fill(t, rows){
 while(t.rows.length > 1) t.deleteRow(1);
 for(const cells of rows){
  const r = t.insertRow();
  for(const [v, cls, href] of cells){
   const c = r.insertCell();
   if(href){const a=document.createElement('a');a.href=href;
    a.textContent=v;c.appendChild(a);}
   else c.textContent = v;
   if(cls) c.className = cls;
  }
 }
}
async function refresh(){
 const job = await j('/api/job');
 const perf = await j('/api/perf');
 const nodes = await j('/api/nodes');
 const rdzv = await j('/api/rdzv');
 const data = await j('/api/datasets');
 document.getElementById('job').textContent = job.job_name;
 document.getElementById('stage').textContent = job.stage;
 document.getElementById('step').textContent = perf.global_step;
 document.getElementById('speed').textContent = perf.speed.toFixed(2);
 document.getElementById('goodput').textContent = (perf.goodput*100).toFixed(1);
 fill(document.getElementById('nodes'), nodes.map(n => [
  [n.type + '-' + n.id, '', '/node/' + n.type + '-' + n.id],
  [n.type], [n.rank], [n.node_group < 0 ? '-' : n.node_group],
  [n.status, n.status], [n.relaunch_count],
  [n.exit_history.join(',') || '-'],
  [n.heartbeat_age_s == null ? '-' : n.heartbeat_age_s + 's'],
  [n.host || '']]));
 fill(document.getElementById('rdzv'), rdzv.map(r => [
  [r.name], [r.round], [r.waiting], [r.world_size]]));
 fill(document.getElementById('data'), data.map(d => [
  [d.name], [d.todo], [d.doing], [d.completed], [d.records_done]]));
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


_NODE_PAGE = """<!DOCTYPE html>
<html><head><title>dlrover-tpu node</title>
<style>
body{font-family:monospace;margin:2em;background:#fafafa}
table{border-collapse:collapse;margin-bottom:1.2em}
td,th{border:1px solid #999;padding:4px 10px}
h1{font-size:1.3em}h2{font-size:1.05em;margin-bottom:.3em}
.Running{color:green}.Failed,.Breakdown{color:red}
.Pending,.Initial{color:#b8860b}.Succeeded{color:blue}
</style></head><body>
<p><a href="/">&larr; job</a></p>
<h1>node <span id="name"></span></h1>
<h2>facts</h2>
<table id="facts"><tr><th>field</th><th>value</th></tr></table>
<h2>status timeline</h2>
<table id="tl"><tr><th>time</th><th>status</th><th>+s</th></tr></table>
<h2>exit history</h2>
<table id="exits"><tr><th>#</th><th>reason</th></tr></table>
<script>
async function refresh(){
 const key = location.pathname.split('/').pop();
 const resp = await fetch('/api/node/' + key);
 if(!resp.ok){document.getElementById('name').textContent =
   key + ' (not found)'; return;}
 const n = await resp.json();
 document.getElementById('name').textContent = n.name;
 const facts = document.getElementById('facts');
 while(facts.rows.length > 1) facts.deleteRow(1);
 const rows = [['type', n.type], ['rank', n.rank],
  ['slice block', n.node_group < 0 ? '-' : n.node_group],
  ['status', n.status], ['reported status', n.reported_status || '-'],
  ['host', (n.host || '-') + (n.host_ip ? ' (' + n.host_ip + ')' : '')],
  ['critical', n.critical], ['relaunches',
   n.relaunch_count + ' / ' + n.max_relaunch_count],
  ['relaunchable', n.relaunchable],
  ['unrecoverable', n.unrecoverable || '-'],
  ['exit reason', n.exit_reason || '-'],
  ['heartbeat age', n.heartbeat_age_s == null ? '-'
    : n.heartbeat_age_s + 's'],
  ['resources', 'cpu ' + n.resource.cpu + ', mem ' +
   n.resource.memory_mb + 'MB, chips ' + n.resource.tpu_chips]];
 for(const [k, v] of rows){
  const r = facts.insertRow();
  r.insertCell().textContent = k;
  const c = r.insertCell(); c.textContent = v;
  if(k == 'status') c.className = n.status;
 }
 const tl = document.getElementById('tl');
 while(tl.rows.length > 1) tl.deleteRow(1);
 const t0 = n.timeline.length ? n.timeline[0].ts : 0;
 for(const ev of n.timeline){
  const r = tl.insertRow();
  r.insertCell().textContent = new Date(ev.ts*1000).toISOString();
  const c = r.insertCell(); c.textContent = ev.status;
  c.className = ev.status;
  r.insertCell().textContent = (ev.ts - t0).toFixed(1);
 }
 const ex = document.getElementById('exits');
 while(ex.rows.length > 1) ex.deleteRow(1);
 n.exit_history.forEach((reason, i) => {
  const r = ex.insertRow();
  r.insertCell().textContent = i + 1;
  r.insertCell().textContent = reason;
 });
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class DashboardServer:
    def __init__(
        self,
        job_manager,
        perf_monitor,
        port: int = 0,
        rdzv_managers=None,
        task_manager=None,
        metric_context=None,
        trace_aggregator=None,
        autoscaler=None,
        control_plane=None,
    ):
        self._job_manager = job_manager
        self._perf_monitor = perf_monitor
        self._rdzv_managers = rdzv_managers or {}
        self._task_manager = task_manager
        self._metric_context = metric_context
        self._trace_aggregator = trace_aggregator
        self._autoscaler = autoscaler
        # Zero-arg callable (the servicer's control_plane_state):
        # overload governor state + per-verb RPC telemetry + bounded
        # buffer occupancy/drops (§32).
        self._control_plane = control_plane
        self._requested_port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self.port = 0
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            # Exact-path JSON providers. Each call is guarded in
            # do_GET: one raising subsystem answers its own endpoint
            # with a 503 + JSON error body instead of an unhandled
            # exception (empty reply on the wire), and the OTHER
            # endpoints keep serving — an incident dashboard must
            # degrade per-panel, not whole-page.
            _JSON_ROUTES = {
                "/api/job": lambda: dashboard._job_detail(),
                "/api/perf": lambda: dashboard._perf(),
                "/api/nodes": lambda: dashboard._nodes(),
                "/api/rdzv": lambda: dashboard._rdzv(),
                "/api/datasets": lambda: dashboard._datasets(),
                "/api/phases": lambda: dashboard._phases(),
                # Live per-rank step-time skew (the autoscaler's and
                # SRE's "which rank is slow RIGHT NOW" view).
                "/api/stragglers": lambda: dashboard._stragglers(),
                # §34 per-cause goodput attribution: where the
                # non-train wall time went (train + ckpt/rescale/
                # straggler/hang/shed + unattributed residual), the
                # averaging basis, and the serving-side useful-token
                # fraction merged into one view.
                "/api/goodput": lambda: dashboard._goodput(),
                # The §32 saturation plane: overload governor state,
                # per-verb RPC telemetry, bounded-buffer occupancy.
                "/api/control_plane": (
                    lambda: dashboard._control_plane_state()
                ),
            }

            def do_GET(self):
                if self.path == "/" or self.path.startswith("/index"):
                    self._send(200, _PAGE, "text/html")
                elif self.path in self._JSON_ROUTES:
                    self._send_json(self._JSON_ROUTES[self.path])
                elif self.path == "/metrics":
                    # One Prometheus scrape covers the whole job:
                    # process registry (event-drop counters, phase
                    # second counters, ...) + live goodput/speed + the
                    # per-node daemon aggregates the master scraped.
                    try:
                        text = dashboard._metrics_text()
                    except Exception as e:  # noqa: BLE001 — degrade, don't die
                        self._send_unavailable(e)
                        return
                    self._send(200, text, "text/plain; version=0.0.4")
                elif self.path.startswith("/api/autoscaler"):
                    # The §30/§34 resource brain: live signal snapshot,
                    # the decision ledger (with realized outcomes), and
                    # the dry-run diff. Query params page the ledger
                    # (?last=N&offset=M) and ?signals=compact drops the
                    # per-decision triggering snapshots — a full ledger
                    # over a large world is a multi-MB response.
                    self._send_json(
                        lambda: dashboard._autoscaler_state(self.path)
                    )
                elif self.path.startswith("/api/traces"):
                    self._send_json(
                        lambda: dashboard._traces(self.path)
                    )
                elif self.path.startswith("/api/node/"):
                    try:
                        detail = dashboard._node_detail(
                            self.path.rsplit("/", 1)[-1]
                        )
                    except Exception as e:  # noqa: BLE001
                        self._send_unavailable(e)
                        return
                    if detail is None:
                        self._send(404, "no such node", "text/plain")
                    else:
                        self._send(
                            200, json.dumps(detail), "application/json"
                        )
                elif self.path.startswith("/node/"):
                    self._send(200, _NODE_PAGE, "text/html")
                else:
                    self._send(404, "not found", "text/plain")

            def _send_json(self, provider):
                try:
                    body = json.dumps(provider())
                except Exception as e:  # noqa: BLE001 — 503, not a dead panel
                    self._send_unavailable(e)
                    return
                self._send(200, body, "application/json")

            def _send_unavailable(self, exc):
                self._send(
                    503,
                    json.dumps({
                        "error": f"{type(exc).__name__}: {exc}"[:300],
                        "unavailable": True,
                    }),
                    "application/json",
                )

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        return Handler

    def _job_detail(self):
        detail = self._job_manager.get_job_detail()
        return {
            "job_name": detail.job_name,
            "stage": detail.stage,
            "nodes": detail.nodes,
        }

    def _perf(self):
        perf = {
            "global_step": self._perf_monitor.global_step,
            "speed": self._perf_monitor.running_speed(),
            "goodput": self._perf_monitor.goodput(),
        }
        breakdown = getattr(self._perf_monitor, "phase_breakdown", None)
        if callable(breakdown):
            perf["phase_breakdown"] = breakdown()
            perf["phase_fractions"] = breakdown(as_fractions=True)
        # Averaging mode + node count (was only a code comment): a
        # 1-node 0.9 and a 64-node 0.9 are different claims.
        basis = getattr(self._perf_monitor, "goodput_basis", None)
        if callable(basis):
            perf["goodput_basis"] = basis()
        return perf

    def _goodput(self):
        attribution = getattr(
            self._perf_monitor, "goodput_attribution", None
        )
        basis = getattr(self._perf_monitor, "goodput_basis", None)
        out = {
            "training": attribution() if callable(attribution) else None,
            "goodput_basis": basis() if callable(basis) else None,
            "serving": self._serving_useful_tokens(),
        }
        return out

    @staticmethod
    def _serving_useful_tokens():
        """Serving-side useful-token fraction from the registry: tokens
        computed minus tokens thrown away by progress resets
        (step-error requeues, pool preemptions). Families absent (no
        engine in this process) read as disabled."""
        from dlrover_tpu.observability.registry import default_registry

        reg = default_registry()
        tokens = reg.get("serving_tokens_total")
        if tokens is None:
            return {"enabled": False}
        by_kind = {
            labels.get("kind", ""): value
            for _, labels, value in tokens.samples()
        }
        total = sum(by_kind.values())
        wasted_fam = reg.get("serving_tokens_wasted_total")
        wasted = {}
        if wasted_fam is not None:
            wasted = {
                labels.get("kind", ""): value
                for _, labels, value in wasted_fam.samples()
            }
        wasted_total = sum(wasted.values())
        return {
            "enabled": True,
            "tokens_total": total,
            "tokens_by_kind": by_kind,
            "tokens_wasted_total": wasted_total,
            "tokens_wasted_by_kind": wasted,
            "useful_token_frac": round(
                (total - wasted_total) / total, 6
            ) if total > 0 else None,
        }

    def _phases(self):
        records = getattr(self._perf_monitor, "phase_records", None)
        if callable(records):
            return records()
        return {"init_time": 0.0, "max_phase_end": 0.0, "records": []}

    def _stragglers(self):
        report = getattr(self._perf_monitor, "straggler_report", None)
        if callable(report):
            return report()
        return {"ranks": {}, "stragglers": [], "median_step_time_s": 0.0}

    def _autoscaler_state(self, path: str = "/api/autoscaler"):
        if self._autoscaler is None:
            return {"enabled": False}
        query = urllib.parse.parse_qs(
            urllib.parse.urlparse(path).query
        )

        def q_int(name, default):
            try:
                return max(int(query[name][0]), 0)
            except (KeyError, ValueError, IndexError):
                return default

        compact = (
            query.get("signals", [""])[0] == "compact"
            or query.get("compact", ["0"])[0] in ("1", "true")
        )
        try:
            return self._autoscaler.api_state(
                last=q_int("last", 50),
                offset=q_int("offset", 0),
                compact=compact,
            )
        except Exception as e:  # noqa: BLE001 — dashboard never 500s
            return {"enabled": True, "error": f"{type(e).__name__}: {e}"}

    def _traces(self, path: str):
        """``/api/traces`` -> recent trace summaries (+ the
        aggregator's occupancy/drop accounting — a trace view that
        hides its own losses overstates coverage);
        ``/api/traces/<trace_id>`` -> that trace's nested span tree."""
        agg = self._trace_aggregator
        if agg is None:
            return {"traces": [], "enabled": False}
        tail = path[len("/api/traces"):].strip("/")
        if tail:
            return {"trace_id": tail, "tree": agg.tree(tail)}
        return {
            "traces": agg.recent(),
            "enabled": True,
            "stats": agg.stats(),
        }

    def _control_plane_state(self):
        if self._control_plane is None:
            return {"enabled": False}
        state = self._control_plane()
        state["enabled"] = True
        return state

    def _metrics_text(self):
        from dlrover_tpu.observability.prom import master_metrics_text

        return master_metrics_text(
            perf_monitor=self._perf_monitor,
            metric_context=self._metric_context,
        )

    def _nodes(self):
        all_nodes = self._all_nodes()
        now = time.time()
        rows = []
        for node in sorted(
            all_nodes, key=lambda n: (n.type, n.rank_index, n.id)
        ):
            rows.append(
                {
                    "id": node.id,
                    "type": node.type,
                    "rank": node.rank_index,
                    "node_group": node.node_group,
                    "status": node.status,
                    "relaunch_count": node.relaunch_count,
                    "exit_reason": node.exit_reason,
                    "exit_history": list(node.exit_history),
                    "heartbeat_age_s": (
                        round(now - node.heartbeat_time)
                        if node.heartbeat_time > 0
                        else None
                    ),
                    "host": node.host_name,
                }
            )
        return rows

    def _all_nodes(self):
        managers = getattr(self._job_manager, "role_managers", None)
        if managers is None:
            worker = getattr(self._job_manager, "worker_manager", None)
            if worker is None:
                return []
            managers = {"worker": worker}
        all_nodes = []
        for manager in managers.values():
            all_nodes.extend(manager.nodes.values())
        return all_nodes

    def _node_detail(self, key: str):
        """Everything the master knows about one node ("type-id" key or
        bare id) — the drill-down an SRE reads during an incident
        (reference dashboard node_detail.html)."""
        for node in self._all_nodes():
            # Unambiguous keys only: a bare numeric id collides across
            # roles in multi-role jobs (actor-3 vs rollout-3).
            if key in (f"{node.type}-{node.id}", node.name):
                now = time.time()
                return {
                    "id": node.id,
                    "name": node.name,
                    "type": node.type,
                    "rank": node.rank_index,
                    "node_group": node.node_group,
                    "status": node.status,
                    "reported_status": node.reported_status,
                    "host": node.host_name,
                    "host_ip": node.host_ip,
                    "critical": node.critical,
                    "relaunch_count": node.relaunch_count,
                    "max_relaunch_count": node.max_relaunch_count,
                    "relaunchable": node.relaunchable,
                    "exit_reason": node.exit_reason,
                    "exit_history": list(node.exit_history),
                    "unrecoverable": node.is_unrecoverable_failure(),
                    "heartbeat_age_s": (
                        round(now - node.heartbeat_time)
                        if node.heartbeat_time > 0
                        else None
                    ),
                    "create_time": node.create_time,
                    "start_time": node.start_time,
                    "finish_time": node.finish_time,
                    "timeline": [
                        {"ts": ts, "status": status}
                        for ts, status in getattr(
                            node, "status_history", []
                        )
                    ],
                    "resource": {
                        "cpu": node.config_resource.cpu,
                        "memory_mb": node.config_resource.memory_mb,
                        "tpu_chips": node.config_resource.tpu_chips,
                        "used_cpu": node.used_resource.cpu,
                        "used_memory_mb": node.used_resource.memory_mb,
                    },
                }
        return None

    def _rdzv(self):
        rows = []
        for name, mgr in self._rdzv_managers.items():
            rows.append(
                {
                    "name": name,
                    "round": getattr(mgr, "_rdzv_round", 0),
                    "waiting": mgr.num_nodes_waiting(),
                    "world_size": len(getattr(mgr, "_latest_world", {})),
                }
            )
        return rows

    def _datasets(self):
        if self._task_manager is None:
            return []
        rows = []
        with self._task_manager._lock:  # noqa: SLF001 - read-only view
            datasets = dict(self._task_manager._datasets)  # noqa: SLF001
        for name, mgr in datasets.items():
            rows.append(
                {
                    "name": name,
                    "todo": len(mgr.todo),
                    "doing": len(mgr.doing),
                    "completed": getattr(mgr, "_completed_count", 0),
                    "records_done": (
                        mgr.completed_records()
                        if hasattr(mgr, "completed_records")
                        else 0
                    ),
                }
            )
        return rows

    def start(self):
        # Bind lazily and degrade gracefully: a taken port must not take
        # down the master for a monitoring-only feature.
        try:
            self._server = ThreadingHTTPServer(
                ("0.0.0.0", self._requested_port), self._make_handler()
            )
        except OSError as e:
            logger.error(
                "dashboard disabled: cannot bind port %d (%s)",
                self._requested_port,
                e,
            )
            return
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="dashboard",
            daemon=True,
        )
        self._thread.start()
        logger.info("dashboard on port %d", self.port)

    def stop(self):
        if self._server is None:
            return
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
