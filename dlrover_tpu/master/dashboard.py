"""Master web dashboard: job/node state over HTTP.

Parity: reference dlrover/dashboard (tornado app wired at
master/main.py:100-107) — rebuilt on the stdlib HTTP server: JSON APIs
(/api/job, /api/perf) plus a single self-contained HTML page rendering
the node table and training progress.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dlrover_tpu.common.log import logger

_PAGE = """<!DOCTYPE html>
<html><head><title>dlrover-tpu</title>
<style>
body{font-family:monospace;margin:2em;background:#fafafa}
table{border-collapse:collapse}td,th{border:1px solid #999;padding:4px 10px}
h1{font-size:1.3em}.Running{color:green}.Failed,.Breakdown{color:red}
.Pending,.Initial{color:#b8860b}.Succeeded{color:blue}
</style></head><body>
<h1>dlrover-tpu job <span id="job"></span></h1>
<p>stage: <b id="stage"></b> | step: <b id="step"></b> |
speed: <b id="speed"></b> steps/s | goodput: <b id="goodput"></b>%</p>
<table id="nodes"><tr><th>id</th><th>rank</th><th>status</th>
<th>relaunches</th><th>host</th></tr></table>
<script>
async function refresh(){
 const job = await (await fetch('/api/job')).json();
 const perf = await (await fetch('/api/perf')).json();
 document.getElementById('job').textContent = job.job_name;
 document.getElementById('stage').textContent = job.stage;
 document.getElementById('step').textContent = perf.global_step;
 document.getElementById('speed').textContent = perf.speed.toFixed(2);
 document.getElementById('goodput').textContent = (perf.goodput*100).toFixed(1);
 const t = document.getElementById('nodes');
 while(t.rows.length > 1) t.deleteRow(1);
 for(const [id, n] of Object.entries(job.nodes)){
  const r = t.insertRow();
  r.insertCell().textContent = id;
  r.insertCell().textContent = n.rank;
  const c = r.insertCell(); c.textContent = n.status;
  c.className = n.status;
  r.insertCell().textContent = n.relaunch_count;
  r.insertCell().textContent = n.host || '';
 }
}
refresh(); setInterval(refresh, 3000);
</script></body></html>"""


class DashboardServer:
    def __init__(self, job_manager, perf_monitor, port: int = 0):
        self._job_manager = job_manager
        self._perf_monitor = perf_monitor
        self._requested_port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self.port = 0
        self._thread: Optional[threading.Thread] = None

    def _make_handler(self):
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                if self.path == "/" or self.path.startswith("/index"):
                    self._send(200, _PAGE, "text/html")
                elif self.path == "/api/job":
                    detail = dashboard._job_detail()
                    self._send(200, json.dumps(detail), "application/json")
                elif self.path == "/api/perf":
                    self._send(
                        200,
                        json.dumps(dashboard._perf()),
                        "application/json",
                    )
                else:
                    self._send(404, "not found", "text/plain")

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        return Handler

    def _job_detail(self):
        detail = self._job_manager.get_job_detail()
        return {
            "job_name": detail.job_name,
            "stage": detail.stage,
            "nodes": detail.nodes,
        }

    def _perf(self):
        return {
            "global_step": self._perf_monitor.global_step,
            "speed": self._perf_monitor.running_speed(),
            "goodput": self._perf_monitor.goodput(),
        }

    def start(self):
        # Bind lazily and degrade gracefully: a taken port must not take
        # down the master for a monitoring-only feature.
        try:
            self._server = ThreadingHTTPServer(
                ("0.0.0.0", self._requested_port), self._make_handler()
            )
        except OSError as e:
            logger.error(
                "dashboard disabled: cannot bind port %d (%s)",
                self._requested_port,
                e,
            )
            return
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="dashboard",
            daemon=True,
        )
        self._thread.start()
        logger.info("dashboard on port %d", self.port)

    def stop(self):
        if self._server is None:
            return
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
