"""Per-role training node managers.

Parity: reference dlrover/python/master/node/training_node.py:181
(TrainingNodeManager) and worker.py:42-108 (WorkerManager). Each manager
owns the node records of one role group, produces relaunch/scale plans,
and answers liveness queries for the job manager.
"""

import copy
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node, NodeGroupResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan


class TrainingNodeManager:
    def __init__(
        self,
        node_type: str,
        group_resource: NodeGroupResource,
        new_node_id_fn,
        max_relaunch_count: int = 3,
        node_group_size: int = 0,
    ):
        self._node_type = node_type
        self._group_resource = group_resource
        self._new_node_id_fn = new_node_id_fn
        self._max_relaunch_count = max_relaunch_count
        # Hosts per TPU slice block; >1 assigns node.node_group at init.
        self._node_group_size = node_group_size
        self._lock = threading.Lock()
        self._nodes: Dict[int, Node] = {}

    @property
    def nodes(self) -> Dict[int, Node]:
        with self._lock:
            return dict(self._nodes)

    @property
    def group_resource(self) -> NodeGroupResource:
        return self._group_resource

    def init_nodes(self) -> List[Node]:
        """Build the initial node records for the configured group size."""
        with self._lock:
            for rank in range(self._group_resource.count):
                node_id = self._new_node_id_fn()
                node = Node(
                    self._node_type,
                    node_id,
                    rank_index=rank,
                    config_resource=copy.copy(
                        self._group_resource.node_resource
                    ),
                    max_relaunch_count=self._max_relaunch_count,
                )
                if self._node_group_size > 1:
                    node.node_group = rank // self._node_group_size
                self._nodes[node_id] = node
            return list(self._nodes.values())

    def update_node(self, node: Node):
        with self._lock:
            self._nodes[node.id] = node

    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def remove_node(self, node_id: int):
        with self._lock:
            self._nodes.pop(node_id, None)

    def relaunch_node(self, node: Node) -> Tuple[Optional[Node], ScalePlan]:
        """Decide the replacement record + plan for a dead node."""
        plan = ScalePlan()
        reason = node.is_unrecoverable_failure()
        if reason:
            logger.warning(
                "node %s not relaunched: %s", node.name, reason
            )
            return None, plan
        with self._lock:
            new_id = self._new_node_id_fn()
            new_node = node.get_relaunch_node(new_id)
            # Replacement pods take the group's CURRENT resource template,
            # not the dead pod's copy: the optimizer may have bumped
            # memory after an OOM, and the relaunch must pick that up.
            new_node.config_resource = copy.copy(
                self._group_resource.node_resource
            )
            self._nodes[new_id] = new_node
        plan.launch_nodes.append(new_node)
        if not node.is_released:
            plan.remove_nodes.append(node)
        return new_node, plan

    # ---- liveness queries --------------------------------------------------

    def alive_nodes(self) -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.status in (NodeStatus.PENDING, NodeStatus.RUNNING)
            ]

    def running_nodes(self) -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.status == NodeStatus.RUNNING
            ]

    def pending_nodes(self) -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.status in (NodeStatus.INITIAL, NodeStatus.PENDING)
            ]

    def all_nodes_exited(self) -> bool:
        with self._lock:
            if not self._nodes:
                return False
            latest = self._latest_incarnations()
            return all(n.is_end() for n in latest)

    def all_nodes_succeeded(self) -> bool:
        with self._lock:
            if not self._nodes:
                return False
            latest = self._latest_incarnations()
            return all(n.status == NodeStatus.SUCCEEDED for n in latest)

    def _latest_incarnations(self) -> List[Node]:
        """One record per rank: the newest relaunch incarnation."""
        by_rank: Dict[int, Node] = {}
        for node in self._nodes.values():
            cur = by_rank.get(node.rank_index)
            if cur is None or node.id > cur.id:
                by_rank[node.rank_index] = node
        return list(by_rank.values())

    def latest_nodes(self) -> List[Node]:
        with self._lock:
            return self._latest_incarnations()

    def first_pending_since(self) -> float:
        """Earliest create_time among still-pending nodes (0 if none)."""
        pending = self.pending_nodes()
        times = [n.create_time for n in pending if n.create_time]
        return min(times) if times else 0.0


class WorkerManager(TrainingNodeManager):
    """Worker-role manager with elastic count adjustment.

    Parity: reference master/node/worker.py:42 (WorkerManager) —
    adds scale-out/in of the worker group used by the auto-scaler.
    """

    def __init__(
        self,
        group_resource: NodeGroupResource,
        new_node_id_fn,
        max_relaunch_count: int = 3,
        node_group_size: int = 0,
    ):
        super().__init__(
            NodeType.WORKER,
            group_resource,
            new_node_id_fn,
            max_relaunch_count,
            node_group_size,
        )

    def adjust_worker(self, target_count: int) -> ScalePlan:
        """Scale the worker group to target_count (reference
        worker.py WorkerManager.adjust_worker)."""
        plan = ScalePlan()
        # Every non-finished record occupies a rank: INITIAL covers the
        # window between a relaunch decision and the watcher seeing the
        # new pod — scaling in that window must not double-assign ranks.
        alive = [n for n in self.nodes.values() if not n.is_end()]
        delta = target_count - len(alive)
        if delta == 0:
            return plan
        self._group_resource.count = target_count
        if delta > 0:
            used_ranks = {n.rank_index for n in alive}
            rank = 0
            with self._lock:
                for _ in range(delta):
                    while rank in used_ranks:
                        rank += 1
                    used_ranks.add(rank)
                    node_id = self._new_node_id_fn()
                    node = Node(
                        self._node_type,
                        node_id,
                        rank_index=rank,
                        config_resource=copy.copy(
                            self._group_resource.node_resource
                        ),
                        max_relaunch_count=self._max_relaunch_count,
                    )
                    self._nodes[node_id] = node
                    plan.launch_nodes.append(node)
        else:
            # Remove the highest ranks first so the surviving world is a
            # contiguous [0, target) — required for legal mesh reshaping.
            for node in sorted(alive, key=lambda n: -n.rank_index)[:-delta]:
                node.relaunchable = False
                plan.remove_nodes.append(node)
        return plan

    def has_exited_worker(self) -> bool:
        return any(
            n.status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN)
            for n in self.nodes.values()
        )

    def wait_worker_restart_window(self, node: Node, window_s: float) -> bool:
        """True if a failed node is still inside its restart window."""
        if node.finish_time is None:
            return False
        return (time.time() - node.finish_time) < window_s


class ChiefManager(TrainingNodeManager):
    """Chief-role manager (reference master/node/training_node.py chief
    handling): the coordinating host. Chief nodes are CRITICAL — they
    gate job success alongside workers, and the relaunch path treats
    their loss with the same urgency as a worker world re-formation
    (in JAX SPMD the rendezvous re-forms the world either way; the
    chief's criticality mainly drives reporting and success gating)."""

    def __init__(
        self,
        group_resource: NodeGroupResource,
        new_node_id_fn,
        max_relaunch_count: int = 3,
    ):
        super().__init__(
            NodeType.CHIEF,
            group_resource,
            new_node_id_fn,
            max_relaunch_count,
        )

    def init_nodes(self) -> List[Node]:
        nodes = super().init_nodes()
        for node in nodes:
            node.critical = True
        return nodes


class EvaluatorManager(TrainingNodeManager):
    """Evaluator-role manager (reference master/node/evaluator.py): a
    side group running evaluations off checkpoints. Evaluators relaunch
    like workers but do NOT gate job success — a finished training job
    with a still-running evaluator succeeds and the evaluator is torn
    down with the job."""

    def __init__(
        self,
        group_resource: NodeGroupResource,
        new_node_id_fn,
        max_relaunch_count: int = 3,
    ):
        super().__init__(
            NodeType.EVALUATOR,
            group_resource,
            new_node_id_fn,
            max_relaunch_count,
        )


def create_role_manager(
    node_type: str,
    group_resource: NodeGroupResource,
    new_node_id_fn,
    max_relaunch_count: int = 3,
    node_group_size: int = 0,
):
    if node_type == NodeType.WORKER:
        return WorkerManager(
            group_resource,
            new_node_id_fn,
            max_relaunch_count,
            node_group_size=node_group_size,
        )
    if node_type == NodeType.CHIEF:
        return ChiefManager(
            group_resource, new_node_id_fn, max_relaunch_count
        )
    if node_type == NodeType.EVALUATOR:
        return EvaluatorManager(
            group_resource, new_node_id_fn, max_relaunch_count
        )
    return TrainingNodeManager(
        node_type, group_resource, new_node_id_fn, max_relaunch_count
    )
