"""Job manager for local/standalone mode (one host, agent-managed restarts).

Parity: reference dlrover/python/master/node/local_job_manager.py:25.
The master only bookkeeps node state and emits diagnosis actions; actual
process restarts happen in the agent.
"""

import time
from typing import List, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import (
    JobStage,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.diagnosis.actions import DiagnosisAction
from dlrover_tpu.master.node.job_context import get_job_context


class LocalJobManager:
    def __init__(self, job_name: str = "local", max_relaunch_count: int = 3):
        self._job_name = job_name
        self._job_context = get_job_context()
        self._max_relaunch_count = max_relaunch_count

    def start(self):
        self._job_context.set_job_stage(JobStage.RUNNING)

    def stop(self):
        self._job_context.set_job_stage(JobStage.STOPPING)

    # ---- servicer surface --------------------------------------------------

    def handle_node_joined(self, node_id: int, node_rank: int):
        node = self._job_context.get_node(NodeType.WORKER, node_id)
        if node is None:
            node = Node(
                NodeType.WORKER,
                node_id,
                rank_index=node_rank,
                max_relaunch_count=self._max_relaunch_count,
            )
        elif node.is_end():
            # A re-join after failure is a new incarnation of the node;
            # keep relaunch bookkeeping but restart the status flow.
            node.status = NodeStatus.INITIAL
        node.update_status(NodeStatus.RUNNING)
        node.heartbeat_time = time.time()
        self._job_context.update_node(node)

    def collect_node_heartbeat(
        self, node_id: int, timestamp: float
    ) -> List[DiagnosisAction]:
        node = self._job_context.get_node(NodeType.WORKER, node_id)
        if node is None:
            node = Node(NodeType.WORKER, node_id)
            self._job_context.update_node(node)
        node.heartbeat_time = timestamp
        return self._job_context.drain_node_actions(node_id)

    def handle_node_failure(self, report: comm.NodeFailureReport):
        self._job_context.inc_failure_count()
        node = self._job_context.get_node(NodeType.WORKER, report.node_id)
        if node is None:
            return
        node.relaunch_count = max(node.relaunch_count, report.restart_count)
        if report.level == TrainingExceptionLevel.NODE_ERROR:
            node.update_status(NodeStatus.FAILED)
        self._job_context.update_node(node)

    def handle_node_succeeded(self, node_id: int):
        node = self._job_context.get_node(NodeType.WORKER, node_id)
        if node is not None:
            node.update_status(NodeStatus.SUCCEEDED)
            self._job_context.update_node(node)

    def handle_reported_node_event(self, report: comm.NodeEventReport):
        logger.info(
            "node %d event %s: %s %s",
            report.node_id,
            report.event_type,
            report.reason,
            report.message,
        )
        if report.event_type == NodeEventType.NODE_CHECK_FAILED:
            # Same semantics as the distributed manager: a node that
            # failed its health probes is broken hardware, evicted from
            # scheduling until relaunched.
            node = self._job_context.get_node(NodeType.WORKER, report.node_id)
            if node is not None:
                node.exit_reason = NodeExitReason.HARDWARE_ERROR
                node.update_status(NodeStatus.BREAKDOWN)
                self._job_context.update_node(node)

    def update_node_resource_usage(self, stats: comm.ResourceStats):
        node = self._job_context.get_node(NodeType.WORKER, stats.node_id)
        if node is not None:
            node.update_from_resource_stats(
                stats.cpu_percent, stats.memory_mb
            )

    def update_ckpt_step(self, node_id: int, step: int, committed: bool):
        self._job_context.update_ckpt_step(node_id, step, committed)

    def get_committed_ckpt_step(self) -> int:
        return self._job_context.committed_ckpt_step()

    def get_parallel_config(self) -> Optional[comm.ParallelConfig]:
        return None

    def get_job_detail(self) -> comm.JobDetailResponse:
        nodes = {}
        for node_id, node in self._job_context.get_nodes().items():
            nodes[node_id] = {
                "type": node.type,
                "rank": node.rank_index,
                "status": node.status,
                "relaunch_count": node.relaunch_count,
            }
        return comm.JobDetailResponse(
            job_name=self._job_name,
            stage=self._job_context.job_stage,
            nodes=nodes,
        )

    def restart_worker_processes(self, reason: str):
        """Queue an in-place restart for every still-running node."""
        from dlrover_tpu.diagnosis.actions import NodeAction

        for node in self._job_context.get_nodes().values():
            if node.status == NodeStatus.RUNNING:
                self._job_context.enqueue_action(
                    NodeAction(
                        instance=node.id, node_id=node.id, reason=reason
                    )
                )

    # ---- queries used by the master run loop --------------------------------

    def all_workers_exited(self) -> bool:
        nodes = self._job_context.get_nodes()
        return bool(nodes) and all(n.is_end() for n in nodes.values())

    def all_workers_succeeded(self) -> bool:
        nodes = self._job_context.get_nodes()
        return bool(nodes) and all(
            n.status == NodeStatus.SUCCEEDED for n in nodes.values()
        )
