"""Exit-reason taxonomy: classify worker exits and budget relaunches
per reason.

Parity: reference dlrover/python/master/node/dist_job_manager.py:996
(_should_relaunch) + common/node.py exit-reason handling — the reference
differentiates OOMKilled / Fatal / preemption when deciding whether a
relaunch is worth a new pod. Here the classification runs from the
agent's failure report (exit code + reason hint mined from worker logs)
as well as from the k8s watcher's container status, and each reason
carries its own relaunch budget:

- PREEMPTED: infra-inflicted, effectively always relaunch (10x budget);
- KILLED (external kill / heartbeat-lost host): 2x budget — likely
  infra, but a kill loop must still terminate;
- OOM / HARDWARE / SOFTWARE / UNKNOWN: 1x budget (OOM additionally
  triggers the resource optimizer's host-memory bump and the
  hyperparam strategy's remat escalation);
- FATAL: zero — a poisoned program must fail fast.
"""

import re
from typing import Optional

from dlrover_tpu.common.constants import (
    HARDWARE_LOG_MARKERS,
    OOM_LOG_MARKERS,
    RELAUNCH_BUDGET_FACTOR,
    ExitCode,
    NodeExitReason,
)

_OOM_RE = re.compile("|".join(OOM_LOG_MARKERS), re.IGNORECASE)
_HARDWARE_RE = re.compile("|".join(HARDWARE_LOG_MARKERS), re.IGNORECASE)
_REASON_HINT_RE = re.compile(r"reason=([A-Za-z]+)")

_HINTABLE = {
    NodeExitReason.OOM,
    NodeExitReason.HARDWARE_ERROR,
    NodeExitReason.SOFTWARE_ERROR,
    NodeExitReason.PREEMPTED,
    NodeExitReason.KILLED,
    NodeExitReason.FATAL_ERROR,
}


def classify_exit(exit_code: int, message: str = "") -> Optional[str]:
    """Map a worker exit (code + evidence string) to a NodeExitReason.

    ``message`` is the agent's error_data — it may carry an explicit
    ``reason=X`` hint (agent-side log diagnosis) which wins over the
    code, since e.g. an HBM OOM and a segfault can share exit code 1.
    Returns None for a clean exit.
    """
    if exit_code == ExitCode.SUCCESS and not message:
        return None
    hint = _REASON_HINT_RE.search(message or "")
    if hint and hint.group(1) in _HINTABLE:
        return hint.group(1)
    if message:
        if _OOM_RE.search(message):
            return NodeExitReason.OOM
        if _HARDWARE_RE.search(message):
            return NodeExitReason.HARDWARE_ERROR
    if exit_code == ExitCode.KILLED:
        return NodeExitReason.KILLED
    if exit_code == ExitCode.TERMED:
        return NodeExitReason.PREEMPTED
    if exit_code in (ExitCode.HARDWARE_ERROR, ExitCode.GPU_DRIVER_ERROR,
                     ExitCode.NODE_CHECK_FAILED):
        return NodeExitReason.HARDWARE_ERROR
    if exit_code != ExitCode.SUCCESS:
        return NodeExitReason.SOFTWARE_ERROR
    return NodeExitReason.UNKNOWN


def relaunch_budget(reason: str, max_relaunch_count: int) -> int:
    factor = RELAUNCH_BUDGET_FACTOR.get(
        reason or NodeExitReason.UNKNOWN, 1.0
    )
    return int(max_relaunch_count * factor)
