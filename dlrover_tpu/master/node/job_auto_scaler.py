"""Job auto-scaler: periodically apply optimizer resource plans.

Parity: reference dlrover/python/master/node/job_auto_scaler.py:71-375
(AllreduceTrainingAutoScaler) — a loop that asks the resource optimizer
for a plan and converges the worker group to it through the scaler. For
TPU SPMD jobs, changing the worker count triggers a rendezvous round
(the agents detect waiting-node changes and re-mesh), so the scaler only
has to adjust the group; elasticity is handled by the normal
membership-change path.
"""

import threading

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import logger
from dlrover_tpu.master.resource.optimizer import (
    ResourceOptimizer,
    ResourcePlan,
)


class AllreduceTrainingAutoScaler:
    def __init__(
        self,
        job_manager,
        scaler,
        optimizer: ResourceOptimizer,
        interval_s: float = 60.0,
        rdzv_managers=None,
    ):
        self._job_manager = job_manager
        self._scaler = scaler
        self._optimizer = optimizer
        self._interval_s = interval_s
        self._rdzv_managers = rdzv_managers or {}
        self._stopped = threading.Event()
        self._thread = None

    def start(self):
        self._stopped.clear()
        self._thread = threading.Thread(
            target=self._loop, name="job-auto-scaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def scale_once(self):
        if hasattr(self._optimizer, "record_speed"):
            self._optimizer.record_speed()
        plan = self._optimizer.generate_plan()
        if plan.empty():
            return
        self.execute_plan(plan)

    def execute_plan(self, plan: ResourcePlan):
        group = plan.node_group_resources.get(NodeType.WORKER)
        if group is None:
            return
        worker_manager = self._job_manager.worker_manager
        current = len(worker_manager.alive_nodes())
        logger.info(
            "auto-scaler plan: workers %d -> %d (%s)",
            current,
            group.count,
            plan.comment,
        )
        from dlrover_tpu.training_event import MasterEvents

        MasterEvents.scale_plan(plan.comment, group.count)
        # Adopt the (possibly resource-bumped) template so relaunches and
        # new nodes use it even when the count is unchanged. Count-only
        # plans carry an empty template and must not wipe the live one.
        if not group.node_resource.is_empty():
            worker_manager.group_resource.node_resource = (
                group.node_resource
            )
        scale_plan = worker_manager.adjust_worker(group.count)
        if not scale_plan.empty():
            self._scaler.scale(scale_plan)
        # A new target count must also move the rendezvous window, or the
        # next round keeps completing at the old world size and freshly
        # launched workers wait forever (reference job_auto_scaler
        # updates rdzv params alongside the plan).
        for mgr in self._rdzv_managers.values():
            mgr.update_rdzv_params(
                min_nodes=group.count, max_nodes=group.count
            )

    def _loop(self):
        while not self._stopped.wait(self._interval_s):
            try:
                self.scale_once()
            except Exception:
                logger.exception("auto-scale round failed")
