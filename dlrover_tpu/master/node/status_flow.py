"""Legal node status transitions.

Parity: reference dlrover/python/master/node/status_flow.py (NodeStateFlow).
Expressed as an allowed-edge set instead of a flow table; semantics match:
once a node reaches an end state it can only be DELETED/RELEASED.
"""

from dlrover_tpu.common.constants import NodeStatus

_ALLOWED = {
    (NodeStatus.INITIAL, NodeStatus.PENDING),
    (NodeStatus.INITIAL, NodeStatus.RUNNING),
    (NodeStatus.INITIAL, NodeStatus.FAILED),
    (NodeStatus.INITIAL, NodeStatus.DELETED),
    (NodeStatus.PENDING, NodeStatus.RUNNING),
    (NodeStatus.PENDING, NodeStatus.SUCCEEDED),
    (NodeStatus.PENDING, NodeStatus.FAILED),
    (NodeStatus.PENDING, NodeStatus.DELETED),
    (NodeStatus.PENDING, NodeStatus.BREAKDOWN),
    (NodeStatus.RUNNING, NodeStatus.SUCCEEDED),
    (NodeStatus.RUNNING, NodeStatus.FAILED),
    (NodeStatus.RUNNING, NodeStatus.DELETED),
    (NodeStatus.RUNNING, NodeStatus.BREAKDOWN),
    (NodeStatus.SUCCEEDED, NodeStatus.DELETED),
    (NodeStatus.FAILED, NodeStatus.DELETED),
    (NodeStatus.BREAKDOWN, NodeStatus.DELETED),
    (NodeStatus.UNKNOWN, NodeStatus.RUNNING),
    (NodeStatus.UNKNOWN, NodeStatus.FAILED),
    (NodeStatus.UNKNOWN, NodeStatus.DELETED),
}


class NodeStateFlow:
    @staticmethod
    def transition_allowed(from_status: str, to_status: str) -> bool:
        if from_status == to_status:
            return True
        if from_status == NodeStatus.UNKNOWN or to_status == NodeStatus.UNKNOWN:
            # Unknown observations never regress a definite state.
            return to_status != NodeStatus.UNKNOWN
        return (from_status, to_status) in _ALLOWED
