"""In-memory job state shared by master components.

Parity: reference dlrover/python/master/node/job_context.py:44 (JobContext
singleton: nodes, job stage, pending diagnosis action queue).
"""

import threading
from collections import deque
from typing import Deque, Dict, Optional

from dlrover_tpu.common.constants import JobStage, NodeType
from dlrover_tpu.common.node import Node
from dlrover_tpu.diagnosis.actions import DiagnosisAction


class JobContext:
    _instance: Optional["JobContext"] = None
    _singleton_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[str, Dict[int, Node]] = {}
        self._job_stage = JobStage.INIT
        self._actions: Deque[DiagnosisAction] = deque()
        self._node_actions: Dict[int, Deque[DiagnosisAction]] = {}
        self._committed_ckpt_step = -1
        self._node_ckpt_steps: Dict[int, int] = {}
        self._failure_count = 0
        self._restart_count = 0

    @classmethod
    def singleton_instance(cls) -> "JobContext":
        if cls._instance is None:
            with cls._singleton_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @classmethod
    def reset_singleton(cls):
        with cls._singleton_lock:
            cls._instance = None

    # ---- nodes -------------------------------------------------------------

    def update_node(self, node: Node):
        with self._lock:
            self._nodes.setdefault(node.type, {})[node.id] = node

    def get_node(self, node_type: str, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_type, {}).get(node_id)

    def find_node_by_id(self, node_id: int) -> Optional[Node]:
        with self._lock:
            for nodes in self._nodes.values():
                if node_id in nodes:
                    return nodes[node_id]
            return None

    def get_nodes(self, node_type: str = NodeType.WORKER) -> Dict[int, Node]:
        with self._lock:
            return dict(self._nodes.get(node_type, {}))

    def remove_node(self, node_type: str, node_id: int):
        with self._lock:
            self._nodes.get(node_type, {}).pop(node_id, None)

    # ---- job stage ---------------------------------------------------------

    @property
    def job_stage(self) -> str:
        with self._lock:
            return self._job_stage

    def set_job_stage(self, stage: str):
        with self._lock:
            changed = stage != self._job_stage
            self._job_stage = stage
        if changed:
            from dlrover_tpu.training_event import MasterEvents

            MasterEvents.job_stage(stage)

    # ---- diagnosis actions -------------------------------------------------

    def enqueue_action(self, action: DiagnosisAction):
        with self._lock:
            if action.instance >= 0:
                self._node_actions.setdefault(
                    action.instance, deque()
                ).append(action)
            else:
                self._actions.append(action)

    def next_master_action(self) -> Optional[DiagnosisAction]:
        with self._lock:
            while self._actions:
                action = self._actions.popleft()
                if not action.is_expired():
                    return action
            return None

    def drain_node_actions(self, node_id: int):
        with self._lock:
            q = self._node_actions.get(node_id)
            if not q:
                return []
            actions = [a for a in q if not a.is_expired()]
            q.clear()
            return actions

    # ---- checkpoint bookkeeping -------------------------------------------

    def update_ckpt_step(self, node_id: int, step: int, committed: bool):
        with self._lock:
            self._node_ckpt_steps[node_id] = step
            if committed:
                self._committed_ckpt_step = max(
                    self._committed_ckpt_step, step
                )

    def committed_ckpt_step(self) -> int:
        with self._lock:
            return self._committed_ckpt_step

    # ---- counters ----------------------------------------------------------

    def inc_failure_count(self):
        with self._lock:
            self._failure_count += 1

    @property
    def failure_count(self):
        with self._lock:
            return self._failure_count


def get_job_context() -> JobContext:
    return JobContext.singleton_instance()
