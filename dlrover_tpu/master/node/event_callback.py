"""Node event callbacks: hooks run on node lifecycle transitions.

Parity: reference dlrover/python/master/node/event_callback.py:43-340
(NodeEventCallback base, AllReduceNodeHandlingCallback,
TaskRescheduleCallback). Callbacks let orthogonal subsystems (rendezvous
membership, data-shard recovery, perf bookkeeping) react to node events
without coupling them into the job manager.
"""

import abc

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.node import Node


class NodeEventCallback(abc.ABC):
    """Hooks fired by the job manager as nodes change state."""

    def on_node_started(self, node: Node):
        pass

    def on_node_succeeded(self, node: Node):
        pass

    def on_node_failed(self, node: Node):
        pass

    def on_node_deleted(self, node: Node):
        pass


class AllReduceNodeHandlingCallback(NodeEventCallback):
    """SPMD (allreduce/psum) strategy: keep rendezvous membership in sync
    and trip the failover counter (reference event_callback.py:252)."""

    def __init__(self, master):
        self._master = master

    def on_node_started(self, node: Node):
        if node.type == NodeType.WORKER:
            for mgr in self._master.rdzv_managers.values():
                mgr.add_alive_node(node.rank_index)

    def on_node_succeeded(self, node: Node):
        self._remove_from_rdzv(node)

    def on_node_failed(self, node: Node):
        self._remove_from_rdzv(node)
        self._master.perf_monitor.reset()

    def on_node_deleted(self, node: Node):
        self._remove_from_rdzv(node)

    def _remove_from_rdzv(self, node: Node):
        if node.type != NodeType.WORKER:
            return
        for mgr in self._master.rdzv_managers.values():
            mgr.remove_alive_node(node.rank_index)


class TaskRescheduleCallback(NodeEventCallback):
    """Dynamic-data-sharding: recover unfinished shards of a dead worker
    (reference event_callback.py TaskRescheduleCallback)."""

    def __init__(self, task_manager):
        self._task_manager = task_manager

    def on_node_failed(self, node: Node):
        if node.type == NodeType.WORKER:
            self._task_manager.recover_node_tasks(node.id)

    def on_node_deleted(self, node: Node):
        self.on_node_failed(node)
